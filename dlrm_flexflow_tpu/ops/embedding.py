"""Embedding operators.

Parity with the reference Embedding op (reference: src/ops/embedding.cu, 364
LoC — custom CUDA gather forward / atomicAdd scatter backward,
embedding.cu:173-224; aggregation modes SUM/AVG; partitioned only over the
sample dim, embedding.cu:115-117) and the AVX2 CPU embedding-bag path
(src/ops/embedding_avx2.cc, 296 LoC). In the reference's DLRM strategies each
table is pinned whole to one device = table parallelism
(dlrm_strategy.cc:252-256); the hetero strategy puts tables on CPUs
(dlrm_strategy_hetero.cc:28-36).

TPU-native redesign:
- forward lookup is `jnp.take` (XLA gather, MXU-free, HBM-bandwidth bound);
  backward is XLA scatter-add from jax.grad — no atomics needed. A Pallas
  double-buffered gather kernel lives in ops/pallas/embedding_kernel.py.
- table ("parameter") parallelism: the table's row or width dim is sharded
  over mesh axes. Width (out_dim) sharding keeps the lookup local and
  concat-compatible. Row sharding (for huge tables) does the lookup under a
  one-hot-free masked gather + psum.
- the stacked EmbeddingBagStacked op (models/dlrm.py uses it) fuses N
  same-shape tables into one (N, rows, dim) parameter sharded on dim 0 —
  the GSPMD expression of "each table whole on one device" with the
  all-to-all the reference got from Legion DMA.
- hetero strategies: `device_type == CPU` host-offloads the COMPUTE
  (compute_on); ZCM memory_types / FFConfig.host_resident_tables store the
  table itself in host RAM with numpy gather + touched-rows scatter around
  the jitted step (host_init/host_lookup/host_sgd_update below) — the
  embedding_avx2.cc capability that lets tables larger than HBM train.
- the sparse-SGD update keeps the forward-gathered tiles as residuals
  (apply_with_fwd) so the scatter WRITES new rows without re-reading them
  (ops/pallas scatter_write_rows_packed) — random HBM rows are the
  latency floor on TPU.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..core.initializers import GlorotUniform
from ..core.op import Op, ParamDef
from ..parallel.pconfig import DEVICE_CPU, ParallelConfig
from ..utils.logging import get_logger

log_emb = get_logger("embedding")

AGGR_MODE_NONE = "none"
AGGR_MODE_SUM = "sum"
AGGR_MODE_AVG = "avg"


def _zcm_candidate(ndims: int) -> ParallelConfig:
    """Host-resident (ZCM) candidate for the strategy search: the table
    stored in CPU RAM, looked up and scatter-updated there (reference
    hetero strategies, dlrm_strategy_hetero.cc:28-49). Offering it as a
    search candidate lets optimize() discover Terabyte-style placements
    (huge tables to host, the rest row-sharded in HBM) instead of only
    executing hand-written hetero .pb files."""
    return ParallelConfig((1,) * ndims, device_type=DEVICE_CPU,
                          memory_types=("ZCM",))


def _pack_factor(dim: int, rows: int) -> int:
    """Rows per 128-lane tile for the packed storage of narrow tables
    (1 when the width is already lane-aligned or doesn't divide 128)."""
    if dim < 128 and 128 % dim == 0:
        r = 128 // dim
        if rows % r == 0:
            return r
    return 1


def _packed_gather_tiles(tbl, ix, r, d):
    """Gather logical rows `ix` from a packed (rows/r, r*d) table.
    Returns (rows `ix.shape + (d,)`, flat tile rows (n,), flat tiles
    (n, r*d)) — THE packed-layout invariant (tile = ix//r, sub-row =
    ix%r, wrap) in one place; the tiles are the forward residuals the
    write-only sparse update reuses."""
    vrow = (ix // r).reshape(-1)
    tiles = jnp.take(tbl, vrow, axis=0, mode="wrap")    # (n, r*d)
    sub = (ix % r).reshape(-1)
    rows = jnp.take_along_axis(
        tiles.reshape(-1, r, d), sub[:, None, None], axis=1)[:, 0, :]
    return rows.reshape(ix.shape + (d,)), vrow, tiles


def _packed_gather(tbl, ix, r, d):
    """Gather logical rows `ix` from a packed (rows/r, r*d) table."""
    return _packed_gather_tiles(tbl, ix, r, d)[0]


def _lookup_count(op) -> float:
    """Rows randomly touched per step by this op's gather: batch × tables
    × bag."""
    t = op.inputs[0]
    batch = t.shape[0]
    bag = t.shape[-1] if t.num_dims > 1 else 1
    tables = getattr(op, "num_tables", 1)
    return float(batch * tables * bag)


# tables at/below this footprint don't pay the random-row latency: their
# whole row space fits a few HBM pages / the chip's caches, so repeated
# lookups behave like streaming. Measured r5: an MLP with 4x64-row tables
# trains its full step in 0.79 ms while pricing its 4k lookups at the
# random-row rate predicted +75%; Criteo-Kaggle's 14 tiny tables (4..13k
# rows) similarly cost ~nothing next to its 12 multi-M-row tables.
_SMALL_TABLE_BYTES = 2 << 20


def _table_sizes(op):
    sizes = getattr(op, "table_sizes", None)
    if sizes is None:
        sizes = [op.num_entries] * getattr(op, "num_tables", 1)
    return sizes


def _table_itemsize(op, pc=None) -> float:
    """Bytes per STORED table element: the op's effective quantized-
    storage policy when one is set (int8 rows stream 1 B/elem against
    the 2 MB threshold), else the actual param dtype — a bf16 table has
    half the fp32 footprint, and hardcoding 4 B would misclassify it as
    large."""
    from ..quant.policy import effective_policy
    pol = effective_policy(op, pc)
    if not pol.is_default:
        return float(pol.itemsize)
    try:
        pd = op.param_defs().get("kernel")
        return float(jnp.dtype(pd.dtype).itemsize)
    except Exception:
        return 4.0


def _has_large_table(op) -> bool:
    row_bytes = op.out_dim * _table_itemsize(op)
    return any(rows * row_bytes > _SMALL_TABLE_BYTES
               for rows in _table_sizes(op))


def _effective_random_rows(op, per_table_lookups: float) -> float:
    """Sum of effective GATHER random-row counts across the op's tables:
    small-table lookups are free (their row space behaves like a
    streamed working set — mlp_heavy's 4k lookups into 64-row tables
    hide entirely inside the step floor, measured r5) and large-table
    counts cap at the table's row count (a gather cannot touch more
    distinct rows than the table has)."""
    row_bytes = op.out_dim * _table_itemsize(op)
    total = 0.0
    for rows in _table_sizes(op):
        if rows * row_bytes <= _SMALL_TABLE_BYTES:
            continue
        total += min(per_table_lookups, float(rows))
    return total


def _is_host_resident(op, pc=None) -> bool:
    return (op.name in getattr(op.model, "_host_resident_ops", set())
            or (pc is not None and "ZCM" in pc.memory_types))


def _embedding_random_rows(op, backward: bool, raw: bool = False) -> float:
    # forward = one random read per lookup into a LARGE table; the
    # sparse-path backward never re-gathers (the train step threads
    # cotangents via overrides). `raw` skips the small-table/dedup
    # gating — the HOST (ZCM) pricing path uses it: the 2 MB streaming
    # heuristic was measured for on-device HBM, not host DRAM over PCIe
    if backward:
        return 0.0
    if raw or _is_host_resident(op):
        return _lookup_count(op)
    t = op.inputs[0]
    batch = t.shape[0]
    bag = t.shape[-1] if t.num_dims > 1 else 1
    return _effective_random_rows(op, float(batch * bag))


def _embedding_update_rows(op, pc=None) -> float:
    # touched-rows scatter: the RMW fallback reads AND writes each row
    # (2.0 accesses/lookup); the write-only path
    # (scatter_write_rows_packed) skips the read but measured step times
    # show random writes amortize only slightly better than reads —
    # 1.6 effective accesses/lookup fits every calibration point within
    # ~16% (benchmarks/calibrate_sim.py). Dense updates stream the table
    # instead (param_bytes_touched_per_step). Stateful sparse updates
    # (lazy momentum/Adam) add one read + one write per state slab per
    # touched row on top of the weight traffic.
    #
    # The choice is STRUCTURAL (op attributes + the CANDIDATE config,
    # never the live process's backend/mesh): write-only needs
    # lane-packed storage and an unsharded table (row-sharded tables take
    # the shard_map RMW path) — the simulator models the target TPU even
    # when the search runs on a CPU host.
    if not _sparse_update_active(op):
        return 0.0
    write_only = (getattr(op, "_pack", 1) > 1
                  and op.aggr in (AGGR_MODE_SUM, AGGR_MODE_AVG)
                  and (pc is None or pc.num_parts == 1))
    accesses = 1.6 if write_only else 2.0
    opt = getattr(op.model, "optimizer", None)
    if opt is not None:
        accesses += 2.0 * len(opt.sparse_slab_names())
    # the update machinery (lane pack + dedup sort + scatter) processes
    # EVERY raw lookup — unlike the gather, tiny-table lookups are not
    # free here unless ALL the op's tables are tiny (then the whole
    # working set streams: mlp_heavy's update hides in the step floor,
    # while Criteo-Kaggle pays ~per-raw-lookup even though 19 of its 26
    # tables are tiny — measured r5, 77-95 ns/lookup update-side on both
    # kaggle and dlrm_random). HOST (ZCM) tables always count raw: the
    # device-cache gating does not describe host DRAM, and a zero here
    # would silently reroute host_update_time to its dense fallback
    if not _is_host_resident(op, pc) and not _has_large_table(op):
        return 0.0
    return accesses * _lookup_count(op)


def _host_init_table(initializer, shape, seed: int):
    """Numpy re-implementation of the common initializers for HOST-resident
    tables (the reference stores hetero tables in CPU RAM and fills them
    there, embedding_avx2.cc / dlrm_strategy_hetero.cc:28-49; jax init on
    the accelerator would defeat the point of host residency)."""
    import numpy as np

    from ..core import initializers as I
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    if isinstance(initializer, I.ZeroInitializer):
        return np.zeros(shape, np.float32)
    if isinstance(initializer, I.ConstantInitializer):
        return np.full(shape, initializer.value, np.float32)
    if isinstance(initializer, I.UniformInitializer):
        return rng.uniform(initializer.min_val, initializer.max_val,
                           shape).astype(np.float32)
    if isinstance(initializer, I.NormInitializer):
        return rng.normal(initializer.mean, initializer.stddev,
                          shape).astype(np.float32)
    # GlorotUniform over the last two dims (matches initializers.py fans)
    fan_in, fan_out = shape[-2], shape[-1]
    lim = float(np.sqrt(6.0 / (fan_in + fan_out)))
    return rng.uniform(-lim, lim, shape).astype(np.float32)


def _native_emb():
    """The native threaded gather/scatter library, or None (numpy
    fallback). The reference's hetero path is blocked AVX2/FMA C++
    (embedding_avx2.cc); native/ffemb.cc is this build's equivalent."""
    from .. import native
    return native.get_lib()


# gather path chosen by MEASUREMENT per shape (the reference's own trick
# for cuDNN conv algos, conv_2d.cu:217,873): the threaded native gather
# wins on many-core hosts, numpy's fancy-index loop wins on small CPU
# quotas — time both once and keep the faster
_GATHER_CHOICE: Dict[tuple, str] = {}


def _native_gather(lib, table, g, aggr, d):
    import ctypes

    import numpy as np
    batch, T, bag = g.shape
    gf = np.ascontiguousarray(g.reshape(batch * T, bag), np.int64)
    out = np.empty((batch * T, d), np.float32)
    fp = ctypes.POINTER(ctypes.c_float)
    ip = ctypes.POINTER(ctypes.c_int64)
    lib.ffemb_bag_gather(
        table.ctypes.data_as(fp), table.shape[0], d,
        gf.ctypes.data_as(ip), batch * T, bag,
        1 if aggr == AGGR_MODE_AVG else 0, out.ctypes.data_as(fp))
    return out.reshape(batch, T, d)


def _numpy_gather(table, g, aggr, d):
    import numpy as np
    rows = table[g.reshape(-1)].reshape(g.shape + (d,))
    out = rows.mean(axis=2) if aggr == AGGR_MODE_AVG else rows.sum(axis=2)
    return np.ascontiguousarray(out, np.float32)


def _host_bag_lookup(table, g, aggr):
    """table (rows, d) numpy; g (batch, T, bag) global rows -> (batch,T,d)."""
    import time

    import numpy as np
    d = table.shape[-1]
    lib = _native_emb()
    native_ok = (lib is not None and table.dtype == np.float32
                 and table.flags["C_CONTIGUOUS"])
    if not native_ok:
        return _numpy_gather(table, g, aggr, d)
    key = (table.shape, g.shape, aggr)
    choice = _GATHER_CHOICE.get(key)
    if choice is None:
        # warm both paths first (the native side pays one-time pool
        # construction and cold caches; timing it cold would cache the
        # wrong verdict forever), then time each once
        _native_gather(lib, table, g, aggr, d)
        _numpy_gather(table, g, aggr, d)
        t0 = time.perf_counter()
        out_n = _native_gather(lib, table, g, aggr, d)
        t_native = time.perf_counter() - t0
        t0 = time.perf_counter()
        out_p = _numpy_gather(table, g, aggr, d)
        t_numpy = time.perf_counter() - t0
        choice = "native" if t_native <= t_numpy else "numpy"
        _GATHER_CHOICE[key] = choice
        return out_n if choice == "native" else out_p
    if choice == "native":
        return _native_gather(lib, table, g, aggr, d)
    return _numpy_gather(table, g, aggr, d)


def _host_bag_update(table, g, ct, lr, aggr):
    """In-place table[g] -= lr * d(out)/d(rows) · ct (duplicate-safe)."""
    import ctypes

    import numpy as np
    d = table.shape[-1]
    lib = _native_emb()
    if (lib is not None and table.dtype == np.float32
            and table.flags["C_CONTIGUOUS"]):
        batch, T, bag = g.shape
        gf = np.ascontiguousarray(g.reshape(batch * T, bag), np.int64)
        cf = np.ascontiguousarray(ct.reshape(batch * T, d), np.float32)
        fp = ctypes.POINTER(ctypes.c_float)
        ip = ctypes.POINTER(ctypes.c_int64)
        lib.ffemb_bag_scatter(
            table.ctypes.data_as(fp), table.shape[0], d,
            gf.ctypes.data_as(ip), batch * T, bag,
            1 if aggr == AGGR_MODE_AVG else 0,
            cf.ctypes.data_as(fp), float(lr))
        return
    bag = g.shape[-1]
    c = ct / bag if aggr == AGGR_MODE_AVG else ct
    upd = np.broadcast_to(c[..., None, :], g.shape + (d,))
    np.add.at(table, g.reshape(-1), -lr * upd.reshape(-1, d))


def _host_dedup_rows(flat, upd):
    """Numpy duplicate combination for the host stateful update: stateful
    optimizers are nonlinear in the gradient, so duplicate lookups must
    pre-sum into one gradient row (same reason as _dedup_rows)."""
    import numpy as np
    uniq, inv = np.unique(flat, return_inverse=True)
    summed = np.zeros((uniq.shape[0], upd.shape[-1]), np.float32)
    np.add.at(summed, inv, upd)
    return uniq, summed


def _host_stateful_update(table, g, ct, opt, slabs, step, aggr):
    """Lazy stateful touched-rows update on a HOST (numpy) table — the
    host twin of _sparse_opt_update (same semantics as the device tile
    path: state rows update only on touch, decay applies lazily).

    table (rows, d) numpy, mutated in place; g (batch, T, bag) global
    rows; ct (batch, T, d); slabs {name: (rows, d)} mutated in place."""
    d = table.shape[-1]
    import numpy as np
    bag = g.shape[-1]
    c = ct / bag if aggr == AGGR_MODE_AVG else ct
    upd = np.broadcast_to(c[..., None, :],
                          g.shape + (d,)).reshape(-1, d)
    uniq, summed = _host_dedup_rows(g.reshape(-1), upd)
    slab_rows = {k: v[uniq] for k, v in slabs.items()}
    wn, sn = opt.sparse_row_update_np(table[uniq], summed, slab_rows,
                                      step)
    table[uniq] = wn
    for k in slabs:
        slabs[k][uniq] = sn[k]


def _touched_bytes_factor(op) -> float:
    """Bytes-per-touched-element / 4: gather read + update read/write of
    the weights (3 accesses at the table's effective STORED width — an
    int8-policy table streams a quarter of the weight bytes), plus
    read+write per optimizer state slab (always fp32) on the stateful
    sparse path. Returned in fp32-element units so callers keep
    multiplying by ``elements * 4``."""
    opt = getattr(op.model, "optimizer", None)
    nslabs = len(opt.sparse_slab_names()) if opt is not None else 0
    return 3.0 * (_table_itemsize(op) / 4.0) + 2.0 * nslabs


def _sparse_update_active(op) -> bool:
    """Whether a touched-rows-only update will actually run for `op` —
    the state-free plain-SGD path or the stateful lazy momentum/Adam
    path (mirrors FFModel._select_sparse_update_ops; optimizer may be
    unset when the search costs ops pre-compile — assume the common
    plain-SGD case then)."""
    if not getattr(op.model.config, "sparse_embedding_update", True):
        return False
    if not op.supports_sparse_update():
        return False
    if op.name in getattr(op.model, "_host_offload_ops", set()):
        return False   # host-offloaded tables take the dense path
    opt = getattr(op.model, "optimizer", None)
    if opt is None:
        return True
    from ..core.optimizers import AdamOptimizer, SGDOptimizer
    if isinstance(opt, SGDOptimizer):
        return (opt.momentum == 0.0 and opt.weight_decay == 0.0) \
            or hasattr(op, "sparse_opt_update")
    return isinstance(opt, AdamOptimizer) and hasattr(op,
                                                      "sparse_opt_update")


def _dedup_rows(gidx, upd, num_rows: int):
    """Row-granularity duplicate combination: sort + segment-sum, exactly
    the sorted-segment trick of the Pallas scatters but in UNPACKED row
    space (stateful optimizers are nonlinear in the gradient, so duplicate
    lookups MUST be pre-summed into one gradient row — dense semantics).

    gidx (n,) int row ids (duplicates allowed); upd (n, d).
    Returns (target (n,), summed (n, d)): distinct target rows with their
    combined updates; pad slots carry target == num_rows (out of bounds,
    dropped by mode='drop' scatters)."""
    n = gidx.shape[0]
    order = jnp.argsort(gidx)
    si = jnp.take(gidx, order)
    sg = jnp.take(upd, order, axis=0)
    first = jnp.concatenate([jnp.ones((1,), jnp.bool_), si[1:] != si[:-1]])
    seg = jnp.cumsum(first) - 1
    summed = jax.ops.segment_sum(sg, seg, num_segments=n,
                                 indices_are_sorted=True)
    target = jax.ops.segment_max(si, seg, num_segments=n,
                                 indices_are_sorted=True)
    valid = jnp.arange(n) < seg[-1] + 1
    target = jnp.where(valid, target, num_rows).astype(jnp.int32)
    return target, summed


def _stateful_update_rows_xla(logical, gidx, upd, opt, slabs, step):
    """Generic stateful touched-rows update on the LOGICAL (rows, d) view:
    dedup -> gather w/state rows -> optimizer row math -> scatter-set.
    Runs on any backend (the CPU-mesh test oracle and the fallback for
    layouts the Pallas tile path doesn't cover).

    logical (rows, d); slabs {name: (rows, d)} in the same layout.
    Returns (new_logical, new_slabs)."""
    rows = logical.shape[0]
    target, summed = _dedup_rows(gidx, upd, rows)
    safe = jnp.minimum(target, rows - 1)
    w = jnp.take(logical, safe, axis=0).astype(jnp.float32)
    slab_rows = {k: jnp.take(v, safe, axis=0).astype(jnp.float32)
                 for k, v in slabs.items()}
    touched = jnp.ones_like(w, dtype=jnp.bool_)
    wn, sn = opt.sparse_row_update(w, summed.astype(jnp.float32),
                                   slab_rows, touched, step)
    new_logical = logical.at[target].set(wn.astype(logical.dtype),
                                         mode="drop")
    new_slabs = {k: slabs[k].at[target].set(sn[k].astype(slabs[k].dtype),
                                            mode="drop")
                 for k in slabs}
    return new_logical, new_slabs


def _stateful_update_tiles_packed(view, gidx, upd, d, opt, slab_views,
                                  step, fwd_tiles=None, interpret=False):
    """TPU tile path of the stateful touched-rows update, on the lane-
    packed (vrows, 128) view (128 // d logical rows per tile).

    Same structure as the write-only sparse-SGD scatter: dedup at TILE
    granularity, then pure Pallas writes of distinct tiles — but each
    tile's new value comes from the optimizer's row math applied to the
    whole 128-lane tile with a per-lane `touched` mask (a tile holds
    several logical rows; only looked-up rows' lanes may change, or lazy
    momentum/Adam would decay their tile-neighbours). Weight tiles come
    from the forward-gather residuals when available (no re-read); state
    tiles are gathered here (their only read).
    """
    from .pallas.embedding_kernel import (_dedup_tile_updates,
                                          _pack_tile_updates,
                                          scatter_write_tiles)
    tile_rows, tile_upds = _pack_tile_updates(gidx, upd, d, jnp.float32)
    _, tile_ones = _pack_tile_updates(gidx, jnp.ones_like(upd), d,
                                      jnp.float32)
    # one sort/segment pass for both the gradient and the touch counts
    both = jnp.concatenate([tile_upds, tile_ones], axis=1)
    target, summed, rep, _ = _dedup_tile_updates(tile_rows, both)
    g_tiles, counts = summed[:, :128], summed[:, 128:]
    touched = counts > 0
    safe = jnp.minimum(jnp.maximum(target, 0), view.shape[0] - 1)
    if fwd_tiles is not None:
        # any duplicate's forward tile is the same pre-update value; rep
        # holds one original lookup position per segment (pad slots 0,
        # dropped by target < 0 at the write)
        w = jnp.take(fwd_tiles, rep, axis=0).astype(jnp.float32)
    else:
        w = jnp.take(view, safe, axis=0).astype(jnp.float32)
    slab_tiles = {k: jnp.take(v, safe, axis=0).astype(jnp.float32)
                  for k, v in slab_views.items()}
    wn, sn = opt.sparse_row_update(w, g_tiles, slab_tiles, touched, step)
    new_view = scatter_write_tiles(view, target, wn, interpret=interpret)
    new_slabs = {k: scatter_write_tiles(slab_views[k], target, sn[k],
                                        interpret=interpret)
                 for k in slab_views}
    return new_view, new_slabs


def _norm_slabs(slabs):
    """Accept {slab: arr} (legacy — the kernel's slab rows) or
    {slab: {param: arr}} (the model passes every param's slabs; the
    hybrid placement has two params). Returns
    (kernel_slabs, hot_slabs | None, was_nested)."""
    nested = any(isinstance(v, dict) for v in slabs.values())
    if not nested:
        return dict(slabs), None, False
    k = {n: v["kernel"] for n, v in slabs.items()}
    hot = None
    if any("hot_kernel" in v for v in slabs.values()):
        hot = {n: v["hot_kernel"] for n, v in slabs.items()}
    return k, hot, True


def _finish_opt_update(out, nested):
    """Normalize a stateful-update result back to the caller's slab
    form: hybrid results (4-tuple) always nest (two params); legacy
    flat callers get flat kernel slabs back."""
    if len(out) == 4:
        new_k, new_s, new_h, new_hs = out
        return ({"kernel": new_k, "hot_kernel": new_h},
                {k: {"kernel": new_s[k], "hot_kernel": new_hs[k]}
                 for k in new_s})
    new_k, new_s = out
    if nested:
        new_s = {k: {"kernel": v} for k, v in new_s.items()}
    return {"kernel": new_k}, new_s


def _sparse_opt_update(op, tbl, gidx, upd, opt, slabs, step, total_rows,
                       fwd_tiles=None, hot_tbl=None, hot_slabs=None):
    """Shared stateful-update router for the embedding ops: lane-packed
    Pallas tile path on TPU, logical-row XLA path elsewhere.

    tbl: stored kernel (any layout reshapeable to (total_rows, d));
    slabs {name: same-layout state}; gidx (n,) UNPACKED global rows;
    upd (n, d) RAW gradient rows (not pre-scaled by -lr — stateful
    optimizers are nonlinear in the gradient).
    Returns (new_kernel, new_slabs) in the stored layout — plus
    (new_hot, new_hot_slabs) under the hybrid placement."""
    d = op.out_dim
    plan = _row_plan(op)
    if plan is not None and gidx.shape[0] % plan.ndev == 0:
        # row-sharded: gradient rows + their global positions route to
        # the owning shard; weights AND state slabs update shard-locally
        # (hybrid hot rows apply in lockstep from an all-gather)
        from ..parallel.alltoall import row_sharded_opt_update
        owner, local, gid, hot_id = op._row_route(gidx)
        spec, _ = op._row_spec_block()
        if hot_id is not None:
            return row_sharded_opt_update(
                plan, tbl, slabs, spec, owner, local, upd, opt, step,
                d, gid=gid, hot_table=hot_tbl, hot_slabs=hot_slabs,
                hot_id=hot_id)
        return row_sharded_opt_update(plan, tbl, slabs, spec, owner,
                                      local, upd, opt, step, d, gid=gid)
    r = getattr(op, "_pack", 1)
    use_tiles = (r * d == 128
                 and _pallas_scatter_ok(op.model, 128, op.name)
                 and _row_shard_axes(op, d, total_rows // r) is None)
    if use_tiles:
        view = tbl.reshape(total_rows // r, r * d)
        slab_views = {k: v.reshape(total_rows // r, r * d)
                      for k, v in slabs.items()}
        nv, ns = _stateful_update_tiles_packed(view, gidx, upd, d, opt,
                                               slab_views, step, fwd_tiles)
    else:
        view = tbl.reshape(total_rows, d)
        slab_views = {k: v.reshape(total_rows, d) for k, v in slabs.items()}
        nv, ns = _stateful_update_rows_xla(view, gidx, upd, opt,
                                           slab_views, step)
    return (nv.reshape(tbl.shape),
            {k: ns[k].reshape(slabs[k].shape) for k in slabs})


def _pallas_common(model, op_name: str, width_ok: bool) -> bool:
    """Checks shared by every Pallas routing gate: opted in, TPU backend,
    supported width, not host-offloaded (a Mosaic TPU custom call cannot
    run inside a compute_on("device_host") region)."""
    if not getattr(model.config, "use_pallas", False):
        return False
    if not width_ok:
        return False
    if jax.default_backend() != "tpu":
        return False
    if op_name and op_name in getattr(model, "_host_offload_ops", set()):
        return False
    return True


def _pallas_gate(model, op_name: str, width_ok: bool) -> bool:
    """Single-chip Pallas gate (under a >1-device mesh the op runs inside
    GSPMD where the direct Pallas call cannot; the multi-chip scatter goes
    through _row_shard_axes + shard_map instead)."""
    if not _pallas_common(model, op_name, width_ok):
        return False
    mesh = getattr(model, "mesh", None)
    return mesh is None or mesh.size <= 1


def _row_shard_axes(op, d: int, packed_rows: int):
    """Mesh axes over which `op`'s packed table rows are block-sharded —
    when the multi-chip Pallas scatter can run (TPU, pallas on, not host-
    offloaded, lane-packable width, table actually sharded on dim 0).
    Returns None when the single-chip or XLA path should be used."""
    model = op.model
    mesh = getattr(model, "mesh", None)
    if mesh is None or mesh.size <= 1:
        return None
    width_ok = d <= 128 and 128 % d == 0
    if not _pallas_common(model, op.name, width_ok):
        return None
    # the sharded kernel assumes the LANE-PACKED layout; an unpacked
    # narrow table (rows not divisible by 128//d) must not be routed here
    expected_r = 128 // d
    if getattr(op, "_pack", 1) != expected_r:
        return None
    sh = getattr(model, "_param_sharding", {}).get(op.name, {}).get("kernel")
    if sh is None or not len(sh.spec) or not sh.spec[0]:
        return None
    spec0 = sh.spec[0]
    axes = (spec0,) if isinstance(spec0, str) else tuple(spec0)
    nsh = 1
    for a in axes:
        nsh *= mesh.shape[a]
    if nsh <= 1:
        return None
    # the shard_map kernel needs equal row blocks per shard
    if packed_rows % nsh != 0:
        return None
    return axes


# ---- row/PARAM-axis sharding with explicit all-to-all routing ------------
# The pod-scale mode (ParallelConfig.param_degree > 1): the table's ROW
# space is block-sharded over mesh devices — no single device ever holds a
# whole table — and lookups are routed to owners and back by the dense
# all-to-all exchange in parallel/alltoall.py. Activated per op by
# FFModel._build_shardings via configure_row_shard(); every routed path
# below gates on `op._row_plan`.


# hot-row quantum, in lane-pack units: the hybrid hot count rounds to a
# multiple of HOT_QUANTUM_PACKS x pack so the SAME hot split works for
# every row-shard degree dividing 8 — an elastic clamp 8 -> 4 -> 2 can
# reshard the cold tail without changing the hot block's shape (and the
# checkpoint stays restorable across the clamp)
HOT_QUANTUM_PACKS = 8


def resolve_hot_rows(rows: int, pack: int, param_degree: int,
                     hot_fraction: float) -> int:
    """Per-table replicated hot-row count H for the hybrid placement:
    `hot_fraction` of `rows`, rounded to the hot quantum, such that the
    cold tail (rows - H) still equal-blocks `param_degree` row shards at
    the lane packing. 0 = no hybrid (infeasible requests resolve to 0
    and the caller degrades loudly to plain row sharding)."""
    if hot_fraction <= 0.0 or param_degree <= 1 or rows <= 0:
        return 0
    q = HOT_QUANTUM_PACKS * max(pack, 1)
    if q >= rows:
        return 0
    h = int(round(hot_fraction * rows / q)) * q
    h = max(h, q)
    h = min(h, rows - q)
    if (rows - h) % (param_degree * max(pack, 1)) != 0:
        return 0
    return h


def row_shard_structural_reason(op, raw_pc, axis_sizes) -> Optional[str]:
    """Mesh-free feasibility of `raw_pc.param_degree`-way row sharding
    for `op` over a factorized mesh with `axis_sizes`, or None when the
    request is executable. THE shared rule set: configure_row_shard
    applies it against the live mesh at compile time, and the static
    plan verifier (analysis/shardcheck.py) and elastic clamp
    (search/replan.py) apply it to offline plans — all three must agree
    on what "silently replicates" means."""
    pd = getattr(raw_pc, "param_degree", 1) if raw_pc is not None else 1
    if pd <= 1:
        return None
    if not hasattr(op, "_row_shard_geometry"):
        return ("op has no row-shard support (no configure_row_shard "
                "hook)")
    rows, pack, _tables = op._row_shard_geometry()
    batch = op.inputs[0].shape[0]
    ndev = 1
    for a in axis_sizes:
        ndev *= int(a)
    aggr = getattr(op, "aggr", AGGR_MODE_SUM)
    if aggr not in (AGGR_MODE_SUM, AGGR_MODE_AVG):
        return f"aggr={aggr!r} has no routed bag aggregation"
    if len(raw_pc.degrees) > 1 and any(d > 1 for d in raw_pc.degrees[1:]):
        return (f"degrees {raw_pc.degrees} also request table/width "
                f"sharding — pick one axis for the table")
    from ..parallel.sharding import assignable
    if pd > ndev or not assignable((pd,), list(axis_sizes)):
        return (f"{pd} row shards do not factorize mesh axes "
                f"{[int(a) for a in axis_sizes]}")
    if rows % (pd * max(pack, 1)) != 0:
        return (f"{pd} row shards must divide the {rows} padded rows "
                f"(lane pack {pack})")
    if batch % ndev != 0:
        return (f"batch {batch} does not divide over the {ndev}-device "
                f"mesh (lookups route from batch shards)")
    frac = getattr(raw_pc, "hot_fraction", 0.0)
    if frac > 0 and not getattr(op, "_hot_split_ok", False):
        return (f"hot_fraction={frac:g} requested but this op has no "
                f"per-table hot/cold split (concatenated non-uniform "
                f"tables keep every row routed)")
    return None


def configure_row_shard(op, raw_pc) -> None:
    """Resolve (and validate) the row-shard plan for `op` from its RAW
    strategy's param_degree (+ the skew refinements: exchange mode and
    hot_fraction). Sets ``op._row_plan`` (None = mode off) and
    ``op._hot_rows`` (per-table replicated hot rows; 0 = no hybrid).
    Infeasible requests degrade loudly to replicated rows — a silent
    fallback would OOM exactly the >HBM configs this mode exists for, so
    the warning names the reason."""
    from ..parallel.alltoall import plan_row_shard
    op._row_plan = None
    op._hot_rows = 0
    pd = getattr(raw_pc, "param_degree", 1) if raw_pc is not None else 1
    if pd <= 1:
        return
    model = op.model
    mesh = getattr(model, "mesh", None)
    rows, pack, tables = op._row_shard_geometry()
    dedup = getattr(raw_pc, "exchange", "dense") == "dedup"
    frac = getattr(raw_pc, "hot_fraction", 0.0)
    reason = None
    if mesh is None or mesh.size <= 1:
        reason = "needs a multi-device mesh"
    elif (op.name in getattr(model, "_host_resident_ops", set())
          or op.name in getattr(model, "_host_offload_ops", set())):
        reason = "host-resident/offloaded tables cannot row-shard in HBM"
    else:
        reason = row_shard_structural_reason(
            op, raw_pc, [int(mesh.shape[a]) for a in mesh.axis_names])
    hot = 0
    if reason is None and frac > 0:
        hot = resolve_hot_rows(rows, pack, pd, frac)
        if hot <= 0:
            log_emb.warning(
                "hot_fraction=%g for %r resolves to no replicable hot "
                "block (rows=%d, lane pack %d, %d shards, quantum %d "
                "rows); executing plain row sharding", frac, op.name,
                rows, pack, pd, HOT_QUANTUM_PACKS * max(pack, 1))
    if reason is None:
        plan = plan_row_shard(mesh, pd, rows - hot, pack, tables,
                              dedup=dedup, hot_rows=hot,
                              overlap=bool(getattr(raw_pc, "overlap",
                                                   False)))
        if plan is None:
            sizes = [int(mesh.shape[a]) for a in mesh.axis_names]
            reason = (f"{pd} row shards must factorize mesh axes {sizes} "
                      f"and divide the {rows} padded rows "
                      f"(lane pack {pack})")
        else:
            op._row_plan = plan
            op._hot_rows = hot
            return
    log_emb.warning(
        "row sharding (param_degree=%d) requested for %r but %s; "
        "executing with replicated rows", pd, op.name, reason)


def configure_quant(op, raw_pc) -> None:
    """Resolve the quantized-storage policy for ``op`` from its RAW
    strategy entry (``quant_dtype``/``quant_update``) with the model's
    ``--emb-dtype``/``--emb-update-rule`` as the default. Sets
    ``op._quant_policy`` — THE per-op policy every byte-accounting and
    storage-boundary site reads via ``quant.effective_policy`` — and
    registers it in ``model._quant_policies`` (non-default policies
    only) for the publisher/serving/manifest consumers."""
    from ..quant.policy import FP32, policy_from_config, policy_from_pc
    pol = policy_from_pc(raw_pc) \
        or policy_from_config(op.model.config) or FP32
    op._quant_policy = pol
    reg = getattr(op.model, "_quant_policies", None)
    if reg is None:
        reg = {}
        op.model._quant_policies = reg
    if pol.is_default:
        reg.pop(op.name, None)
        return
    reg[op.name] = pol
    log_emb.info(
        "quantized storage for %r: dtype=%s update_rule=%s "
        "(row-wise scales%s)", op.name, pol.dtype, pol.update_rule,
        "" if pol.is_quantized else " n/a")


def _row_plan(op):
    return getattr(op, "_row_plan", None)


def _id_histogram(op):
    """The op's observed id-frequency sketch (utils/histogram.py),
    attached by FFModel.attach_id_histograms / fit_stream collection, or
    a uniform default — under which dedup ~= dense and the hybrid
    placement never looks attractive, exactly right for unknown
    traffic."""
    from ..utils.histogram import IdFrequencySketch
    hist = getattr(op.model, "_id_histograms", {}).get(op.name)
    if hist is not None:
        return hist
    rows, _pack, tables = op._row_shard_geometry() \
        if hasattr(op, "_row_shard_geometry") else (op.num_entries, 1, 1)
    return IdFrequencySketch(rows * tables)


def expected_routed_lookups(op, pc, per_device_lookups: float) -> float:
    """THE skew term: how many lookup slots one device actually routes
    through the exchange per step under `pc`'s exchange/hot policy,
    from the op's observed id histogram.

    - hybrid (hot_fraction > 0): hot hits are served locally, so only
      the cold fraction routes;
    - dedup: duplicates collapse, so the routed count is the EXPECTED
      DISTINCT (cold) ids among the device's draws — the quantity
      ``IdFrequencySketch.expected_distinct`` computes.

    Uniform (no histogram) traffic makes dedup ~= dense on big tables
    and prices the hot set at its row fraction — so the search only
    reaches for these modes when the observed distribution rewards
    them."""
    rows, pack, tables = op._row_shard_geometry()
    pd = max(getattr(pc, "param_degree", 1), 1)
    hot = resolve_hot_rows(rows, pack, pd,
                           getattr(pc, "hot_fraction", 0.0)) \
        if getattr(op, "_hot_split_ok", False) else 0
    hist = _id_histogram(op)
    if getattr(pc, "exchange", "dense") == "dedup":
        return hist.expected_distinct(per_device_lookups,
                                      hot_rows_per_table=hot,
                                      rows_per_table=rows)
    if hot > 0:
        return per_device_lookups * (1.0 - hist.hot_mass(hot, rows,
                                                         tables))
    return per_device_lookups


def _a2a_payload_bytes(op, ndev: int, itemsize: int, pc=None):
    """Per-device all-to-all payloads for a row-sharded lookup under the
    balanced (production/ragged) exchange, for the simulator: (request
    ids, embedded rows back, gradient rows out). The (P−1)/P exchanged
    fraction is applied by CostModel.alltoall_time_axes per axis. With
    `pc`, the skew-aware exchange policies shrink the routed count
    (expected distinct / cold-only ids from the observed histogram)."""
    n_dev = _lookup_count(op) / max(ndev, 1)
    if pc is not None:
        n_dev = expected_routed_lookups(op, pc, n_dev)
    d = op.out_dim
    req = n_dev * 4.0                      # int32 row ids
    # embedded rows back: at the table's STORED width under a quantized
    # policy (int8/fp8 rows + one fp32 scale each ride the exchange —
    # ids route unchanged, the payload shrinks ~4x), else compute dtype
    from ..quant.policy import effective_policy
    pol = effective_policy(op, pc)
    if pol.is_default:
        rows = n_dev * d * float(itemsize)
    else:
        rows = n_dev * pol.row_bytes(d)
    grad = n_dev * (4.0 + d * 4.0)         # fp32 grad rows + positions
    return req, rows, grad


def expected_hot_distinct(op, pc, per_device_lookups: float) -> float:
    """Expected DISTINCT hot ids one device touches per step under
    `pc`'s hybrid placement — the hot update stream is pre-combined per
    hot id before the all-gather (parallel/alltoall._hot_combine), so
    this, not the raw hot-hit count, is what moves and what every
    replica scatters."""
    rows, pack, tables = op._row_shard_geometry()
    pd = max(getattr(pc, "param_degree", 1), 1)
    hot = resolve_hot_rows(rows, pack, pd,
                           getattr(pc, "hot_fraction", 0.0)) \
        if getattr(op, "_hot_split_ok", False) else 0
    if hot <= 0:
        return 0.0
    hist = _id_histogram(op)
    all_d = hist.expected_distinct(per_device_lookups)
    cold_d = hist.expected_distinct(per_device_lookups,
                                    hot_rows_per_table=hot,
                                    rows_per_table=rows)
    return min(max(all_d - cold_d, 0.0), float(hot * tables))


def hot_update_bytes(op, pc, ndev: int) -> float:
    """Per-device bytes of the hybrid placement's HOT update stream:
    the all-gathered fp32 per-hot-id partial sums (+ id/position) every
    replica applies in lockstep — priced like the replicated-table
    allreduce the simulator already knows, but only over the DISTINCT
    hot ids actually touched."""
    n_dev = _lookup_count(op) / max(ndev, 1)
    hot_d = expected_hot_distinct(op, pc, n_dev)
    return hot_d * (8.0 + op.out_dim * 4.0)


# hot fractions the search samples for the hybrid placement (resolved
# against each table's geometry; unresolvable ones are skipped)
_HOT_FRACTIONS = (1.0 / 64, 1.0 / 16)


def _row_shard_candidates(op, num_devices, feasible_degrees, nd):
    """PARAM-axis candidates for the MCMC search: rows split over pp
    shards, output data-parallel over the whole target mesh (the
    pod-scale shape the cost model trades against pure DP) — in the
    dense exchange, the dedup'd (unique-ids) exchange, and, for ops
    with a per-table hot split, the hot/cold hybrid placement. The
    skew term (expected_routed_lookups) is what lets the walk tell
    them apart: on uniform ids dense wins (dedup pays its sort for
    nothing), on zipfian ids dedup/hybrid win."""
    rows, pack, _ = op._row_shard_geometry()
    batch = op.inputs[0].shape[0]
    if batch % num_devices != 0 or op.aggr not in (AGGR_MODE_SUM,
                                                   AGGR_MODE_AVG):
        return []
    # the skew variants enter the walk ONLY when an observed histogram
    # is attached: without one the cost model assumes uniform ids,
    # under which dedup/hybrid price at best ~dense (minus the sort
    # overhead) — offering them would just dilute the walk. The
    # pipelined-exchange overlap flag is never a candidate here for the
    # same reason: it is a pure schedule toggle over the same bytes, so
    # mcmc.optimize flips it greedily on the annealed winner instead
    skewed = op.name in getattr(op.model, "_id_histograms", {})
    out = []
    for pp in feasible_degrees:
        if 1 < pp <= num_devices and rows % (pp * max(pack, 1)) == 0:
            degs = [1] * nd
            degs[0] = num_devices
            out.append(ParallelConfig(tuple(degs), param_degree=pp))
            if not skewed:
                continue
            out.append(ParallelConfig(tuple(degs), param_degree=pp,
                                      exchange="dedup"))
            if getattr(op, "_hot_split_ok", False):
                for frac in _HOT_FRACTIONS:
                    if resolve_hot_rows(rows, pack, pp, frac) > 0:
                        out.append(ParallelConfig(
                            tuple(degs), param_degree=pp,
                            exchange="dedup", hot_fraction=frac))
    return out


def _pallas_scatter_ok(model, out_dim: int, op_name: str = "") -> bool:
    """Gate for the Pallas RMW scatter kernel: XLA's TPU scatter lowers to
    a serialized loop (~250 ms for 2k rows on an 8M-row table)."""
    from .pallas.embedding_kernel import scatter_supports
    return _pallas_gate(model, op_name, scatter_supports(out_dim))


def _pallas_ok(model, out_dim: int, op_name: str = "") -> bool:
    """Gate for the Pallas row-streaming gather kernel."""
    from .pallas.embedding_kernel import supports
    return _pallas_gate(model, op_name, supports(out_dim))


class Embedding(Op):
    """Embedding bag: int indices (batch, bag) -> (batch, out_dim) with
    SUM/AVG aggregation, or (batch, bag, out_dim) with AGGR_MODE_NONE."""

    type_name = "Embed"
    # per-bag-slot (aggr="none") outputs work on the host-resident path
    host_aggr_none_ok = True

    def __init__(self, model, input_tensor, num_entries: int, out_dim: int,
                 aggr: str = AGGR_MODE_SUM, kernel_initializer=None,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.num_entries = int(num_entries)
        self.out_dim = int(out_dim)
        if aggr not in (AGGR_MODE_NONE, AGGR_MODE_SUM, AGGR_MODE_AVG):
            raise ValueError(f"bad aggr mode {aggr}")
        self.aggr = aggr
        self.kernel_initializer = kernel_initializer or GlorotUniform()
        batch = input_tensor.shape[0]
        if aggr == AGGR_MODE_NONE:
            out_shape = tuple(input_tensor.shape) + (self.out_dim,)
        else:
            out_shape = (batch, self.out_dim)
        self.outputs = [self._make_output(out_shape)]

    def param_defs(self) -> Dict[str, ParamDef]:
        H = getattr(self, "_hot_rows", 0)
        if H > 0:
            # hybrid placement (configure_row_shard resolved a hot
            # split): cold tail row-sharded, hot head replicated
            return {"kernel": ParamDef(
                        (self.num_entries - H, self.out_dim),
                        jnp.float32, self.kernel_initializer),
                    "hot_kernel": ParamDef(
                        (H, self.out_dim), jnp.float32,
                        self.kernel_initializer)}
        return {"kernel": ParamDef((self.num_entries, self.out_dim),
                                   jnp.float32, self.kernel_initializer)}

    def init_params(self, key):
        H = getattr(self, "_hot_rows", 0)
        if H <= 0:
            return super().init_params(key)
        # draw at the FULL logical shape with the same key the
        # non-hybrid build would use, then split — the hybrid table's
        # initial values are bitwise the baseline's
        keys = jax.random.split(key, 1)
        logical = self.kernel_initializer(
            keys[0], (self.num_entries, self.out_dim), jnp.float32)
        return {"kernel": logical[H:], "hot_kernel": logical[:H]}

    # ---- row/PARAM-axis sharding hooks (see configure_row_shard) -------
    _row_needs_2d_idx = True
    _hot_split_ok = True    # per-table hot/cold hybrid supported

    def _row_shard_geometry(self):
        return self.num_entries, getattr(self, "_pack", 1), 1

    def _row_route(self, g):
        """Flat global (wrapped) ids t*rows + ix -> the routed-lookup
        arrays (owner, local, gid, hot_id). Shared with
        EmbeddingBagStacked: each shard owns the same COLD row block of
        EVERY table; under the hybrid placement the per-table head
        (ix < hot rows) is served from the replicated hot block — those
        slots carry owner == nshards (excluded from the exchange), a
        gid in a disjoint key range (so the dedup machinery never
        merges them into a cold id's partial sum), and their flat
        hot-block row in hot_id (sentinel on cold slots)."""
        plan = self._row_plan
        rows = self.num_entries
        H = getattr(self, "_hot_rows", 0)
        rl = plan.rows_local
        ix = g % rows
        t = g // rows
        if H <= 0:
            return ((ix // rl).astype(jnp.int32),
                    (t * rl + ix % rl).astype(jnp.int32),
                    g.astype(jnp.int32), None)
        rc = rows - H
        is_hot = ix < H
        cix = jnp.maximum(ix - H, 0)
        owner = jnp.where(is_hot, plan.nshards,
                          cix // rl).astype(jnp.int32)
        local = jnp.where(is_hot, plan.flat_rows_local,
                          t * rl + cix % rl).astype(jnp.int32)
        hid = (t * H + ix).astype(jnp.int32)
        gid = jnp.where(is_hot, plan.tables * rc + hid,
                        t * rc + cix).astype(jnp.int32)
        hot_id = jnp.where(is_hot, hid,
                           plan.hot_rows_flat).astype(jnp.int32)
        return owner, local, gid, hot_id

    def _row_spec_block(self):
        from jax.sharding import PartitionSpec
        plan = self._row_plan
        return (PartitionSpec(plan.row_axes, None),
                (plan.rows_local, self.out_dim))

    def _hot_block_shape(self):
        return (getattr(self, "_hot_rows", 0), self.out_dim)

    def apply(self, params, xs, *, training=False, rng=None):
        (idx,) = xs
        table = params["kernel"]
        plan = _row_plan(self)
        if (plan is not None and idx.ndim == 2
                and idx.shape[0] % plan.ndev == 0):
            from ..parallel.alltoall import row_sharded_bag_lookup
            g = idx.astype(jnp.int32) % self.num_entries
            owner, local, gid, hot_id = self._row_route(g)
            spec, block = self._row_spec_block()
            return [row_sharded_bag_lookup(
                plan, table, spec, owner, local, self.out_dim,
                self.aggr, block, gid=gid,
                hot_table=params.get("hot_kernel"), hot_id=hot_id,
                hot_block_shape=self._hot_block_shape())]
        if (self.aggr in (AGGR_MODE_SUM, AGGR_MODE_AVG) and idx.ndim == 2
                and _pallas_ok(self.model, self.out_dim, self.name)):
            from .pallas.embedding_kernel import embedding_bag
            return [embedding_bag(table, idx, self.aggr)]
        # mode="wrap": modulo-index gather — scalar-only constants, so the
        # trace stays valid under compute_on host offload (the reference's
        # CUDA gather does no bounds handling at all, embedding.cu:173-224)
        rows = jnp.take(table, idx.astype(jnp.int32), axis=0,
                        mode="wrap")  # (..., bag, d)
        if self.aggr == AGGR_MODE_SUM:
            rows = jnp.sum(rows, axis=-2)
        elif self.aggr == AGGR_MODE_AVG:
            rows = jnp.mean(rows, axis=-2)
        return [rows]

    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        """Sample DP × width-sharded table, plus PARAM-axis row sharding
        (DP output over the whole mesh, rows split over pp shards with
        all-to-all lookup routing). (Reference partitions only the
        sample dim, embedding.cu:115-117.)"""
        out = []
        nd = self.outputs[0].num_dims
        for ds in feasible_degrees:
            for dc in feasible_degrees:
                if ds * dc <= num_devices and self.out_dim % max(dc, 1) == 0:
                    degs = [1] * nd
                    degs[0] = ds
                    degs[-1] = dc
                    out.append(ParallelConfig(tuple(degs)))
        out.extend(_row_shard_candidates(self, num_devices,
                                         feasible_degrees, nd))
        out.append(_zcm_candidate(nd))
        return out

    def param_axes(self, pc: ParallelConfig, out_axes,
                   raw_pc=None):
        if _row_plan(self) is not None:
            axes = {"kernel": (self._row_plan.row_axes, ())}
            if getattr(self, "_hot_rows", 0) > 0:
                axes["hot_kernel"] = ((), ())   # replicated hot head
            return axes
        # width sharding follows the output channel axes; rows replicated
        ch = out_axes[-1] if len(out_axes) >= 2 else ()
        return {"kernel": ((), ch)}

    def flops_per_sample(self) -> float:
        bag = self.inputs[0].shape[-1] if self.inputs[0].num_dims > 1 else 1
        return float(bag * self.out_dim)  # bandwidth-bound; count adds

    def param_shard_shapes(self, pc: ParallelConfig, ndev=None):
        pd = max(getattr(pc, "param_degree", 1), 1)
        if pd > 1:
            # row sharding: each shard holds cold_rows/pd full-width
            # rows (+ the whole replicated hot head under the hybrid)
            H = resolve_hot_rows(self.num_entries,
                                 getattr(self, "_pack", 1), pd,
                                 getattr(pc, "hot_fraction", 0.0))
            out = {"kernel": (max((self.num_entries - H) // pd, 1),
                              self.out_dim)}
            if H > 0:
                out["hot_kernel"] = (H, self.out_dim)
            return out
        # width sharding splits out_dim by the last degree
        dc = pc.degrees[-1] if len(pc.degrees) > 1 else 1
        return {"kernel": (self.num_entries, max(self.out_dim // dc, 1))}


    def random_hbm_rows(self, backward: bool = False,
                        raw: bool = False) -> float:
        return _embedding_random_rows(self, backward, raw)

    def update_random_hbm_rows(self, pc=None) -> float:
        return _embedding_update_rows(self, pc)

    def alltoall_payload_bytes(self, ndev: int, itemsize: int, pc=None):
        return _a2a_payload_bytes(self, ndev, itemsize, pc=pc)

    def param_bytes_touched_per_step(self, num_parts: int = 1) -> int:
        if not _sparse_update_active(self):
            return self.param_bytes()   # dense grad+update streams the table
        # gather read + sparse-update read/write of this shard's rows only
        batch = self.inputs[0].shape[0]
        bag = self.inputs[0].shape[-1] if self.inputs[0].num_dims > 1 else 1
        return int(_touched_bytes_factor(self) * batch * bag
                   * self.out_dim * 4 // max(num_parts, 1))

    # ---- sparse (touched-rows-only) SGD update -------------------------
    # The dense path materializes a gradient the size of the whole table
    # (XLA scatter-add of row cotangents into zeros — the functional analog
    # of the reference's table-sized gradient region, embedding.cu:95-105)
    # and then streams the full table through the SGD update. For plain SGD
    # that traffic is avoidable: dense grad rows are zero except gathered
    # rows, so  w -= lr*grad  ==  scatter_add(w, idx, -lr*row_ct)  exactly
    # (duplicate indices accumulate in both). model._build_steps routes
    # eligible embeddings through this method.
    def supports_sparse_update(self) -> bool:
        return self.aggr in (AGGR_MODE_SUM, AGGR_MODE_AVG, AGGR_MODE_NONE)

    def _fwd_residual_ok(self) -> bool:
        """Forward-gather residuals are usable only when a logical row IS
        one 128-lane tile (out_dim == 128, unpacked storage): then the
        rows the XLA-gather forward materializes anyway double as the
        update's weight tiles, sparing the update's random re-read. (The
        lane-packed variants cover narrower widths; see
        EmbeddingBagStacked._fwd_residual_ok.)"""
        return (self.out_dim == 128
                and getattr(self, "_pack", 1) == 1
                and self.aggr in (AGGR_MODE_SUM, AGGR_MODE_AVG)
                and self.inputs[0].num_dims == 2
                and _row_plan(self) is None
                and not _pallas_ok(self.model, self.out_dim, self.name)
                and _pallas_scatter_ok(self.model, 128, self.name)
                and _row_shard_axes(self, self.out_dim, self.num_entries)
                is None)

    def apply_with_fwd(self, params, xs, *, rng=None):
        """apply() plus forward-gather residuals (global rows + tiles);
        None residuals = caller should treat as plain apply."""
        if not self._fwd_residual_ok():
            return self.apply(params, xs, training=True, rng=rng), None
        (idx,) = xs
        table = params["kernel"]
        g = idx.astype(jnp.int32) % self.num_entries   # (batch, bag)
        rows = jnp.take(table, g, axis=0)              # (batch, bag, 128)
        out = (jnp.mean(rows, axis=-2) if self.aggr == AGGR_MODE_AVG
               else jnp.sum(rows, axis=-2))
        return [out], (g.reshape(-1), rows.reshape(-1, 128))

    def sparse_sgd_update(self, params, xs, out_ct, lr,
                          fwd=None):
        """params - lr * d(loss)/d(table), given out_ct = d(loss)/d(output).
        Touches only the gathered rows."""
        (idx,) = xs
        tbl = params["kernel"]
        idx = idx.astype(jnp.int32) % self.num_entries  # match wrap gather
        d = self.out_dim
        ct = out_ct.astype(tbl.dtype)
        if self.aggr == AGGR_MODE_AVG:
            ct = ct / idx.shape[-1]
        if self.aggr == AGGR_MODE_NONE:
            upd = ct.reshape(-1, d)                     # (batch*bag, d)
        else:
            # each row of the bag receives the bag-sum's cotangent
            upd = jnp.broadcast_to(ct[..., None, :],
                                   idx.shape + (d,)).reshape(-1, d)
        plan = _row_plan(self)
        if plan is not None and idx.size % plan.ndev == 0:
            # row-sharded: gradient rows route to their owning shard
            # (all-to-all) and apply there, in canonical order; hybrid
            # hot rows apply in lockstep from an all-gather
            from ..parallel.alltoall import row_sharded_sgd_update
            owner, local, gid, hot_id = self._row_route(idx.reshape(-1))
            spec, _ = self._row_spec_block()
            out = row_sharded_sgd_update(
                plan, tbl, spec, owner, local, upd, lr, d, gid=gid,
                hot_table=params.get("hot_kernel"), hot_id=hot_id)
            if hot_id is None:
                return {"kernel": out}
            new, new_hot = out
            return {"kernel": new, "hot_kernel": new_hot}
        if fwd is not None and self._fwd_residual_ok():
            # write-only path: the forward's gathered rows are the tiles,
            # so new rows land without the RMW read
            from .pallas.embedding_kernel import scatter_write_rows_packed
            g_flat, tiles = fwd
            new = scatter_write_rows_packed(tbl, g_flat, -lr * upd,
                                            tiles, d)
            return {"kernel": new}
        if _pallas_scatter_ok(self.model, d, self.name):
            from .pallas.embedding_kernel import scatter_add_rows
            new = scatter_add_rows(tbl, idx.reshape(-1), -lr * upd)
        else:
            new = tbl.at[idx.reshape(-1)].add(-lr * upd)
        return {"kernel": new}

    def sparse_opt_update(self, params, xs, out_ct, opt, slabs, step,
                          fwd=None):
        """Stateful touched-rows update (lazy momentum / Adam): the dense
        update streams the whole table + state slabs (reference
        optimizer_kernel.cu adam_update world); this touches only the
        gathered rows' weights AND state."""
        (idx,) = xs
        tbl = params["kernel"]
        idx = idx.astype(jnp.int32) % self.num_entries
        d = self.out_dim
        ct = out_ct.astype(jnp.float32)
        if self.aggr == AGGR_MODE_AVG:
            ct = ct / idx.shape[-1]
        if self.aggr == AGGR_MODE_NONE:
            upd = ct.reshape(-1, d)
        else:
            upd = jnp.broadcast_to(ct[..., None, :],
                                   idx.shape + (d,)).reshape(-1, d)
        fwd_tiles = (fwd[1] if fwd is not None and self._fwd_residual_ok()
                     else None)
        kslabs, hslabs, nested = _norm_slabs(slabs)
        out = _sparse_opt_update(self, tbl, idx.reshape(-1), upd,
                                 opt, kslabs, step,
                                 self.num_entries, fwd_tiles,
                                 hot_tbl=params.get("hot_kernel"),
                                 hot_slabs=hslabs)
        return _finish_opt_update(out, nested)

    # ---- delta publication (utils/delta.py) ----------------------------
    # A batch's lookup indices mapped to the rows of the STORED kernel
    # (flattened to 2-D over all-but-the-last axis) that a touched-rows
    # update can change. The continual-learning publisher restricts its
    # publish-time diff to these candidates; serving's EmbeddingCache
    # uses the host variant to invalidate only dirtied samples.
    def delta_touched_rows(self, idx_np) -> "np.ndarray":
        import numpy as np
        g = np.asarray(idx_np).astype(np.int64).reshape(-1) \
            % self.num_entries
        H = getattr(self, "_hot_rows", 0)
        if H > 0:
            # "kernel" stores only the cold tail under the hybrid
            # placement; the (small) replicated hot block stays
            # untracked — the publisher diffs it whole
            g = g[g >= H] - H
        return np.unique(g)

    def host_delta_touched_rows(self, idx_np) -> "np.ndarray":
        # host table is (num_entries, out_dim) — same natural layout
        # (host-resident tables never row-shard, so never hybrid)
        import numpy as np
        g = np.asarray(idx_np).astype(np.int64).reshape(-1) \
            % self.num_entries
        return np.unique(g)

    def flat_lookup_ids(self, idx_np) -> "np.ndarray":
        """Batch indices -> flat lookup-id space, for the id-frequency
        sketch (utils/histogram.py) collected at staging."""
        import numpy as np
        return (np.asarray(idx_np).astype(np.int64).reshape(-1)
                % self.num_entries)

    # ---- host-resident table form (reference embedding_avx2.cc) --------
    def host_init(self, seed: int):
        return {"kernel": _host_init_table(
            self.kernel_initializer, (self.num_entries, self.out_dim), seed)}

    def host_flat_indices(self, idx_np):
        """Per-sample FLAT row ids, shaped (batch, 1, bag) — the shared
        geometry the host lookup and the serving shard tier
        (serve/shardtier.py) route lookups through."""
        import numpy as np
        g = idx_np.astype(np.int64) % self.num_entries
        if g.ndim == 1:
            g = g[:, None]
        return g[:, None, :]

    def host_lookup_rows(self, rows_2d, g3):
        """``host_lookup`` against an arbitrary (rows, d) row matrix
        with already-remapped flat indices: the shard tier assembles
        fetched shard rows through this, so a sharded lookup is
        bit-identical to the local host path (same gather, same bag
        reduction, same order)."""
        import numpy as np
        if self.aggr == AGGR_MODE_NONE:
            # per-bag-slot outputs: no reduction, (batch, bag, d)
            return np.ascontiguousarray(rows_2d[g3[:, 0]], np.float32)
        return _host_bag_lookup(rows_2d, g3, self.aggr)[:, 0]  # (batch,d)

    def host_lookup(self, host_params, idx_np):
        return self.host_lookup_rows(host_params["kernel"],
                                     self.host_flat_indices(idx_np))

    def host_sgd_update(self, host_params, idx_np, ct_np, lr):
        import numpy as np
        g = idx_np.astype(np.int64) % self.num_entries
        if g.ndim == 1:
            g = g[:, None]
        if self.aggr == AGGR_MODE_NONE:
            # ct (batch, bag, d): each slot's cotangent lands on its row
            d = self.out_dim
            np.add.at(host_params["kernel"], g.reshape(-1),
                      -lr * ct_np.reshape(-1, d))
            return
        _host_bag_update(host_params["kernel"], g[:, None, :],
                         ct_np[:, None, :], lr, self.aggr)

    def host_opt_update(self, host_params, idx_np, ct_np, opt, slabs,
                        step):
        """Lazy stateful (momentum/Adam) host update; see
        _host_stateful_update."""
        import numpy as np
        g = idx_np.astype(np.int64) % self.num_entries
        if g.ndim == 1:
            g = g[:, None]
        if self.aggr == AGGR_MODE_NONE:
            uniq, summed = _host_dedup_rows(
                g.reshape(-1), ct_np.reshape(-1, self.out_dim))
            tbl = host_params["kernel"]
            slab_rows = {k: v[uniq] for k, v in slabs.items()}
            wn, sn = opt.sparse_row_update_np(tbl[uniq], summed,
                                              slab_rows, step)
            tbl[uniq] = wn
            for k in slabs:
                slabs[k][uniq] = sn[k]
            return
        _host_stateful_update(host_params["kernel"], g[:, None, :],
                              ct_np[:, None, :], opt, slabs, step,
                              self.aggr)


class EmbeddingBagStacked(Op):
    """N same-shape embedding bags fused into one (N, rows, dim) parameter.

    This is the TPU-native form of the reference DLRM strategy "each table
    whole on one device" (dlrm_strategy.cc:252-256): shard dim 0 (the table
    dim) over mesh axes; each device holds num_tables/parts full tables,
    looks up the *global* batch for its tables, and the downstream
    batch-dim resharding is the all-to-all the reference got implicitly
    from Legion region movement. XLA emits that collective from the
    sharding constraints alone.

    input: int (batch, num_tables, bag)  ->  output (batch, num_tables, dim)
    """

    type_name = "EmbedStack"

    def __init__(self, model, input_tensor, num_tables: int, num_entries: int,
                 out_dim: int, aggr: str = AGGR_MODE_SUM,
                 kernel_initializer=None, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        assert input_tensor.num_dims == 3, "expect (batch, num_tables, bag)"
        assert input_tensor.shape[1] == num_tables
        self.num_tables = int(num_tables)
        self.num_entries = int(num_entries)
        self.out_dim = int(out_dim)
        self.aggr = aggr
        self.kernel_initializer = kernel_initializer or GlorotUniform()
        # lane packing: narrow rows (d < 128 dividing 128) are stored
        # r-per-128-lane-tile as (T, rows/r, r*d) so the TPU keeps the
        # natural row-major tiled layout — an unpacked (rows, d) table gets
        # a transposed lane-packing layout from XLA, which forces
        # whole-table transpose copies at every Pallas kernel boundary
        self._pack = _pack_factor(self.out_dim, self.num_entries)
        batch = input_tensor.shape[0]
        self.outputs = [self._make_output((batch, self.num_tables, self.out_dim))]
        # storage permutation honoring strategy device_ids: stored slot s
        # holds LOGICAL table _table_order[s], so block-sharding dim 0
        # reproduces the reference's per-table device assignment
        # (dlrm_strategy.cc:242-296 round-robins table i to device i%N;
        # mapper.cc:33-97 places point tasks there). None = identity.
        self._table_order = None
        self._table_inv = None

    def set_table_order(self, order):
        """Storage order for the stacked tables (see __init__)."""
        order = tuple(int(t) for t in order)
        if sorted(order) != list(range(self.num_tables)):
            raise ValueError(f"not a table permutation: {order}")
        if order == tuple(range(self.num_tables)):
            self._table_order = self._table_inv = None
            return
        inv = [0] * self.num_tables
        for s, t in enumerate(order):
            inv[t] = s
        self._table_order = jnp.asarray(order, jnp.int32)
        self._table_inv = jnp.asarray(inv, jnp.int32)

    def param_defs(self):
        r = self._pack
        H = getattr(self, "_hot_rows", 0)
        if H > 0:
            return {"kernel": ParamDef(
                        (self.num_tables, (self.num_entries - H) // r,
                         self.out_dim * r),
                        jnp.float32, self.kernel_initializer),
                    "hot_kernel": ParamDef(
                        (self.num_tables, H // r, self.out_dim * r),
                        jnp.float32, self.kernel_initializer)}
        return {"kernel": ParamDef(
            (self.num_tables, self.num_entries // r, self.out_dim * r),
            jnp.float32, self.kernel_initializer)}

    def init_params(self, key):
        # initialize each table at its LOGICAL (rows, d) shape so
        # shape-dependent initializers (Glorot fans) match the unfused
        # per-table ops, then pack
        keys = jax.random.split(key, self.num_tables)
        tables = jnp.stack([
            self.kernel_initializer(
                k, (self.num_entries, self.out_dim), jnp.float32)
            for k in keys])
        H = getattr(self, "_hot_rows", 0)
        if H <= 0:
            return {"kernel": self.pack_kernel(tables)}
        # hybrid: the SAME draws split into the replicated hot head and
        # the row-sharded cold tail — bitwise the baseline's values
        r, d = self._pack, self.out_dim
        if self._table_order is not None:
            tables = jnp.take(tables, self._table_order, axis=0)
        return {"kernel": tables[:, H:].reshape(
                    self.num_tables, (self.num_entries - H) // r, r * d),
                "hot_kernel": tables[:, :H].reshape(
                    self.num_tables, H // r, r * d)}

    def unpack_kernel(self, kernel):
        """(T, rows/r, r*d) stored form -> logical (T, rows, d)."""
        logical = kernel.reshape(self.num_tables, self.num_entries,
                                 self.out_dim)
        if self._table_order is not None:
            logical = jnp.take(logical, self._table_inv, axis=0)
        return logical

    def pack_kernel(self, logical):
        r = self._pack
        if self._table_order is not None:
            logical = jnp.take(logical, self._table_order, axis=0)
        return logical.reshape(self.num_tables, self.num_entries // r,
                               self.out_dim * r)

    # ---- row/PARAM-axis sharding hooks (see configure_row_shard) -------
    _hot_split_ok = True    # uniform tables: per-table hot/cold split

    def _row_shard_geometry(self):
        return self.num_entries, self._pack, self.num_tables

    _row_route = Embedding._row_route

    def _row_spec_block(self):
        from jax.sharding import PartitionSpec
        plan = self._row_plan
        r = self._pack
        return (PartitionSpec(None, plan.row_axes, None),
                (self.num_tables, plan.rows_local // r,
                 self.out_dim * r))

    def _hot_block_shape(self):
        r = self._pack
        return (self.num_tables, getattr(self, "_hot_rows", 0) // r,
                self.out_dim * r)

    def apply(self, params, xs, *, training=False, rng=None):
        (idx,) = xs  # (batch, T, bag)
        table = params["kernel"]  # (T, rows/r, r*d)
        idx = idx.astype(jnp.int32) % self.num_entries
        if self._table_order is not None:
            idx = jnp.take(idx, self._table_order, axis=1)
        r, d = self._pack, self.out_dim

        plan = _row_plan(self)
        if plan is not None and idx.shape[0] % plan.ndev == 0:
            # row-sharded lookup: indices route to owning shards over
            # the mesh's row axes, embedded rows route back
            from ..parallel.alltoall import row_sharded_bag_lookup
            rows = self.num_entries
            offs = (jnp.arange(self.num_tables, dtype=jnp.int32)
                    * rows)[None, :, None]
            owner, local, gid, hot_id = self._row_route(idx + offs)
            spec, block = self._row_spec_block()
            out = row_sharded_bag_lookup(
                plan, table, spec, owner, local, d, self.aggr, block,
                gid=gid, hot_table=params.get("hot_kernel"),
                hot_id=hot_id, hot_block_shape=self._hot_block_shape())
            if self._table_inv is not None:
                out = jnp.take(out, self._table_inv, axis=1)
            return [out]

        if (self.aggr in (AGGR_MODE_SUM, AGGR_MODE_AVG) and r == 1
                and _pallas_ok(self.model, self.out_dim, self.name)):
            from .pallas.embedding_kernel import stacked_embedding_bag
            out = stacked_embedding_bag(table, idx, self.aggr)
        else:
            # vmap over the table dim: for each table t, gather its own
            # rows for the full batch. With dim-0 sharded params + matching
            # sharding constraints this lowers to per-device local gathers
            # + all-to-all.
            def one_table(tbl, ix):  # tbl (rows/r, r*d), ix (batch, bag)
                if r == 1:
                    rows = jnp.take(tbl, ix, axis=0, mode="wrap")
                else:
                    rows = _packed_gather(tbl, ix, r, d)   # (batch, bag, d)
                if self.aggr == AGGR_MODE_AVG:
                    return jnp.mean(rows, axis=1)
                return jnp.sum(rows, axis=1)

            out = jax.vmap(one_table, in_axes=(0, 1), out_axes=1)(table, idx)
        if self._table_order is not None:
            out = jnp.take(out, self._table_inv, axis=1)
        return [out]  # (batch, T, d) in LOGICAL table order

    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        # partition the table dim (dim 1 of the output) and/or sample
        # dim, plus PARAM-axis row sharding of every table
        out = []
        for ds in feasible_degrees:
            for dt in feasible_degrees:
                if ds * dt <= num_devices and self.num_tables % max(dt, 1) == 0:
                    out.append(ParallelConfig((ds, dt, 1)))
        out.extend(_row_shard_candidates(self, num_devices,
                                         feasible_degrees, 3))
        out.append(_zcm_candidate(3))
        return out

    def param_axes(self, pc: ParallelConfig, out_axes,
                   raw_pc=None):
        if _row_plan(self) is not None:
            # rows of EVERY table block-shard over the row axes; the
            # table dim stays whole on each shard (the hybrid hot head
            # is replicated everywhere)
            axes = {"kernel": ((), self._row_plan.row_axes, ())}
            if getattr(self, "_hot_rows", 0) > 0:
                axes["hot_kernel"] = ((), (), ())
            return axes
        # table dim of the param follows output dim 1's axes
        t_axes = out_axes[1] if len(out_axes) >= 2 else ()
        return {"kernel": (t_axes, (), ())}

    def flops_per_sample(self) -> float:
        bag = self.inputs[0].shape[-1]
        return float(self.num_tables * bag * self.out_dim)

    def input_shard_shapes(self, pc: ParallelConfig):
        # indices follow the output's (sample, table) sharding so measured
        # microbenchmarks trace at consistent per-device shapes
        ds = max(pc.degrees[0] if pc.degrees else 1, 1)
        dt = pc.degrees[1] if len(pc.degrees) > 1 else 1
        batch, T, bag = self.inputs[0].shape
        return [(max(batch // ds, 1), max(T // max(dt, 1), 1), bag)]

    def param_shard_shapes(self, pc: ParallelConfig, ndev=None):
        r = self._pack
        pd = max(getattr(pc, "param_degree", 1), 1)
        if pd > 1:
            # row sharding: all T tables present, cold_rows/pd of each
            # (+ the whole replicated hot head under the hybrid)
            H = resolve_hot_rows(self.num_entries, r, pd,
                                 getattr(pc, "hot_fraction", 0.0))
            out = {"kernel": (self.num_tables,
                              max((self.num_entries - H) // r // pd, 1),
                              self.out_dim * r)}
            if H > 0:
                out["hot_kernel"] = (self.num_tables, H // r,
                                     self.out_dim * r)
            return out
        # table-dim sharding by degrees[1]
        dt = pc.degrees[1] if len(pc.degrees) > 1 else 1
        return {"kernel": (max(self.num_tables // dt, 1),
                           self.num_entries // r, self.out_dim * r)}


    def random_hbm_rows(self, backward: bool = False,
                        raw: bool = False) -> float:
        return _embedding_random_rows(self, backward, raw)

    def update_random_hbm_rows(self, pc=None) -> float:
        return _embedding_update_rows(self, pc)

    def alltoall_payload_bytes(self, ndev: int, itemsize: int, pc=None):
        return _a2a_payload_bytes(self, ndev, itemsize, pc=pc)

    def param_bytes_touched_per_step(self, num_parts: int = 1) -> int:
        if not _sparse_update_active(self):
            return self.param_bytes()
        batch, _, bag = self.inputs[0].shape
        return int(_touched_bytes_factor(self) * batch * self.num_tables
                   * bag * self.out_dim * 4 // max(num_parts, 1))

    # ---- sparse (touched-rows-only) SGD update (see Embedding) ---------
    def supports_sparse_update(self) -> bool:
        return self.aggr in (AGGR_MODE_SUM, AGGR_MODE_AVG)

    def _fwd_residual_ok(self) -> bool:
        """Whether the packed-gather forward can hand its tiles to a
        write-only sparse update (single chip, lane-packed storage, the
        Pallas scatter available, XLA gather path in use)."""
        return (self._pack > 1
                and self.aggr in (AGGR_MODE_SUM, AGGR_MODE_AVG)
                and _row_plan(self) is None
                and not _pallas_ok(self.model, self.out_dim, self.name)
                and _pallas_scatter_ok(self.model, 128, self.name)
                and _row_shard_axes(
                    self, self.out_dim,
                    self.num_tables * self.num_entries // self._pack)
                is None)

    def apply_with_fwd(self, params, xs, *, rng=None):
        """apply() plus forward-gather residuals (global unpacked rows +
        packed tiles): random HBM rows are latency-bound (~0.3 µs each,
        BENCHMARKS.md), so keeping the 1 MB of gathered tiles lets the
        sparse update WRITE new rows without re-reading them — halving
        the update's random accesses vs the RMW kernel. Returns
        (outs, fwd|None); None = caller should treat as plain apply."""
        if not self._fwd_residual_ok():
            return self.apply(params, xs, training=True, rng=rng), None
        (idx,) = xs
        table = params["kernel"]
        idx = idx.astype(jnp.int32) % self.num_entries
        if self._table_order is not None:
            idx = jnp.take(idx, self._table_order, axis=1)
        r, d = self._pack, self.out_dim
        T, rows = self.num_tables, self.num_entries
        view = table.reshape(T * rows // r, r * d)
        offs = (jnp.arange(T, dtype=jnp.int32) * rows)[None, :, None]
        g = idx + offs                                 # (batch, T, bag)
        rows_g, _, tiles = _packed_gather_tiles(view, g, r, d)
        out = (jnp.mean(rows_g, axis=2) if self.aggr == AGGR_MODE_AVG
               else jnp.sum(rows_g, axis=2))
        if self._table_order is not None:
            out = jnp.take(out, self._table_inv, axis=1)
        return [out], (g.reshape(-1), tiles)

    def sparse_sgd_update(self, params, xs, out_ct, lr, fwd=None):
        (idx,) = xs                       # (batch, T, bag)
        tbl = params["kernel"]            # (T, rows/r, r*d)
        idx = idx.astype(jnp.int32) % self.num_entries
        ct = out_ct.astype(tbl.dtype)     # (batch, T, d)
        if self._table_order is not None:
            # stored slot s holds logical table _table_order[s]
            idx = jnp.take(idx, self._table_order, axis=1)
            ct = jnp.take(ct, self._table_order, axis=1)
        if self.aggr == AGGR_MODE_AVG:
            ct = ct / idx.shape[-1]
        r, d = self._pack, self.out_dim
        T, rows = self.num_tables, self.num_entries

        plan = _row_plan(self)
        if plan is not None and idx.size % plan.ndev == 0:
            from ..parallel.alltoall import row_sharded_sgd_update
            offs = (jnp.arange(T, dtype=jnp.int32) * rows)[None, :, None]
            owner, local, gid, hot_id = self._row_route(
                (idx + offs).reshape(-1))
            upd = jnp.broadcast_to(
                ct[..., None, :], idx.shape + (d,)).reshape(-1, d)
            spec, _ = self._row_spec_block()
            out = row_sharded_sgd_update(
                plan, tbl, spec, owner, local, upd, lr, d, gid=gid,
                hot_table=params.get("hot_kernel"), hot_id=hot_id)
            if hot_id is None:
                return {"kernel": out}
            new, new_hot = out
            return {"kernel": new, "hot_kernel": new_hot}

        if fwd is not None and self._fwd_residual_ok():
            # write-only path: fwd tiles + summed deltas -> pure scatter
            # writes (apply_with_fwd produced g in the SAME permuted
            # (batch, T, bag) order as idx/ct here)
            from .pallas.embedding_kernel import scatter_write_rows_packed
            g_flat, tiles = fwd
            upd = jnp.broadcast_to(
                ct[..., None, :], idx.shape + (d,)).reshape(-1, d)
            new = scatter_write_rows_packed(
                tbl.reshape(T * rows // r, r * d), g_flat, -lr * upd,
                tiles, d)
            return {"kernel": new.reshape(tbl.shape)}

        shard_axes = _row_shard_axes(self, d, T * rows // r)
        if shard_axes is not None:
            # multi-chip: table-dim-sharded packed view; every shard masks
            # the global updates to its row block and runs the local RMW
            # kernel under shard_map
            from .pallas.embedding_kernel import sharded_scatter_add_packed
            offs = (jnp.arange(T, dtype=jnp.int32) * rows)[None, :, None]
            gidx = (idx + offs).reshape(-1)
            upd = jnp.broadcast_to(
                ct[..., None, :], idx.shape + (d,)).reshape(-1, d)
            new = sharded_scatter_add_packed(
                self.model.mesh, shard_axes,
                tbl.reshape(T * rows // r, r * d), gidx, -lr * upd, d)
            return {"kernel": new.reshape(tbl.shape)}
        if _pallas_scatter_ok(self.model, d if r == 1 else 128, self.name):
            # one fused scatter over the packed (T*rows/r, 128|r*d) view;
            # global unpacked row g = t*rows + ix keeps g//r, g%r aligned
            # with the per-table packing because rows % r == 0
            from .pallas.embedding_kernel import (scatter_add_rows,
                                                  scatter_add_rows_packed)
            offs = (jnp.arange(T, dtype=jnp.int32) * rows)[None, :, None]
            gidx = (idx + offs).reshape(-1)
            upd = jnp.broadcast_to(
                ct[..., None, :], idx.shape + (d,)).reshape(-1, d)
            view = tbl.reshape(T * rows // r, r * d)
            if r == 1:
                new = scatter_add_rows(view, gidx, -lr * upd)
            else:
                new = scatter_add_rows_packed(view, gidx, -lr * upd, d)
            return {"kernel": new.reshape(tbl.shape)}

        def one_table(t, ix, c):   # (rows/r, r*d), (batch,bag), (batch,d)
            upd = jnp.broadcast_to(c[:, None, :], ix.shape + (d,))
            tu = t.reshape(rows, d)
            tu = tu.at[ix.reshape(-1)].add(-lr * upd.reshape(-1, d))
            return tu.reshape(t.shape)

        new = jax.vmap(one_table, in_axes=(0, 1, 1))(tbl, idx, ct)
        return {"kernel": new}

    def sparse_opt_update(self, params, xs, out_ct, opt, slabs, step,
                          fwd=None):
        """Stateful touched-rows update (lazy momentum / Adam) on the
        fused stacked tables; see Embedding.sparse_opt_update."""
        (idx,) = xs                       # (batch, T, bag)
        tbl = params["kernel"]            # (T, rows/r, r*d)
        idx = idx.astype(jnp.int32) % self.num_entries
        ct = out_ct.astype(jnp.float32)   # (batch, T, d)
        if self._table_order is not None:
            idx = jnp.take(idx, self._table_order, axis=1)
            ct = jnp.take(ct, self._table_order, axis=1)
        if self.aggr == AGGR_MODE_AVG:
            ct = ct / idx.shape[-1]
        d = self.out_dim
        T, rows = self.num_tables, self.num_entries
        offs = (jnp.arange(T, dtype=jnp.int32) * rows)[None, :, None]
        g = (idx + offs).reshape(-1)
        upd = jnp.broadcast_to(ct[..., None, :],
                               idx.shape + (d,)).reshape(-1, d)
        fwd_tiles = fwd[1] if fwd is not None else None
        kslabs, hslabs, nested = _norm_slabs(slabs)
        out = _sparse_opt_update(self, tbl, g, upd, opt, kslabs,
                                 step, T * rows, fwd_tiles,
                                 hot_tbl=params.get("hot_kernel"),
                                 hot_slabs=hslabs)
        return _finish_opt_update(out, nested)

    # ---- delta publication (utils/delta.py; see Embedding) -------------
    def delta_touched_rows(self, idx_np) -> "np.ndarray":
        # stored kernel (T, rows/r, r*d) flattens to (T*rows/r, r*d);
        # logical table t lives at stored slot _table_inv[t], logical row
        # ix at packed row ix // r of that slot
        import numpy as np
        r, rows = self._pack, self.num_entries
        g = np.asarray(idx_np).astype(np.int64) % rows    # (batch, T, bag)
        slot = np.arange(self.num_tables, dtype=np.int64)
        if self._table_inv is not None:
            slot = np.asarray(self._table_inv, dtype=np.int64)
        H = getattr(self, "_hot_rows", 0)
        if H > 0:
            # hybrid: "kernel" stores only the cold tail; the (small)
            # replicated hot block stays untracked — diffed whole
            flat = slot[None, :, None] * ((rows - H) // r) + (g - H) // r
            return np.unique(flat.reshape(-1)[g.reshape(-1) >= H])
        flat = slot[None, :, None] * (rows // r) + g // r
        return np.unique(flat.reshape(-1))

    def flat_lookup_ids(self, idx_np) -> "np.ndarray":
        """Batch indices -> flat t*rows + ix lookup ids, for the
        id-frequency sketch collected at staging."""
        import numpy as np
        rows = self.num_entries
        g = np.asarray(idx_np).astype(np.int64) % rows
        offs = (np.arange(self.num_tables, dtype=np.int64)
                * rows)[None, :, None]
        return (g + offs).reshape(-1)

    def host_delta_touched_rows(self, idx_np) -> "np.ndarray":
        # host table is (T, rows, d) in LOGICAL table order, unpacked
        import numpy as np
        rows = self.num_entries
        g = np.asarray(idx_np).astype(np.int64) % rows
        offs = (np.arange(self.num_tables, dtype=np.int64)
                * rows)[None, :, None]
        return np.unique((g + offs).reshape(-1))

    # ---- host-resident table form (reference embedding_avx2.cc) --------
    def host_init(self, seed: int):
        return {"kernel": _host_init_table(
            self.kernel_initializer,
            (self.num_tables, self.num_entries, self.out_dim), seed)}

    def host_flat_indices(self, idx_np):
        """Per-sample FLAT row ids, (batch, T, bag), into the (T*rows, d)
        flattened host table — shared with the serving shard tier."""
        import numpy as np
        rows = self.num_entries
        offs = (np.arange(self.num_tables, dtype=np.int64)
                * rows)[None, :, None]
        return idx_np.astype(np.int64) % rows + offs      # (batch, T, bag)

    def host_lookup_rows(self, rows_2d, g3):
        """See :meth:`Embedding.host_lookup_rows`."""
        return _host_bag_lookup(rows_2d, g3, self.aggr)

    def host_lookup(self, host_params, idx_np):
        T, rows, d = host_params["kernel"].shape
        return self.host_lookup_rows(
            host_params["kernel"].reshape(T * rows, d),
            self.host_flat_indices(idx_np))

    def host_sgd_update(self, host_params, idx_np, ct_np, lr):
        import numpy as np
        T, rows, d = host_params["kernel"].shape
        offs = (np.arange(T, dtype=np.int64) * rows)[None, :, None]
        g = idx_np.astype(np.int64) % rows + offs
        _host_bag_update(host_params["kernel"].reshape(T * rows, d), g,
                         ct_np, lr, self.aggr)

    def host_opt_update(self, host_params, idx_np, ct_np, opt, slabs,
                        step):
        import numpy as np
        T, rows, d = host_params["kernel"].shape
        offs = (np.arange(T, dtype=np.int64) * rows)[None, :, None]
        g = idx_np.astype(np.int64) % rows + offs
        _host_stateful_update(
            host_params["kernel"].reshape(T * rows, d), g, ct_np, opt,
            {k: v.reshape(T * rows, d) for k, v in slabs.items()},
            step, self.aggr)


class EmbeddingBagConcat(Op):
    """N embedding bags with a SHARED width but DIFFERENT row counts,
    concatenated row-wise into one (sum_rows_padded, dim) parameter; each
    lookup adds its table's row offset. This is the non-uniform-table form
    of EmbeddingBagStacked and the natural TPU layout for Criteo-Kaggle's
    26 tables (4 … 3.1M rows × 16-d, run_criteo_kaggle.sh): the reference
    places each table whole on one device (dlrm_strategy.cc:252-256); here
    the concatenated rows are block-sharded over the mesh, all 26 gathers
    fuse into ONE gather and the sparse update into ONE scatter.

    input: int (batch, num_tables, bag)  ->  output (batch, num_tables, dim)
    """

    type_name = "EmbedConcat"

    # the table-dim degree is intent ("row-shard the concatenated table"),
    # not an output partitioning — _effective_pc clamping it is expected
    raw_degree_semantics = True

    # row padding so the concatenated row count divides any power-of-two
    # mesh (and most mixed meshes)
    _ROW_PAD = 8192

    def __init__(self, model, input_tensor, table_sizes, out_dim: int,
                 aggr: str = AGGR_MODE_SUM, kernel_initializer=None,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        assert input_tensor.num_dims == 3, "expect (batch, num_tables, bag)"
        self.table_sizes = tuple(int(s) for s in table_sizes)
        self.num_tables = len(self.table_sizes)
        assert input_tensor.shape[1] == self.num_tables
        self.out_dim = int(out_dim)
        self.aggr = aggr
        self.kernel_initializer = kernel_initializer or GlorotUniform()
        total = sum(self.table_sizes)
        self.total_rows = -(-total // self._ROW_PAD) * self._ROW_PAD
        offs = [0]
        for s in self.table_sizes[:-1]:
            offs.append(offs[-1] + s)
        self._offsets = tuple(offs)
        # lane packing (see EmbeddingBagStacked): total_rows is a power-of-
        # two multiple of any pack factor, so narrow rows always pack
        self._pack = _pack_factor(self.out_dim, self.total_rows)
        batch = input_tensor.shape[0]
        self.outputs = [self._make_output(
            (batch, self.num_tables, self.out_dim))]

    def set_device_groups(self, dev_of):
        """Group the concatenated tables by their strategy device: row
        block k holds exactly the tables the strategy places on the k-th
        named device, each block padded to one common size, so GSPMD's
        equal-block row sharding lands every table WHOLE on its intended
        device — the reference's per-table round-robin placement
        (dlrm_strategy.cc:242-296, mapper.cc:33-97) with UNEVEN table
        counts per device. Must be called before init_params (compile-time
        strategy resolution does)."""
        assert len(dev_of) == self.num_tables
        devs = sorted(set(dev_of))
        groups = [[i for i, dg in enumerate(dev_of) if dg == g]
                  for g in devs]
        block = max(sum(self.table_sizes[i] for i in grp)
                    for grp in groups)
        block = -(-block // self._ROW_PAD) * self._ROW_PAD
        offs = [0] * self.num_tables
        for k, grp in enumerate(groups):
            off = k * block
            for i in grp:
                offs[i] = off
                off += self.table_sizes[i]
        self._offsets = tuple(offs)
        self.total_rows = block * len(groups)
        self._pack = _pack_factor(self.out_dim, self.total_rows)
        self._device_groups = tuple(devs)

    def param_defs(self):
        r = self._pack
        return {"kernel": ParamDef(
            (self.total_rows // r, self.out_dim * r), jnp.float32,
            self.kernel_initializer)}

    def init_params(self, key):
        # per-table init at each table's LOGICAL (rows_t, d) shape:
        # one Glorot over the fused multi-million-row shape would collapse
        # small tables' scale to ~0 versus the unfused per-table ops.
        # Tables land at their _offsets (sequential by default; grouped by
        # device under set_device_groups), pad rows stay zero.
        keys = jax.random.split(key, self.num_tables)
        logical = jnp.zeros((self.total_rows, self.out_dim), jnp.float32)
        for i, rows in enumerate(self.table_sizes):
            part = self.kernel_initializer(
                keys[i], (rows, self.out_dim), jnp.float32)
            logical = jax.lax.dynamic_update_slice(
                logical, part, (self._offsets[i], 0))
        return {"kernel": self.pack_kernel(logical)}

    def unpack_kernel(self, kernel):
        """(total_rows/r, r*d) stored form -> logical (total_rows, d)."""
        return kernel.reshape(self.total_rows, self.out_dim)

    def pack_kernel(self, logical):
        r = self._pack
        return logical.reshape(self.total_rows // r, self.out_dim * r)

    def _global_indices(self, idx):
        """Per-table modulo (wrap semantics like the gathers above) then
        offset into the concatenated rows."""
        sizes = jnp.asarray(self.table_sizes, jnp.int32)[None, :, None]
        offs = jnp.asarray(self._offsets, jnp.int32)[None, :, None]
        return idx.astype(jnp.int32) % sizes + offs       # (batch, T, bag)

    # ---- row/PARAM-axis sharding hooks (see configure_row_shard) -------
    def _row_shard_geometry(self):
        return self.total_rows, self._pack, 1

    def _row_route(self, g):
        """Concatenated global rows -> (owner, local, gid, hot_id).
        The dedup'd exchange keys on the concatenated row id; the
        hot/cold hybrid does NOT apply here (non-uniform tables have no
        per-table hot split — row_shard_structural_reason says so)."""
        plan = self._row_plan
        rl = plan.rows_local
        return ((g // rl).astype(jnp.int32),
                (g % rl).astype(jnp.int32),
                g.astype(jnp.int32), None)

    def _row_spec_block(self):
        from jax.sharding import PartitionSpec
        plan = self._row_plan
        r = self._pack
        return (PartitionSpec(plan.row_axes, None),
                (self.total_rows // r // plan.nshards, self.out_dim * r))

    def apply(self, params, xs, *, training=False, rng=None):
        (idx,) = xs                        # (batch, T, bag)
        tbl = params["kernel"]             # (total_rows/r, r*d)
        g = self._global_indices(idx)
        batch, T, bag = g.shape
        r, d = self._pack, self.out_dim
        plan = _row_plan(self)
        if plan is not None and batch % plan.ndev == 0:
            from ..parallel.alltoall import row_sharded_bag_lookup
            owner, local, gid, _hot = self._row_route(g)
            spec, block = self._row_spec_block()
            return [row_sharded_bag_lookup(plan, tbl, spec, owner,
                                           local, d, self.aggr, block,
                                           gid=gid)]
        if (self.aggr in (AGGR_MODE_SUM, AGGR_MODE_AVG) and r == 1
                and _pallas_ok(self.model, self.out_dim, self.name)):
            # one Pallas row-stream over the concatenated table; per-table
            # bags become the kernel's bag dim via (batch*T, bag) indices
            from .pallas.embedding_kernel import embedding_bag
            out = embedding_bag(tbl, g.reshape(batch * T, bag), self.aggr)
            return [out.reshape(batch, T, self.out_dim)]
        if r == 1:
            rows = jnp.take(tbl, g.reshape(-1), axis=0,
                            mode="wrap").reshape(g.shape + (d,))
        else:
            rows = _packed_gather(tbl, g, r, d)   # (batch, T, bag, d)
        if self.aggr == AGGR_MODE_AVG:
            return [jnp.mean(rows, axis=2)]
        return [jnp.sum(rows, axis=2)]     # (batch, T, d)

    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        # same divisibility filter as EmbeddingBagStacked so the degrees
        # the search costs are the degrees compile() executes (the clamp in
        # _effective_pc would otherwise silently rewrite them)
        out = []
        for ds in feasible_degrees:
            for dt in feasible_degrees:
                if ds * dt <= num_devices and self.num_tables % max(dt, 1) == 0:
                    out.append(ParallelConfig((ds, dt, 1)))
        out.extend(_row_shard_candidates(self, num_devices,
                                         feasible_degrees, 3))
        out.append(_zcm_candidate(3))
        return out

    def output_axes(self, pc: ParallelConfig, assigner, raw_pc=None):
        # Under table parallelism (RAW degrees[1] > 1 — same trigger as
        # param_axes, surviving the output-shape clamp) the PARAM is
        # row-block sharded over the whole mesh; the fused gather's
        # natural output layout is then batch-sharded over the whole
        # mesh, matching the data-parallel consumers. Constraining the T
        # dim instead (the positional reading of the degrees) forces
        # GSPMD into a full rematerialization per step.
        raw = raw_pc or pc
        if len(raw.degrees) > 1 and raw.degrees[1] > 1:
            batch = self.outputs[0].shape[0]
            full = assigner.mesh.size
            if batch % full == 0:
                return [tuple(assigner.axis_names), (), ()]
        return assigner.assign(pc.degrees)

    def param_axes(self, pc: ParallelConfig, out_axes,
                   raw_pc=None):
        # explicit PARAM-axis row sharding (all-to-all routed lookups)
        # takes precedence over the implicit GSPMD row-block sharding
        if _row_plan(self) is not None:
            return {"kernel": (self._row_plan.row_axes, ())}
        # table parallelism = row-block sharding of the concatenated rows.
        # Keyed off the RAW (unclamped) strategy degrees: the output's
        # table dim often can't split evenly (26 tables on 8 chips), but
        # the padded row count always can — and sharding the rows is the
        # memory-scaling point of placing tables across devices. GSPMD
        # inserts the gather/scatter collectives.
        raw = raw_pc or pc
        if len(raw.degrees) >= 2 and raw.degrees[1] > 1:
            rows_axes = tuple(self.model.mesh.axis_names)
        else:
            rows_axes = ()
        return {"kernel": (rows_axes, ())}

    def flops_per_sample(self) -> float:
        bag = self.inputs[0].shape[-1]
        return float(self.num_tables * bag * self.out_dim)

    def param_shard_shapes(self, pc: ParallelConfig, ndev=None):
        # any table parallelism row-shards the concatenated table over the
        # WHOLE mesh (param_axes), not just pc.num_parts; an explicit
        # PARAM-axis degree shards rows by exactly that many shards
        pd = max(getattr(pc, "param_degree", 1), 1)
        full = ndev or (self.model.mesh.size if self.model.mesh else 1)
        if pd > 1:
            dt = pd
        else:
            dt = full if (len(pc.degrees) > 1 and pc.degrees[1] > 1) else 1
        r = self._pack
        return {"kernel": (max(self.total_rows // r // max(dt, 1), 1),
                           self.out_dim * r)}


    def random_hbm_rows(self, backward: bool = False,
                        raw: bool = False) -> float:
        return _embedding_random_rows(self, backward, raw)

    def update_random_hbm_rows(self, pc=None) -> float:
        return _embedding_update_rows(self, pc)

    def alltoall_payload_bytes(self, ndev: int, itemsize: int, pc=None):
        return _a2a_payload_bytes(self, ndev, itemsize, pc=pc)

    def param_bytes_touched_per_step(self, num_parts: int = 1) -> int:
        if not _sparse_update_active(self):
            return self.param_bytes()
        batch, _, bag = self.inputs[0].shape
        return int(_touched_bytes_factor(self) * batch * self.num_tables
                   * bag * self.out_dim * 4 // max(num_parts, 1))

    # ---- sparse (touched-rows-only) SGD update (see Embedding) ---------
    def supports_sparse_update(self) -> bool:
        return self.aggr in (AGGR_MODE_SUM, AGGR_MODE_AVG)

    def _fwd_residual_ok(self) -> bool:
        """See EmbeddingBagStacked._fwd_residual_ok."""
        return (self._pack > 1
                and self.aggr in (AGGR_MODE_SUM, AGGR_MODE_AVG)
                and _row_plan(self) is None
                and not _pallas_ok(self.model, self.out_dim, self.name)
                and _pallas_scatter_ok(self.model, 128, self.name)
                and _row_shard_axes(self, self.out_dim,
                                    self.total_rows // self._pack) is None)

    def apply_with_fwd(self, params, xs, *, rng=None):
        """apply() plus forward-gather residuals for the write-only sparse
        update (see EmbeddingBagStacked.apply_with_fwd)."""
        if not self._fwd_residual_ok():
            return self.apply(params, xs, training=True, rng=rng), None
        (idx,) = xs
        tbl = params["kernel"]             # (total_rows/r, r*d)
        g = self._global_indices(idx)      # (batch, T, bag) unpacked rows
        r, d = self._pack, self.out_dim
        rows, _, tiles = _packed_gather_tiles(tbl, g, r, d)
        out = (jnp.mean(rows, axis=2) if self.aggr == AGGR_MODE_AVG
               else jnp.sum(rows, axis=2))
        return [out], (g.reshape(-1), tiles)

    def sparse_sgd_update(self, params, xs, out_ct, lr,
                          fwd=None):
        (idx,) = xs                        # (batch, T, bag)
        tbl = params["kernel"]             # (total_rows, d)
        g = self._global_indices(idx)
        ct = out_ct.astype(tbl.dtype)      # (batch, T, d)
        if self.aggr == AGGR_MODE_AVG:
            ct = ct / g.shape[-1]
        r, d = self._pack, self.out_dim
        upd = jnp.broadcast_to(ct[..., None, :], g.shape + (d,))
        upd = upd.reshape(-1, d)
        plan = _row_plan(self)
        if plan is not None and g.size % plan.ndev == 0:
            from ..parallel.alltoall import row_sharded_sgd_update
            owner, local, gid, _hot = self._row_route(g.reshape(-1))
            spec, _ = self._row_spec_block()
            new = row_sharded_sgd_update(plan, tbl, spec, owner, local,
                                         upd, lr, d, gid=gid)
            return {"kernel": new}
        if fwd is not None and self._fwd_residual_ok():
            from .pallas.embedding_kernel import scatter_write_rows_packed
            g_flat, tiles = fwd
            new = scatter_write_rows_packed(tbl, g_flat, -lr * upd,
                                            tiles, d)
            return {"kernel": new}
        shard_axes = _row_shard_axes(self, d, self.total_rows // r)
        if shard_axes is not None:
            from .pallas.embedding_kernel import sharded_scatter_add_packed
            new = sharded_scatter_add_packed(
                self.model.mesh, shard_axes, tbl, g.reshape(-1),
                -lr * upd, d)
        elif _pallas_scatter_ok(self.model, d if r == 1 else 128, self.name):
            from .pallas.embedding_kernel import (scatter_add_rows,
                                                  scatter_add_rows_packed)
            if r == 1:
                new = scatter_add_rows(tbl, g.reshape(-1), -lr * upd)
            else:
                new = scatter_add_rows_packed(tbl, g.reshape(-1),
                                              -lr * upd, d)
        elif r == 1:
            new = tbl.at[g.reshape(-1)].add(-lr * upd)
        else:
            new = self.pack_kernel(
                self.unpack_kernel(tbl).at[g.reshape(-1)].add(-lr * upd))
        return {"kernel": new}

    def sparse_opt_update(self, params, xs, out_ct, opt, slabs, step,
                          fwd=None):
        """Stateful touched-rows update (lazy momentum / Adam) on the
        concatenated non-uniform tables; see Embedding.sparse_opt_update."""
        (idx,) = xs                        # (batch, T, bag)
        tbl = params["kernel"]             # (total_rows/r, r*d)
        g = self._global_indices(idx)
        ct = out_ct.astype(jnp.float32)    # (batch, T, d)
        if self.aggr == AGGR_MODE_AVG:
            ct = ct / g.shape[-1]
        d = self.out_dim
        upd = jnp.broadcast_to(ct[..., None, :],
                               g.shape + (d,)).reshape(-1, d)
        fwd_tiles = fwd[1] if fwd is not None else None
        kslabs, _hslabs, nested = _norm_slabs(slabs)
        out = _sparse_opt_update(self, tbl, g.reshape(-1), upd,
                                 opt, kslabs, step,
                                 self.total_rows, fwd_tiles)
        return _finish_opt_update(out, nested)

    # ---- host-resident table form (reference embedding_avx2.cc) --------
    def host_init(self, seed: int):
        import numpy as np
        logical = np.zeros((self.total_rows, self.out_dim), np.float32)
        for i, rows in enumerate(self.table_sizes):
            logical[self._offsets[i]:self._offsets[i] + rows] = \
                _host_init_table(self.kernel_initializer,
                                 (rows, self.out_dim), seed + i)
        return {"kernel": logical}

    def _host_global_indices(self, idx_np):
        import numpy as np
        sizes = np.asarray(self.table_sizes, np.int64)[None, :, None]
        offs = np.asarray(self._offsets, np.int64)[None, :, None]
        return idx_np.astype(np.int64) % sizes + offs     # (batch, T, bag)

    def host_flat_indices(self, idx_np):
        """Per-sample FLAT row ids, (batch, T, bag), into the
        (total_rows, d) concatenated host table — shared with the
        serving shard tier."""
        return self._host_global_indices(idx_np)

    def host_lookup_rows(self, rows_2d, g3):
        """See :meth:`Embedding.host_lookup_rows`."""
        return _host_bag_lookup(rows_2d, g3, self.aggr)

    def host_lookup(self, host_params, idx_np):
        return self.host_lookup_rows(host_params["kernel"],
                                     self.host_flat_indices(idx_np))

    def host_sgd_update(self, host_params, idx_np, ct_np, lr):
        _host_bag_update(host_params["kernel"],
                         self._host_global_indices(idx_np), ct_np, lr,
                         self.aggr)

    def host_opt_update(self, host_params, idx_np, ct_np, opt, slabs,
                        step):
        _host_stateful_update(host_params["kernel"],
                              self._host_global_indices(idx_np), ct_np,
                              opt, slabs, step, self.aggr)

    # ---- delta publication (utils/delta.py; see Embedding) -------------
    def delta_touched_rows(self, idx_np) -> "np.ndarray":
        # stored kernel is (total_rows/r, r*d): concatenated global rows,
        # r logical rows per packed row
        import numpy as np
        g = self._host_global_indices(idx_np)
        return np.unique(g.reshape(-1) // self._pack)

    def host_delta_touched_rows(self, idx_np) -> "np.ndarray":
        # host table is the unpacked (total_rows, d) concatenation
        import numpy as np
        return np.unique(self._host_global_indices(idx_np).reshape(-1))

    def flat_lookup_ids(self, idx_np) -> "np.ndarray":
        """Batch indices -> concatenated global rows, for the
        id-frequency sketch collected at staging."""
        return self._host_global_indices(idx_np).reshape(-1)

