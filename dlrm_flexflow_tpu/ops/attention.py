"""Multi-head attention with sequence/context parallelism.

The reference has NO attention ops and no intra-op sequence parallelism
(reference survey §5.7: nmt/rnn.h:23,58-63 only statically partitions the
LSTM grid). This op is the designed-in TPU upgrade: long-context scaling via

- **ring attention** (seq-dim sharding, degrees[1] > 1): each device keeps
  its Q block and passes K/V blocks around the ICI ring with
  `lax.ppermute` under `shard_map`, accumulating with an online-softmax
  (flash-style, fp32 running max/sum) — seq length scales linearly with
  devices, memory per device stays O(seq/p).
- **head parallelism** (model-dim sharding, degrees[2] > 1): QKV/output
  projections column/row-sharded Megatron-style; GSPMD inserts the psum.
- plain DP (degrees[0]) composes with both.

Self-attention: pass the same tensor as q, k, v.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.initializers import DEFAULT_KERNEL_INIT, ZeroInitializer
from ..core.op import Op, ParamDef
from ..parallel.pconfig import ParallelConfig


def _online_softmax_block(q, k, v, m_prev, num_prev, den_prev, mask):
    """One K/V block of flash-style attention. q:(b,h,sq,hd) k/v:(b,h,sk,hd);
    m/num/den are fp32 running stats. mask:(sq,sk) additive (0 or -inf)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = s * (1.0 / math.sqrt(q.shape[-1])) + mask
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (m == -inf): exp(-inf - -inf) -> use 0
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    scale = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0)
    num = num_prev * scale[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    den = den_prev * scale + jnp.sum(p, axis=-1)
    return m_new, num, den


def _attention_local(q, k, v, causal, q_offset=0, k_offset=0):
    """Dense attention on local blocks (single shard or within-block)."""
    b, h, sq, hd = q.shape
    sk = k.shape[2]
    if causal:
        qpos = q_offset + jnp.arange(sq)[:, None]
        kpos = k_offset + jnp.arange(sk)[None, :]
        mask = jnp.where(kpos <= qpos, 0.0, -jnp.inf).astype(jnp.float32)
    else:
        mask = jnp.zeros((sq, sk), jnp.float32)
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    num0 = jnp.zeros((b, h, sq, hd), jnp.float32)
    den0 = jnp.zeros((b, h, sq), jnp.float32)
    m, num, den = _online_softmax_block(q, k, v, m0, num0, den0, mask)
    return num / jnp.maximum(den, 1e-20)[..., None]


def _flash_gate(model, op_name, q, k) -> bool:
    """Route single-chip TPU attention through jax's shipped Pallas
    flash-attention kernel (jax.experimental.pallas.ops.tpu): O(seq)
    memory instead of the O(seq²) scores _attention_local materializes —
    seq 8192 @ d1024/h16 OOMs 16 GB of HBM without it. Shares the common
    Pallas routing policy (TPU backend, opt-in, single chip, not
    host-offloaded — a Mosaic call can't run under compute_on) and adds
    the shapes/dtypes validated on hardware (bf16, head_dim %64,
    seq %512)."""
    from .embedding import _pallas_gate
    if not _pallas_gate(model, op_name, True):
        return False
    hd, sq, sk = q.shape[3], q.shape[2], k.shape[2]
    if not (q.dtype == jnp.bfloat16 and hd % 64 == 0
            and sq % 512 == 0 and sk % 512 == 0):
        return False
    # measured on v5e: XLA's fused dense attention is FASTER while the
    # fp32 score tensor fits comfortably (377k vs 313k tok/s @ seq 2048);
    # flash wins only where the scores blow HBM (seq 8192 @ d1024/h16
    # OOMs dense, runs 108k tok/s with flash). Route by score footprint.
    b, h = q.shape[0], q.shape[1]
    score_bytes = 4.0 * b * h * sq * sk
    return score_bytes > 6e9


def ring_attention(q, k, v, axis_name: str, causal: bool):
    """Blockwise ring attention under shard_map: q/k/v are LOCAL blocks
    (b, h, s_local, hd); K/V rotate around `axis_name` via ppermute."""
    # lax.axis_size is absent on older jax; psum(1) folds to the same
    # static axis size at trace time
    p = (lax.axis_size(axis_name) if hasattr(lax, "axis_size")
         else int(lax.psum(1, axis_name)))
    idx = lax.axis_index(axis_name)
    b, h, sl, hd = q.shape

    m = jnp.full((b, h, sl), -jnp.inf, jnp.float32)
    num = jnp.zeros((b, h, sl, hd), jnp.float32)
    den = jnp.zeros((b, h, sl), jnp.float32)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def body(r, carry):
        m, num, den, kr, vr = carry
        # the K/V block currently held came from device (idx - r) mod p
        src = (idx - r) % p
        if causal:
            qpos = idx * sl + jnp.arange(sl)[:, None]
            kpos = src * sl + jnp.arange(sl)[None, :]
            mask = jnp.where(kpos <= qpos, 0.0, -jnp.inf).astype(jnp.float32)
        else:
            mask = jnp.zeros((sl, sl), jnp.float32)
        m, num, den = _online_softmax_block(q, kr, vr, m, num, den, mask)
        kr = lax.ppermute(kr, axis_name, perm)
        vr = lax.ppermute(vr, axis_name, perm)
        return m, num, den, kr, vr

    m, num, den, _, _ = lax.fori_loop(0, p, body, (m, num, den, k, v))
    return (num / jnp.maximum(den, 1e-20)[..., None]).astype(q.dtype)


class MultiHeadAttention(Op):
    type_name = "MultiHeadAttention"

    def __init__(self, model, q, k, v, embed_dim: int, num_heads: int,
                 causal: bool = False, name: Optional[str] = None):
        if q.num_dims != 3:
            raise ValueError("attention expects (batch, seq, dim) inputs")
        if embed_dim % num_heads != 0:
            raise ValueError("embed_dim must divide num_heads")
        inputs = [q] if (k is q and v is q) else [q, k, v]
        super().__init__(model, inputs, name)
        self.self_attention = len(inputs) == 1
        self.embed_dim = int(embed_dim)
        self.num_heads = int(num_heads)
        self.head_dim = self.embed_dim // self.num_heads
        self.causal = bool(causal)
        b, s, _ = q.shape
        self.outputs = [self._make_output((b, s, self.embed_dim))]

    def param_defs(self) -> Dict[str, ParamDef]:
        dq = self.inputs[0].shape[-1]
        dkv = self.inputs[-1].shape[-1]
        e = self.embed_dim
        init = DEFAULT_KERNEL_INIT()
        return {
            "wq": ParamDef((dq, e), jnp.float32, init),
            "wk": ParamDef((dkv, e), jnp.float32, init),
            "wv": ParamDef((dkv, e), jnp.float32, init),
            "wo": ParamDef((e, e), jnp.float32, init),
            "bo": ParamDef((e,), jnp.float32, ZeroInitializer()),
        }

    def _split_heads(self, x):
        b, s, _ = x.shape
        return x.reshape(b, s, self.num_heads, self.head_dim).transpose(
            0, 2, 1, 3)

    def apply(self, params, xs, *, training=False, rng=None):
        q_in = xs[0]
        k_in = xs[0] if self.self_attention else xs[1]
        v_in = xs[0] if self.self_attention else xs[2]
        cdt = self.model.compute_dtype
        pe = jnp.float32

        def proj(x, w):
            return jnp.einsum("bsd,de->bse", x.astype(cdt), w.astype(cdt),
                              preferred_element_type=pe).astype(cdt)

        q = self._split_heads(proj(q_in, params["wq"]))
        k = self._split_heads(proj(k_in, params["wk"]))
        v = self._split_heads(proj(v_in, params["wv"]))

        pc = getattr(self, "_compiled_pc", None)
        seq_axes = ()
        if pc is not None and len(pc.degrees) >= 2 and pc.degrees[1] > 1:
            seq_axes = getattr(self, "_seq_axes", ())

        if seq_axes:
            # ring attention over the seq-dim mesh axes
            mesh = self.model.mesh
            from jax.sharding import PartitionSpec as P
            axis = seq_axes if len(seq_axes) > 1 else seq_axes[0]
            spec = P(None, None, axis, None)
            fn = partial(ring_attention,
                         axis_name=seq_axes if len(seq_axes) > 1 else seq_axes[0],
                         causal=self.causal)
            from ..parallel.alltoall import _smap
            attn = _smap(fn, mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)
        elif _flash_gate(self.model, self.name, q, k):
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention)
            attn = flash_attention(
                q, k, v, causal=self.causal,
                sm_scale=1.0 / math.sqrt(self.head_dim)).astype(q.dtype)
        else:
            attn = _attention_local(q, k, v, self.causal).astype(q.dtype)

        b, h, s, hd = attn.shape
        merged = attn.transpose(0, 2, 1, 3).reshape(b, s, h * hd)
        out = jnp.einsum("bse,ef->bsf", merged.astype(cdt),
                         params["wo"].astype(cdt),
                         preferred_element_type=pe) + params["bo"]
        return [out.astype(q_in.dtype)]

    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        out = []
        b, s, _ = self.outputs[0].shape
        for ds in feasible_degrees:
            if ds <= num_devices:
                out.append(ParallelConfig((ds, 1, 1)))          # DP
        for dseq in feasible_degrees:
            if 1 < dseq <= num_devices and s % dseq == 0:
                out.append(ParallelConfig((1, dseq, 1)))        # ring SP
        for dh in feasible_degrees:
            if 1 < dh <= num_devices and self.num_heads % dh == 0:
                out.append(ParallelConfig((1, 1, dh)))          # head TP
        return out

    def param_axes(self, pc: ParallelConfig, out_axes,
                   raw_pc=None):
        ch = out_axes[2] if len(out_axes) >= 3 else ()
        # head TP: qkv projections column-sharded, wo row-sharded (psum by
        # GSPMD); bo replicated-ish (sharded on ch like bias)
        return {"wq": ((), ch), "wk": ((), ch), "wv": ((), ch),
                "wo": (ch, ()), "bo": ((),)}

    def param_shard_shapes(self, pc: ParallelConfig, ndev=None):
        dc = pc.degrees[2] if len(pc.degrees) > 2 else 1
        shapes = {n_: list(d.shape) for n_, d in self.param_defs().items()}
        if dc > 1:
            for n_ in ("wq", "wk", "wv"):
                shapes[n_][1] = max(shapes[n_][1] // dc, 1)
            shapes["wo"][0] = max(shapes["wo"][0] // dc, 1)
        return {n_: tuple(v) for n_, v in shapes.items()}

    def flops_per_sample(self) -> float:
        _, s, _ = self.outputs[0].shape
        e = self.embed_dim
        # per sample: 4 projections (2*s*e*e each) + QK^T and PV (2*s^2*e each)
        return 8.0 * s * e * e + 4.0 * s * s * e

    def mxu_utilization_factor(self) -> float:
        # measured (r4 sweep, b8 s2048 d1024 causal training): ~13% of
        # bf16 peak vs the gemm-calibrated 55% — flash attention pays
        # block-wise softmax rescaling/recomputation, the causal mask
        # discards half the score tiles' work, and small batch*heads
        # grids underfill the chip
        return 0.25
