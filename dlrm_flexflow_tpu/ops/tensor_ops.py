"""Shape/layout operators: Concat, Split, Flat, Reshape, Transpose, Reverse.

Parity with the reference ops (reference: src/ops/concat.cu 352 LoC,
split.cu 281, flat.cu 270, reshape.cu 291, transpose.cu 275, reverse.cu 257 —
all custom CUDA copy kernels). On TPU every one of these is a pure XLA
reshape/transpose/concatenate/rev that the compiler fuses into neighbors;
no hand-written kernels are warranted (they'd only add copies).
"""

from __future__ import annotations

import math
from typing import List, Optional

import jax.numpy as jnp

from ..core.op import Op
from ..parallel.pconfig import ParallelConfig


class Concat(Op):
    """Reference: src/ops/concat.cu — DLRM feature-interaction hot path."""

    type_name = "Concat"

    def __init__(self, model, inputs, axis: int, name: Optional[str] = None):
        super().__init__(model, inputs, name)
        nd = inputs[0].num_dims
        self.axis = axis % nd
        for t in inputs[1:]:
            if t.num_dims != nd:
                raise ValueError("concat rank mismatch")
            for d in range(nd):
                if d != self.axis and t.shape[d] != inputs[0].shape[d]:
                    raise ValueError(f"concat shape mismatch on dim {d}")
        out_shape = list(inputs[0].shape)
        out_shape[self.axis] = sum(t.shape[self.axis] for t in inputs)
        self.outputs = [self._make_output(out_shape, inputs[0].dtype)]
        # channel-concat of NHWC branches (Inception towers) stays NHWC:
        # logical axis 1 (C) is physical axis 3
        self._phys_axis = self.axis
        if (nd == 4 and self.axis == 1
                and all(t.physical == "nhwc" for t in inputs)):
            self.outputs[0].physical = "nhwc"
            self._accepts_nhwc_inputs = True
            self._phys_axis = 3

    def apply(self, params, xs, *, training=False, rng=None):
        return [jnp.concatenate(xs, axis=self._phys_axis)]


class Split(Op):
    """Reference: src/ops/split.cu — inverse of concat; sizes along axis."""

    type_name = "Split"

    def __init__(self, model, input_tensor, sizes: List[int], axis: int,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        nd = input_tensor.num_dims
        self.axis = axis % nd
        self.sizes = [int(s) for s in sizes]
        if sum(self.sizes) != input_tensor.shape[self.axis]:
            raise ValueError("split sizes must sum to the axis extent")
        self.outputs = []
        for i, s in enumerate(self.sizes):
            shape = list(input_tensor.shape)
            shape[self.axis] = s
            self.outputs.append(self._make_output(shape, input_tensor.dtype, i))

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        outs, off = [], 0
        for s in self.sizes:
            sl = [slice(None)] * x.ndim
            sl[self.axis] = slice(off, off + s)
            outs.append(x[tuple(sl)])
            off += s
        return outs


class Flat(Op):
    """Flatten all non-sample dims (reference: src/ops/flat.cu, 4D→2D)."""

    type_name = "Flat"

    def __init__(self, model, input_tensor, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        batch = input_tensor.shape[0]
        rest = int(math.prod(input_tensor.shape[1:]))
        self.outputs = [self._make_output((batch, rest), input_tensor.dtype)]

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        return [x.reshape(x.shape[0], -1)]


class Reshape(Op):
    """Reference: src/ops/reshape.cu — used 2↔3-D for the DLRM dot
    interaction. Total element count must match."""

    type_name = "Reshape"

    def __init__(self, model, input_tensor, shape, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        shape = tuple(int(s) for s in shape)
        if math.prod(shape) != math.prod(input_tensor.shape):
            raise ValueError(
                f"reshape {input_tensor.shape} -> {shape}: element count mismatch")
        self.outputs = [self._make_output(shape, input_tensor.dtype)]

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        shape = self.outputs[0].shape
        if (x.shape[0] != shape[0]
                and math.prod(x.shape[1:]) == math.prod(shape[1:])):
            # sample-dim polymorphism: the graph bakes the compile-time
            # batch into the target shape, but eval may trace at a
            # different (e.g. serving-bucket) batch. When the reshape
            # keeps the per-sample element count — it never mixes the
            # sample dim with features — re-deriving the target against
            # the traced batch is exact. Folding reshapes (NMT's
            # (b,s,h)->(b*s,h)) fail this guard and keep the baked
            # shape, erroring at trace time as before.
            shape = (x.shape[0],) + tuple(shape[1:])
        return [x.reshape(shape)]


class Transpose(Op):
    """Swap the innermost two dims (reference: src/ops/transpose.cu:140 —
    kernel flips the inner 2 dims; batch dims untouched)."""

    type_name = "Transpose"

    def __init__(self, model, input_tensor, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        if input_tensor.num_dims < 2:
            raise ValueError("transpose needs rank >= 2")
        shape = list(input_tensor.shape)
        shape[-1], shape[-2] = shape[-2], shape[-1]
        self.outputs = [self._make_output(shape, input_tensor.dtype)]

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        perm = list(range(x.ndim))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        return [jnp.transpose(x, perm)]


class IndexSelect(Op):
    """Static-index gather along one axis (torch.index_select semantics).

    No single reference op maps here; it implements the lower-triangle
    selection of the DLRM dot interaction that the reference left
    unimplemented (dlrm.cc:49-65 asserts on "dot") — the indices are static
    so XLA lowers this to a free gather fused with its consumer.
    """

    type_name = "IndexSelect"

    def __init__(self, model, input_tensor, indices, axis: int,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.axis = axis % input_tensor.num_dims
        self.indices = [int(i) for i in indices]
        ext = input_tensor.shape[self.axis]
        for i in self.indices:
            if not 0 <= i < ext:
                raise ValueError(f"index {i} out of range for dim {ext}")
        shape = list(input_tensor.shape)
        shape[self.axis] = len(self.indices)
        self.outputs = [self._make_output(shape, input_tensor.dtype)]

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        idx = jnp.asarray(self.indices, dtype=jnp.int32)
        return [jnp.take(x, idx, axis=self.axis)]


class Reverse(Op):
    """Reverse along one axis (reference: src/ops/reverse.cu — used by
    NMT-style models to reverse source sequences)."""

    type_name = "Reverse"

    def __init__(self, model, input_tensor, axis: int, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.axis = axis % input_tensor.num_dims
        self.outputs = [self._make_output(input_tensor.shape, input_tensor.dtype)]

    def apply(self, params, xs, *, training=False, rng=None):
        return [jnp.flip(xs[0], axis=self.axis)]
