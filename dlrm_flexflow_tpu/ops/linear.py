"""Linear (Dense) operator.

Parity with the reference Linear op (reference: src/ops/linear.cu, 1051 LoC):
cuBLAS sgemm + bias + fused activation, with 2-D sample×channel parallelism —
`num_par_c > 1` broadcasts the input via a replica tensor and reduce-sums
input gradients in a second backward task (linear.cu:188-293, 766-794).

TPU-native redesign: y = x @ W + b is `jnp.dot` on the MXU in the configured
compute dtype (bfloat16 by default — model-level setting). Channel
parallelism is expressed by sharding W's output dim and the activation's
channel dim on the same mesh axes; GSPMD inserts the input all-gather and
input-grad reduce-scatter that the replica tensor + BWD2 task hand-coded.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import jax.numpy as jnp

from ..core.initializers import DEFAULT_BIAS_INIT, DEFAULT_KERNEL_INIT
from ..core.op import Op, ParamDef
from ..parallel.pconfig import ParallelConfig
from .common import AC_MODE_NONE, apply_activation


class Linear(Op):
    type_name = "Dense"

    def __init__(self, model, input_tensor, out_dim: int,
                 activation=AC_MODE_NONE, use_bias: bool = True,
                 kernel_initializer=None, bias_initializer=None,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        if input_tensor.num_dims < 2:
            raise ValueError("Linear expects rank>=2 input (sample dim first)")
        self.in_dim = int(input_tensor.shape[-1])
        self.out_dim = int(out_dim)
        self.activation = activation
        self.use_bias = bool(use_bias)
        self.kernel_initializer = kernel_initializer or DEFAULT_KERNEL_INIT()
        self.bias_initializer = bias_initializer or DEFAULT_BIAS_INIT()
        out_shape = tuple(input_tensor.shape[:-1]) + (self.out_dim,)
        self.outputs = [self._make_output(out_shape)]

    def param_defs(self) -> Dict[str, ParamDef]:
        defs = {"kernel": ParamDef((self.in_dim, self.out_dim), jnp.float32,
                                   self.kernel_initializer)}
        if self.use_bias:
            defs["bias"] = ParamDef((self.out_dim,), jnp.float32,
                                    self.bias_initializer)
        return defs

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        cdt = self.model.compute_dtype
        y = jnp.dot(x.astype(cdt), params["kernel"].astype(cdt),
                    preferred_element_type=jnp.float32)
        if self.use_bias:
            y = y + params["bias"]
        return [apply_activation(y, self.activation).astype(x.dtype)]

    # -- parallelization ---------------------------------------------------
    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        """Sample × channel 2-D grid, mirroring Linear's search space
        (reference linear.cu + model.cc:295-324)."""
        out = []
        nd = self.outputs[0].num_dims
        for ds in feasible_degrees:
            for dc in feasible_degrees:
                if ds * dc <= num_devices:
                    degs = [1] * nd
                    degs[0] = ds
                    degs[-1] = dc
                    out.append(ParallelConfig(tuple(degs)))
        return out

    def param_shard_shapes(self, pc: ParallelConfig, ndev=None):
        # channel TP splits the kernel/bias out dim by the LAST degree
        # (candidate_parallel_configs/param_axes put channel TP there)
        dc = pc.degrees[-1] if len(pc.degrees) > 1 else 1
        shapes = {n: list(d.shape) for n, d in self.param_defs().items()}
        if dc > 1:
            for v in shapes.values():
                v[-1] = max(v[-1] // dc, 1)
        return {n: tuple(v) for n, v in shapes.items()}

    def param_axes(self, pc: ParallelConfig, out_axes,
                   raw_pc=None):
        # channel (last output dim) partition shards the kernel's out dim and
        # the bias *on the same mesh axes* as the activation's channel dim;
        # sample partition replicates weights (grad psum by GSPMD)
        ch = out_axes[-1] if len(out_axes) >= 2 else ()
        out = {"kernel": ((), ch)}
        if self.use_bias:
            out["bias"] = (ch,)
        return out

    def flops_per_sample(self) -> float:
        rows = math.prod(self.outputs[0].shape[1:-1]) if self.outputs[0].num_dims > 2 else 1
        return 2.0 * rows * self.in_dim * self.out_dim
