"""Shared op utilities: activation modes matching the reference enum
(reference: include/ffconst.h ActiMode used by Linear/Conv2D, applied fused
inside the cuDNN/cuBLAS kernels e.g. linear.cu:474-532). XLA fuses these
elementwise epilogues into the matmul/conv automatically — same effect,
compiler-driven."""

from __future__ import annotations

import jax
import jax.numpy as jnp

AC_MODE_NONE = "none"
AC_MODE_RELU = "relu"
AC_MODE_SIGMOID = "sigmoid"
AC_MODE_TANH = "tanh"
AC_MODE_ELU = "elu"

_ACTIVATIONS = {
    AC_MODE_NONE: lambda x: x,
    None: lambda x: x,
    AC_MODE_RELU: jax.nn.relu,
    AC_MODE_SIGMOID: jax.nn.sigmoid,
    AC_MODE_TANH: jnp.tanh,
    AC_MODE_ELU: jax.nn.elu,
}


def apply_activation(x, activation):
    if callable(activation):
        return activation(x)
    try:
        return _ACTIVATIONS[activation](x)
    except KeyError:
        raise ValueError(f"unknown activation {activation!r}") from None
