"""Conv2D, Pool2D, BatchNorm operators (NCHW API, matching the reference).

Parity with the reference ops (reference: src/ops/conv_2d.cu 1046 LoC —
cuDNN conv with auto-picked algorithm + fused ReLU; src/ops/pool_2d.cu 510 —
cuDNN pooling; src/ops/batch_norm.cu 565 — cuDNN BN training mode).

TPU-native redesign: `lax.conv_general_dilated` lowers to the MXU's native
convolution; algorithm picking is XLA's job (the cuDNN find-algorithm dance
at conv_2d.cu:217 has no TPU analog). BatchNorm is a fused
normalize-scale-shift in fp32 statistics; running stats are parameters
updated functionally (the train step threads them through like weights but
with direct assignment, not gradients).

Layout: the API is NCHW (reference parity) but the conv stack COMPUTES in
NHWC — the layout the TPU's vector units and XLA's conv emitter want
(channels on the 128-lane minor dim). Each op consumes its input in
whatever physical layout the producer declared (Tensor.physical) and
declares "nhwc" on its own outputs; layout-agnostic consumers ride along
and everything else transposes back to logical NCHW at the op boundary
(FFModel._forward_env). Disable with FFConfig.conv_nhwc=False / --no-nhwc.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.initializers import (ConstantInitializer, DEFAULT_BIAS_INIT,
                                 DEFAULT_KERNEL_INIT, ZeroInitializer)
from ..core.op import Op, ParamDef
from ..parallel.pconfig import ParallelConfig
from .common import AC_MODE_NONE, apply_activation

POOL_MAX = "max"
POOL_AVG = "avg"


def _nhwc_enabled(model) -> bool:
    return bool(getattr(model.config, "conv_nhwc", True))


def _to_nhwc(x, t):
    """Bring a concrete array for logical-NCHW tensor `t` into NHWC."""
    return x if t.physical == "nhwc" else jnp.transpose(x, (0, 2, 3, 1))


def _s2d_conv_nhwc(x, kernel, stride, padding, out_hw):
    """Space-to-depth lowering of a strided conv (the MLPerf ResNet stem
    reformulation): a k x k stride-s conv over C channels becomes a
    ceil(k/s) x ceil(k/s) stride-1 conv over C*s*s channels. A 3-channel
    224x224 stem fills 3/128 MXU lanes (~7% stem MFU measured,
    benchmarks/CONV_MFU_ANALYSIS.md); after the transform the stem
    carries C*s*s lanes and the conv's inner dim grows s*s-fold.

    Exact algebra: with explicit input padding, output pixel i reads
    input rows s*i+p (p < k). Writing p = p'*s + u, rows s*(i+p') + u
    are exactly space-to-depth block row i+p', sub-row u — so the
    original conv equals a stride-1 VALID conv over the s2d input with
    the kernel regrouped as [o, (u, v, c), p', q'] (kernel padded with
    zero taps to a multiple of s first).

    x: NHWC; kernel: OIHW; returns NHWC conv output of spatial out_hw.
    """
    n, h, w, c = x.shape
    o, _, kh, kw = kernel.shape
    sh, sw = stride
    ph, pw = padding
    oh, ow = out_hw
    kh_p = -(-kh // sh) * sh
    kw_p = -(-kw // sw) * sw
    # exact padded extent each spatial dim must provide: the last output
    # window starts at (o-1)*s and spans the zero-padded kernel
    h_need = (oh - 1) * sh + kh_p
    w_need = (ow - 1) * sw + kw_p
    x = jnp.pad(x, ((0, 0), (ph, max(h_need - h - ph, 0)),
                    (pw, max(w_need - w - pw, 0)), (0, 0)))
    x = x[:, :h_need, :w_need]         # crop rows no window reads
    # space-to-depth: channel index becomes (u*sw + v)*C + c
    x = x.reshape(n, h_need // sh, sh, w_need // sw, sw, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(
        n, h_need // sh, w_need // sw, sh * sw * c)
    # kernel: zero-pad taps to (kh_p, kw_p), regroup to match
    k = jnp.pad(kernel, ((0, 0), (0, 0), (0, kh_p - kh), (0, kw_p - kw)))
    k = k.reshape(o, c, kh_p // sh, sh, kw_p // sw, sw)
    k = jnp.transpose(k, (0, 3, 5, 1, 2, 4)).reshape(
        o, sh * sw * c, kh_p // sh, kw_p // sw)
    return lax.conv_general_dilated(
        x, k, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "OIHW", "NHWC"))


def _from_nhwc(x, t):
    """Bring an NHWC array back to tensor `t`'s declared physical form."""
    return x if t.physical == "nhwc" else jnp.transpose(x, (0, 3, 1, 2))


class Conv2D(Op):
    type_name = "Conv2D"

    def __init__(self, model, input_tensor, out_channels: int,
                 kernel_h: int, kernel_w: int, stride_h: int, stride_w: int,
                 padding_h: int, padding_w: int, activation=AC_MODE_NONE,
                 use_bias: bool = True, groups: int = 1,
                 kernel_initializer=None, bias_initializer=None,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        if input_tensor.num_dims != 4:
            raise ValueError("Conv2D expects NCHW rank-4 input")
        n, c, h, w = input_tensor.shape
        self.in_channels = c
        self.out_channels = int(out_channels)
        self.kernel = (int(kernel_h), int(kernel_w))
        self.stride = (int(stride_h), int(stride_w))
        self.padding = (int(padding_h), int(padding_w))
        self.activation = activation
        self.use_bias = bool(use_bias)
        self.groups = int(groups)
        self.kernel_initializer = kernel_initializer or DEFAULT_KERNEL_INIT()
        self.bias_initializer = bias_initializer or DEFAULT_BIAS_INIT()
        oh = (h + 2 * self.padding[0] - self.kernel[0]) // self.stride[0] + 1
        ow = (w + 2 * self.padding[1] - self.kernel[1]) // self.stride[1] + 1
        self.outputs = [self._make_output((n, self.out_channels, oh, ow))]
        if _nhwc_enabled(model):
            self.outputs[0].physical = "nhwc"
            self._accepts_nhwc_inputs = True

    def param_defs(self) -> Dict[str, ParamDef]:
        # OIHW kernel layout (cuDNN default, conv_2d.cu)
        defs = {"kernel": ParamDef(
            (self.out_channels, self.in_channels // self.groups,
             *self.kernel), jnp.float32, self.kernel_initializer)}
        if self.use_bias:
            defs["bias"] = ParamDef((self.out_channels,), jnp.float32,
                                    self.bias_initializer)
        return defs

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        cdt = self.model.compute_dtype
        pads = [(self.padding[0], self.padding[0]),
                (self.padding[1], self.padding[1])]
        # no preferred_element_type upcast: jax's conv transpose rule
        # rejects mixed dtypes (fp32 cotangent vs bf16 operands), so emit a
        # bf16-out conv (MXU still accumulates fp32 internally) and upcast
        if self.outputs[0].physical == "nhwc":
            if getattr(self, "_use_s2d", False):
                y = _s2d_conv_nhwc(
                    _to_nhwc(x, self.inputs[0]).astype(cdt),
                    params["kernel"].astype(cdt), self.stride,
                    self.padding,
                    self.outputs[0].shape[2:]).astype(jnp.float32)
            else:
                y = lax.conv_general_dilated(
                    _to_nhwc(x, self.inputs[0]).astype(cdt),
                    params["kernel"].astype(cdt),
                    window_strides=self.stride, padding=pads,
                    dimension_numbers=("NHWC", "OIHW", "NHWC"),
                    feature_group_count=self.groups).astype(jnp.float32)
            if self.use_bias:
                y = y + params["bias"]
        else:
            y = lax.conv_general_dilated(
                x.astype(cdt), params["kernel"].astype(cdt),
                window_strides=self.stride, padding=pads,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=self.groups).astype(jnp.float32)
            if self.use_bias:
                y = y + params["bias"][None, :, None, None]
        return [apply_activation(y, self.activation).astype(x.dtype)]

    def s2d_eligible(self) -> bool:
        """Space-to-depth pays when the conv is strided and its input
        channels underfill the 128 MXU lanes (stems: 3 channels). The
        transformed channel count must still be lane-friendly."""
        sh, sw = self.stride
        return (self.groups == 1
                and self.outputs[0].physical == "nhwc"
                and (sh > 1 or sw > 1)
                and self.in_channels <= 8
                and self.in_channels * sh * sw <= 128
                and self.kernel[0] >= sh and self.kernel[1] >= sw)

    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        """Sample DP plus attribute (h/w) splits — SOAP "A" parallelism
        (reference model.cc:502-526, 738-744 partitions conv over n/c/h/w)."""
        out = []
        n, c, h, w = self.outputs[0].shape
        for ds in feasible_degrees:
            if ds <= num_devices:
                out.append(ParallelConfig((ds, 1, 1, 1)))
        for dh in feasible_degrees:
            if 1 < dh <= num_devices and h % dh == 0:
                out.append(ParallelConfig((1, 1, dh, 1)))
        for ds in feasible_degrees:
            for dc in feasible_degrees:
                if ds * dc <= num_devices and 1 < dc and self.out_channels % dc == 0:
                    out.append(ParallelConfig((ds, dc, 1, 1)))
        return out

    def param_axes(self, pc: ParallelConfig, out_axes,
                   raw_pc=None):
        ch = out_axes[1] if len(out_axes) >= 2 else ()
        out = {"kernel": (ch, (), (), ())}
        if self.use_bias:
            out["bias"] = (ch,)
        return out

    def param_shard_shapes(self, pc: ParallelConfig, ndev=None):
        dc = pc.degrees[1] if len(pc.degrees) > 1 else 1
        shapes = {n_: list(d.shape) for n_, d in self.param_defs().items()}
        if dc > 1:
            shapes["kernel"][0] = max(shapes["kernel"][0] // dc, 1)
            if "bias" in shapes:
                shapes["bias"][0] = max(shapes["bias"][0] // dc, 1)
        return {n_: tuple(v) for n_, v in shapes.items()}

    def flops_per_sample(self) -> float:
        _, co, oh, ow = self.outputs[0].shape
        kh, kw = self.kernel
        return 2.0 * co * oh * ow * (self.in_channels // self.groups) * kh * kw

    def mxu_utilization_factor(self) -> float:
        # measured (r4 sweep, re-fit r5 with the per-step-floor model):
        # ResNet-18 b128 sustains ~78% of bf16 peak end-to-end vs the
        # gemm-calibrated 55% — XLA's conv emitter tiles large spatial
        # convs onto the MXU better than the global constant assumes
        return 1.42


def measure_s2d_wins(op, iters: int = 24) -> bool:
    """Time one fwd+bwd of `op` under both lowerings on the attached
    device and return True when space-to-depth is faster — the TPU analog
    of the reference's cudnnFindConvolutionForwardAlgorithm pick
    (conv_2d.cu:217): decided by measurement on the real machine, once,
    at init. The timed graph scans applications with a data dependence
    (XLA cannot hoist the conv) and consumes the gradients; the cost is
    the MARGINAL time between a long and a short scan, which cancels
    the dispatch roundtrip (~100 ms on a tunneled chip — larger than
    the op being measured)."""
    import time

    import numpy as np

    t_in = op.inputs[0]
    n, c, h, w = t_in.shape
    shape = (n, h, w, c) if t_in.physical == "nhwc" else (n, c, h, w)
    rng = np.random.RandomState(0)
    cdt = op.model.compute_dtype
    x = jnp.asarray(rng.rand(*shape).astype(np.float32)).astype(cdt)
    params = {k: jnp.asarray(rng.rand(*d.shape).astype(np.float32))
              for k, d in op.param_defs().items()}

    def timed(use_s2d: bool) -> float:
        old = getattr(op, "_use_s2d", False)
        op._use_s2d = use_s2d
        try:
            def make(length):
                @jax.jit
                def f(p, xx):
                    def body(acc, _):
                        xb = xx + (acc * 1e-38).astype(xx.dtype)

                        def loss(pp, xi):
                            out = op.apply(pp, [xi], training=True)[0]
                            return jnp.sum(out.astype(jnp.float32))

                        l, (gp, gx) = jax.value_and_grad(
                            loss, argnums=(0, 1))(p, xb)
                        consume = sum(
                            jnp.sum(g).astype(jnp.float32) * 1e-30
                            for g in jax.tree.leaves(gp))
                        consume += jnp.sum(gx).astype(jnp.float32) * 1e-30
                        return acc + l + consume, None

                    acc, _ = lax.scan(body, jnp.float32(0.0), None,
                                      length=length)
                    return acc
                return f

            short, long_ = make(2), make(2 + iters)

            def best(f):
                float(f(params, x))        # compile + true wait
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    float(f(params, x))    # dependent readback
                    ts.append(time.perf_counter() - t0)
                return min(ts)

            return (best(long_) - best(short)) / iters
        finally:
            op._use_s2d = old

    return timed(True) < timed(False)


class Pool2D(Op):
    type_name = "Pool2D"

    def __init__(self, model, input_tensor, kernel_h, kernel_w, stride_h,
                 stride_w, padding_h, padding_w, pool_type: str = POOL_MAX,
                 activation=AC_MODE_NONE, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        n, c, h, w = input_tensor.shape
        self.kernel = (int(kernel_h), int(kernel_w))
        self.stride = (int(stride_h), int(stride_w))
        self.padding = (int(padding_h), int(padding_w))
        self.pool_type = pool_type
        self.activation = activation
        oh = (h + 2 * self.padding[0] - self.kernel[0]) // self.stride[0] + 1
        ow = (w + 2 * self.padding[1] - self.kernel[1]) // self.stride[1] + 1
        self.outputs = [self._make_output((n, c, oh, ow))]
        if _nhwc_enabled(model):
            self.outputs[0].physical = "nhwc"
            self._accepts_nhwc_inputs = True

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        nhwc = self.outputs[0].physical == "nhwc"
        if nhwc:
            x = _to_nhwc(x, self.inputs[0])
            pads = [(0, 0),
                    (self.padding[0], self.padding[0]),
                    (self.padding[1], self.padding[1]), (0, 0)]
            dims = (1, *self.kernel, 1)
            strides = (1, *self.stride, 1)
        else:
            pads = [(0, 0), (0, 0),
                    (self.padding[0], self.padding[0]),
                    (self.padding[1], self.padding[1])]
            dims = (1, 1, *self.kernel)
            strides = (1, 1, *self.stride)
        if self.pool_type == POOL_MAX:
            init = -jnp.inf
            y = lax.reduce_window(x, init, lax.max, dims, strides, pads)
        else:
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
            if self.padding != (0, 0):
                # exclude padded positions from the divisor (reference uses
                # CUDNN_POOLING_AVERAGE_COUNT_EXCLUDE_PADDING, pool_2d.cu:190)
                counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                           dims, strides, pads)
                y = y / counts
            else:
                y = y / float(self.kernel[0] * self.kernel[1])
        return [apply_activation(y, self.activation)]


class BatchNorm(Op):
    """BatchNorm2D over NCHW (normalize per channel). `relu` flag matches the
    reference ctor (batch_norm.cu). Running stats are non-gradient state the
    train step updates in-place-functionally; eval mode uses them."""

    type_name = "BatchNorm"

    def hbm_io_factor(self) -> float:
        # fused into the producer's epilogue by XLA (see Op.hbm_io_factor)
        return 0.5
    momentum = 0.9
    eps = 1e-5

    def __init__(self, model, input_tensor, relu: bool = True,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.relu = bool(relu)
        self.channels = input_tensor.shape[1]
        self.outputs = [self._make_output(input_tensor.shape)]
        if _nhwc_enabled(model):
            self.outputs[0].physical = "nhwc"
            self._accepts_nhwc_inputs = True

    def param_defs(self):
        c = self.channels
        return {
            "scale": ParamDef((c,), jnp.float32, ConstantInitializer(1.0)),
            "bias": ParamDef((c,), jnp.float32, ZeroInitializer()),
        }

    # running stats: handled as op state (see model.py state threading)
    def state_defs(self):
        c = self.channels
        return {
            "running_mean": ParamDef((c,), jnp.float32, ZeroInitializer()),
            "running_var": ParamDef((c,), jnp.float32, ConstantInitializer(1.0)),
        }

    def apply_with_state(self, params, state, xs, *, training=False, rng=None):
        (x,) = xs
        nhwc = self.outputs[0].physical == "nhwc"
        if nhwc:
            x = _to_nhwc(x, self.inputs[0])
            reduce_axes = (0, 1, 2)
        else:
            reduce_axes = (0, 2, 3)

        def _b(v):  # broadcast a (C,) vector over the channel dim
            return v[None, :, None, None] if not nhwc else v

        x32 = x.astype(jnp.float32)
        if training:
            # single-pass statistics: E[x] and E[x^2] reduce together in
            # one traversal of the activation stream (jnp.var alone would
            # re-read x after computing the mean — one extra full pass
            # over every conv output per step, benchmarks/
            # CONV_MFU_ANALYSIS.md names BN stat passes as a top cost).
            # XLA fuses the two accumulations into one loop.
            mean = jnp.mean(x32, axis=reduce_axes)
            mean_sq = jnp.mean(x32 * x32, axis=reduce_axes)
            var = jnp.maximum(mean_sq - mean * mean, 0.0)
            new_state = {
                "running_mean": self.momentum * state["running_mean"]
                                + (1 - self.momentum) * mean,
                "running_var": self.momentum * state["running_var"]
                               + (1 - self.momentum) * var,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        y = (x32 - _b(mean)) * _b(inv)
        y = y * _b(params["scale"]) + _b(params["bias"])
        if self.relu:
            y = jax.nn.relu(y)
        return [y.astype(x.dtype)], new_state

    def apply(self, params, xs, *, training=False, rng=None):
        raise RuntimeError("BatchNorm uses apply_with_state")
