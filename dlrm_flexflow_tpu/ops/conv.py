"""Conv2D, Pool2D, BatchNorm operators (NCHW API, matching the reference).

Parity with the reference ops (reference: src/ops/conv_2d.cu 1046 LoC —
cuDNN conv with auto-picked algorithm + fused ReLU; src/ops/pool_2d.cu 510 —
cuDNN pooling; src/ops/batch_norm.cu 565 — cuDNN BN training mode).

TPU-native redesign: `lax.conv_general_dilated` lowers to the MXU's native
convolution; algorithm picking is XLA's job (the cuDNN find-algorithm dance
at conv_2d.cu:217 has no TPU analog). BatchNorm is a fused
normalize-scale-shift in fp32 statistics; running stats are parameters
updated functionally (the train step threads them through like weights but
with direct assignment, not gradients).

Layout: the API is NCHW (reference parity) but the conv stack COMPUTES in
NHWC — the layout the TPU's vector units and XLA's conv emitter want
(channels on the 128-lane minor dim). Each op consumes its input in
whatever physical layout the producer declared (Tensor.physical) and
declares "nhwc" on its own outputs; layout-agnostic consumers ride along
and everything else transposes back to logical NCHW at the op boundary
(FFModel._forward_env). Disable with FFConfig.conv_nhwc=False / --no-nhwc.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.initializers import (ConstantInitializer, DEFAULT_BIAS_INIT,
                                 DEFAULT_KERNEL_INIT, ZeroInitializer)
from ..core.op import Op, ParamDef
from ..parallel.pconfig import ParallelConfig
from .common import AC_MODE_NONE, apply_activation

POOL_MAX = "max"
POOL_AVG = "avg"


def _nhwc_enabled(model) -> bool:
    return bool(getattr(model.config, "conv_nhwc", True))


def _to_nhwc(x, t):
    """Bring a concrete array for logical-NCHW tensor `t` into NHWC."""
    return x if t.physical == "nhwc" else jnp.transpose(x, (0, 2, 3, 1))


def _from_nhwc(x, t):
    """Bring an NHWC array back to tensor `t`'s declared physical form."""
    return x if t.physical == "nhwc" else jnp.transpose(x, (0, 3, 1, 2))


class Conv2D(Op):
    type_name = "Conv2D"

    def __init__(self, model, input_tensor, out_channels: int,
                 kernel_h: int, kernel_w: int, stride_h: int, stride_w: int,
                 padding_h: int, padding_w: int, activation=AC_MODE_NONE,
                 use_bias: bool = True, groups: int = 1,
                 kernel_initializer=None, bias_initializer=None,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        if input_tensor.num_dims != 4:
            raise ValueError("Conv2D expects NCHW rank-4 input")
        n, c, h, w = input_tensor.shape
        self.in_channels = c
        self.out_channels = int(out_channels)
        self.kernel = (int(kernel_h), int(kernel_w))
        self.stride = (int(stride_h), int(stride_w))
        self.padding = (int(padding_h), int(padding_w))
        self.activation = activation
        self.use_bias = bool(use_bias)
        self.groups = int(groups)
        self.kernel_initializer = kernel_initializer or DEFAULT_KERNEL_INIT()
        self.bias_initializer = bias_initializer or DEFAULT_BIAS_INIT()
        oh = (h + 2 * self.padding[0] - self.kernel[0]) // self.stride[0] + 1
        ow = (w + 2 * self.padding[1] - self.kernel[1]) // self.stride[1] + 1
        self.outputs = [self._make_output((n, self.out_channels, oh, ow))]
        if _nhwc_enabled(model):
            self.outputs[0].physical = "nhwc"
            self._accepts_nhwc_inputs = True

    def param_defs(self) -> Dict[str, ParamDef]:
        # OIHW kernel layout (cuDNN default, conv_2d.cu)
        defs = {"kernel": ParamDef(
            (self.out_channels, self.in_channels // self.groups,
             *self.kernel), jnp.float32, self.kernel_initializer)}
        if self.use_bias:
            defs["bias"] = ParamDef((self.out_channels,), jnp.float32,
                                    self.bias_initializer)
        return defs

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        cdt = self.model.compute_dtype
        pads = [(self.padding[0], self.padding[0]),
                (self.padding[1], self.padding[1])]
        # no preferred_element_type upcast: jax's conv transpose rule
        # rejects mixed dtypes (fp32 cotangent vs bf16 operands), so emit a
        # bf16-out conv (MXU still accumulates fp32 internally) and upcast
        if self.outputs[0].physical == "nhwc":
            y = lax.conv_general_dilated(
                _to_nhwc(x, self.inputs[0]).astype(cdt),
                params["kernel"].astype(cdt),
                window_strides=self.stride, padding=pads,
                dimension_numbers=("NHWC", "OIHW", "NHWC"),
                feature_group_count=self.groups).astype(jnp.float32)
            if self.use_bias:
                y = y + params["bias"]
        else:
            y = lax.conv_general_dilated(
                x.astype(cdt), params["kernel"].astype(cdt),
                window_strides=self.stride, padding=pads,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=self.groups).astype(jnp.float32)
            if self.use_bias:
                y = y + params["bias"][None, :, None, None]
        return [apply_activation(y, self.activation).astype(x.dtype)]

    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        """Sample DP plus attribute (h/w) splits — SOAP "A" parallelism
        (reference model.cc:502-526, 738-744 partitions conv over n/c/h/w)."""
        out = []
        n, c, h, w = self.outputs[0].shape
        for ds in feasible_degrees:
            if ds <= num_devices:
                out.append(ParallelConfig((ds, 1, 1, 1)))
        for dh in feasible_degrees:
            if 1 < dh <= num_devices and h % dh == 0:
                out.append(ParallelConfig((1, 1, dh, 1)))
        for ds in feasible_degrees:
            for dc in feasible_degrees:
                if ds * dc <= num_devices and 1 < dc and self.out_channels % dc == 0:
                    out.append(ParallelConfig((ds, dc, 1, 1)))
        return out

    def param_axes(self, pc: ParallelConfig, out_axes,
                   raw_pc=None):
        ch = out_axes[1] if len(out_axes) >= 2 else ()
        out = {"kernel": (ch, (), (), ())}
        if self.use_bias:
            out["bias"] = (ch,)
        return out

    def param_shard_shapes(self, pc: ParallelConfig, ndev=None):
        dc = pc.degrees[1] if len(pc.degrees) > 1 else 1
        shapes = {n_: list(d.shape) for n_, d in self.param_defs().items()}
        if dc > 1:
            shapes["kernel"][0] = max(shapes["kernel"][0] // dc, 1)
            if "bias" in shapes:
                shapes["bias"][0] = max(shapes["bias"][0] // dc, 1)
        return {n_: tuple(v) for n_, v in shapes.items()}

    def flops_per_sample(self) -> float:
        _, co, oh, ow = self.outputs[0].shape
        kh, kw = self.kernel
        return 2.0 * co * oh * ow * (self.in_channels // self.groups) * kh * kw


class Pool2D(Op):
    type_name = "Pool2D"

    def __init__(self, model, input_tensor, kernel_h, kernel_w, stride_h,
                 stride_w, padding_h, padding_w, pool_type: str = POOL_MAX,
                 activation=AC_MODE_NONE, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        n, c, h, w = input_tensor.shape
        self.kernel = (int(kernel_h), int(kernel_w))
        self.stride = (int(stride_h), int(stride_w))
        self.padding = (int(padding_h), int(padding_w))
        self.pool_type = pool_type
        self.activation = activation
        oh = (h + 2 * self.padding[0] - self.kernel[0]) // self.stride[0] + 1
        ow = (w + 2 * self.padding[1] - self.kernel[1]) // self.stride[1] + 1
        self.outputs = [self._make_output((n, c, oh, ow))]
        if _nhwc_enabled(model):
            self.outputs[0].physical = "nhwc"
            self._accepts_nhwc_inputs = True

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        nhwc = self.outputs[0].physical == "nhwc"
        if nhwc:
            x = _to_nhwc(x, self.inputs[0])
            pads = [(0, 0),
                    (self.padding[0], self.padding[0]),
                    (self.padding[1], self.padding[1]), (0, 0)]
            dims = (1, *self.kernel, 1)
            strides = (1, *self.stride, 1)
        else:
            pads = [(0, 0), (0, 0),
                    (self.padding[0], self.padding[0]),
                    (self.padding[1], self.padding[1])]
            dims = (1, 1, *self.kernel)
            strides = (1, 1, *self.stride)
        if self.pool_type == POOL_MAX:
            init = -jnp.inf
            y = lax.reduce_window(x, init, lax.max, dims, strides, pads)
        else:
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
            if self.padding != (0, 0):
                # exclude padded positions from the divisor (reference uses
                # CUDNN_POOLING_AVERAGE_COUNT_EXCLUDE_PADDING, pool_2d.cu:190)
                counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                           dims, strides, pads)
                y = y / counts
            else:
                y = y / float(self.kernel[0] * self.kernel[1])
        return [apply_activation(y, self.activation)]


class BatchNorm(Op):
    """BatchNorm2D over NCHW (normalize per channel). `relu` flag matches the
    reference ctor (batch_norm.cu). Running stats are non-gradient state the
    train step updates in-place-functionally; eval mode uses them."""

    type_name = "BatchNorm"
    momentum = 0.9
    eps = 1e-5

    def __init__(self, model, input_tensor, relu: bool = True,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.relu = bool(relu)
        self.channels = input_tensor.shape[1]
        self.outputs = [self._make_output(input_tensor.shape)]
        if _nhwc_enabled(model):
            self.outputs[0].physical = "nhwc"
            self._accepts_nhwc_inputs = True

    def param_defs(self):
        c = self.channels
        return {
            "scale": ParamDef((c,), jnp.float32, ConstantInitializer(1.0)),
            "bias": ParamDef((c,), jnp.float32, ZeroInitializer()),
        }

    # running stats: handled as op state (see model.py state threading)
    def state_defs(self):
        c = self.channels
        return {
            "running_mean": ParamDef((c,), jnp.float32, ZeroInitializer()),
            "running_var": ParamDef((c,), jnp.float32, ConstantInitializer(1.0)),
        }

    def apply_with_state(self, params, state, xs, *, training=False, rng=None):
        (x,) = xs
        nhwc = self.outputs[0].physical == "nhwc"
        if nhwc:
            x = _to_nhwc(x, self.inputs[0])
            reduce_axes = (0, 1, 2)
        else:
            reduce_axes = (0, 2, 3)

        def _b(v):  # broadcast a (C,) vector over the channel dim
            return v[None, :, None, None] if not nhwc else v

        x32 = x.astype(jnp.float32)
        if training:
            mean = jnp.mean(x32, axis=reduce_axes)
            var = jnp.var(x32, axis=reduce_axes)
            new_state = {
                "running_mean": self.momentum * state["running_mean"]
                                + (1 - self.momentum) * mean,
                "running_var": self.momentum * state["running_var"]
                               + (1 - self.momentum) * var,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        y = (x32 - _b(mean)) * _b(inv)
        y = y * _b(params["scale"]) + _b(params["bias"])
        if self.relu:
            y = jax.nn.relu(y)
        return [y.astype(x.dtype)], new_state

    def apply(self, params, xs, *, training=False, rng=None):
        raise RuntimeError("BatchNorm uses apply_with_state")
