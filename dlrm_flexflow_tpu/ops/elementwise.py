"""Elementwise, softmax and dropout operators.

Parity with the reference ElementUnary (exp/relu/sigmoid/tanh/elu —
src/ops/element_unary.cu, 621 LoC, cuDNN activation or custom kernels),
ElementBinary (add/sub/mul/div — src/ops/element_binary.cu, 730 LoC, cuDNN
OpTensor), Softmax (src/ops/softmax.cu, cuDNN softmax), Dropout
(src/ops/dropout.cu, cuDNN dropout with reserve space), and the fork's
standalone Tanh op (src/ops/tanh.cu — dead code there; a live alias here).

On TPU all of these are single XLA HLOs the compiler fuses into adjacent
matmuls; dropout uses jax PRNG instead of a cuDNN reserve-space state.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.op import Op

_UNARY = {
    "exp": jnp.exp,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "elu": jax.nn.elu,
    "identity": lambda x: x,
}

_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
}


class ElementUnary(Op):
    type_name = "ElementUnary"

    def hbm_io_factor(self) -> float:
        # fused into the producer's epilogue by XLA (see Op.hbm_io_factor)
        return 0.5

    def __init__(self, model, input_tensor, op_type: str,
                 name: Optional[str] = None):
        if op_type not in _UNARY:
            raise ValueError(f"unknown unary op {op_type}")
        # reference names ops "<Type>_<guid>" per concrete type (e.g. Exp_3)
        self.type_name = op_type.capitalize()
        super().__init__(model, [input_tensor], name)
        self.op_type = op_type
        self.outputs = [self._make_output(input_tensor.shape, input_tensor.dtype)]
        # layout-agnostic: ride along in the producer's physical layout
        # (keeps ResNet/Inception activation chains in NHWC end to end)
        self.outputs[0].physical = input_tensor.physical
        self._accepts_nhwc_inputs = input_tensor.physical == "nhwc"

    def apply(self, params, xs, *, training=False, rng=None):
        return [_UNARY[self.op_type](xs[0])]


class ElementBinary(Op):
    type_name = "ElementBinary"

    def hbm_io_factor(self) -> float:
        # fused into the producer's epilogue by XLA (see Op.hbm_io_factor)
        return 0.5

    def __init__(self, model, a, b, op_type: str, name: Optional[str] = None):
        if op_type not in _BINARY:
            raise ValueError(f"unknown binary op {op_type}")
        self.type_name = op_type.capitalize()
        super().__init__(model, [a, b], name)
        if a.shape != b.shape:
            raise ValueError(f"elementwise shape mismatch {a.shape} vs {b.shape}")
        self.op_type = op_type
        self.outputs = [self._make_output(a.shape, a.dtype)]
        # layout-agnostic only when BOTH operands share a physical layout
        # (e.g. two NHWC conv branches summed in a residual block)
        if a.physical == b.physical and a.physical is not None:
            self.outputs[0].physical = a.physical
            self._accepts_nhwc_inputs = True

    def apply(self, params, xs, *, training=False, rng=None):
        return [_BINARY[self.op_type](xs[0], xs[1])]


class Softmax(Op):
    """Reference softmax.cu:169 — cuDNN softmax over the channel dim of a
    2-D (batch, classes) tensor."""

    type_name = "Softmax"

    def __init__(self, model, input_tensor, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.outputs = [self._make_output(input_tensor.shape)]

    def apply(self, params, xs, *, training=False, rng=None):
        return [jax.nn.softmax(xs[0].astype(jnp.float32), axis=-1)]


class Dropout(Op):
    """Reference dropout.cu — cuDNN dropout; here jax PRNG, active only in
    training mode (inverted dropout, same expectation)."""

    type_name = "Dropout"

    def __init__(self, model, input_tensor, rate: float, seed: int = 0,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.rate = float(rate)
        self.seed = int(seed)
        self.outputs = [self._make_output(input_tensor.shape, input_tensor.dtype)]
        # layout-agnostic (elementwise mask)
        self.outputs[0].physical = input_tensor.physical
        self._accepts_nhwc_inputs = input_tensor.physical == "nhwc"

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs
        if not training or self.rate <= 0.0:
            return [x]
        if rng is None:
            raise ValueError("Dropout in training mode needs an rng")
        keep = 1.0 - self.rate
        key = jax.random.fold_in(jax.random.fold_in(rng, self.guid), self.seed)
        mask = jax.random.bernoulli(key, keep, x.shape)
        return [jnp.where(mask, x / keep, 0.0).astype(x.dtype)]
