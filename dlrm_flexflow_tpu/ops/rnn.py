"""Recurrent ops: LSTM layer.

Parity with the reference NMT mini-framework's LSTM (reference: nmt/lstm.cu,
574 LoC — cuDNN RNN kernels; one op per (layer, word-position) chunk of
LSTM_PER_NODE_LENGTH=10 cells, nmt/rnn.h:23,58-63, placed per-cell by a
hand-written GlobalConfig table).

TPU-native redesign: the whole sequence is ONE op whose time loop is a
`lax.scan` — XLA unrolls nothing, compiles one cell and iterates, keeping
the (batch, 4*hidden) gate matmuls on the MXU. The reference's per-cell
device placement (its only sequence-scaling trick) is subsumed by batch/
hidden sharding; hidden-state TP shards the gate matmul columns. The
sequence dim itself must stay unpartitioned for the scan (degrees[1] == 1);
long-sequence scaling on TPU is the job of sequence-parallel attention
(ops/attention.py), not RNN chunking.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.initializers import DEFAULT_KERNEL_INIT, ZeroInitializer
from ..core.op import Op, ParamDef
from ..parallel.pconfig import ParallelConfig


class LSTM(Op):
    """input (batch, seq, in_dim) -> output (batch, seq, hidden) and the
    final hidden state is discarded (sequence-to-sequence layer form).
    Gate order i,f,g,o (torch convention, for golden tests)."""

    type_name = "LSTM"

    def __init__(self, model, input_tensor, hidden: int,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        if input_tensor.num_dims != 3:
            raise ValueError("LSTM expects (batch, seq, in_dim)")
        b, s, d = input_tensor.shape
        self.in_dim = d
        self.hidden = int(hidden)
        self.outputs = [self._make_output((b, s, self.hidden))]

    def param_defs(self) -> Dict[str, ParamDef]:
        h, d = self.hidden, self.in_dim
        return {
            "wx": ParamDef((d, 4 * h), jnp.float32, DEFAULT_KERNEL_INIT()),
            "wh": ParamDef((h, 4 * h), jnp.float32, DEFAULT_KERNEL_INIT()),
            "bias": ParamDef((4 * h,), jnp.float32, ZeroInitializer()),
        }

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs  # (b, s, d)
        cdt = self.model.compute_dtype
        h = self.hidden
        wx, wh, bias = params["wx"], params["wh"], params["bias"]
        # precompute input projections for the whole sequence in one big
        # MXU matmul, then scan only the recurrent part
        xproj = jnp.einsum("bsd,dk->bsk", x.astype(cdt), wx.astype(cdt),
                           preferred_element_type=jnp.float32) + bias
        b = x.shape[0]
        h0 = jnp.zeros((b, h), jnp.float32)
        c0 = jnp.zeros((b, h), jnp.float32)
        # cast the recurrent weights ONCE outside the loop: a cast inside
        # the body would re-stream the (h, 4h) matrix every timestep if
        # XLA declines to hoist it (16 MB/step at reference scale)
        whc = wh.astype(cdt)

        def cell(carry, xp):
            hprev, cprev = carry
            gates = xp + jnp.dot(hprev.astype(cdt), whc,
                                 preferred_element_type=jnp.float32)
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c = f * cprev + i * g
            hcur = o * jnp.tanh(c)
            return (hcur, c), hcur

        (_, _), hs = lax.scan(cell, (h0, c0),
                              jnp.swapaxes(xproj, 0, 1))  # (s, b, h)
        return [jnp.swapaxes(hs, 0, 1).astype(x.dtype)]

    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        # batch DP x hidden TP; seq dim must stay whole for the scan
        out = []
        for ds in feasible_degrees:
            for dh in feasible_degrees:
                if ds * dh <= num_devices and self.hidden % max(dh, 1) == 0:
                    out.append(ParallelConfig((ds, 1, dh)))
        return out

    def param_axes(self, pc: ParallelConfig, out_axes,
                   raw_pc=None):
        ch = out_axes[2] if len(out_axes) >= 3 else ()
        # gate matrices are (.., 4h): sharding 4h on the hidden axes keeps
        # each device's gate slice local (i/f/g/o interleave is fine since
        # split(4) is along the same sharded dim)
        return {"wx": ((), ch), "wh": ((), ch), "bias": (ch,)}

    def param_shard_shapes(self, pc: ParallelConfig, ndev=None):
        dc = pc.degrees[2] if len(pc.degrees) > 2 else 1
        shapes = {n_: list(d.shape) for n_, d in self.param_defs().items()}
        if dc > 1:
            for n_ in shapes:
                shapes[n_][-1] = max(shapes[n_][-1] // dc, 1)
        return {n_: tuple(v) for n_, v in shapes.items()}

    def flops_per_sample(self) -> float:
        s = self.inputs[0].shape[1]
        return 2.0 * s * 4 * self.hidden * (self.in_dim + self.hidden)

    def sequential_steps(self) -> int:
        # the recurrent scan: one serial iteration per sequence position
        return int(self.inputs[0].shape[1])
