"""Recurrent ops: LSTM layer.

Parity with the reference NMT mini-framework's LSTM (reference: nmt/lstm.cu,
574 LoC — cuDNN RNN kernels; one op per (layer, word-position) chunk of
LSTM_PER_NODE_LENGTH=10 cells, nmt/rnn.h:23,58-63, placed per-cell by a
hand-written GlobalConfig table).

TPU-native redesign: the whole sequence is ONE op whose time loop is a
`lax.scan` — XLA unrolls nothing, compiles one cell and iterates, keeping
the (batch, 4*hidden) gate matmuls on the MXU. The reference's per-cell
device placement (its only sequence-scaling trick) is subsumed by batch/
hidden sharding; hidden-state TP shards the gate matmul columns. The
sequence dim itself must stay unpartitioned for the scan (degrees[1] == 1);
long-sequence scaling on TPU is the job of sequence-parallel attention
(ops/attention.py), not RNN chunking.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.initializers import DEFAULT_KERNEL_INIT, ZeroInitializer
from ..core.op import Op, ParamDef
from ..parallel.pconfig import ParallelConfig


def _dp_route(model, op, b, hidden, seq):
    """(batch_axes, nsh) when the resident kernel can run PER-SHARD
    under shard_map: pure data parallelism (seq and hidden unsharded,
    recurrent weights replicated) AND per-shard kernel eligibility
    (resident_scan_ok with the local batch — pallas flag, backend,
    alignment, VMEM budget). None otherwise. Same pattern as the
    sharded embedding scatter
    (ops/embedding.py:_row_shard_axes → sharded_scatter_add_packed)."""
    mesh = getattr(model, "mesh", None)
    if mesh is None or mesh.size <= 1 or op is None:
        return None
    sh = getattr(model, "_out_sharding", {}).get(op.outputs[0].guid)
    if sh is None:
        return None
    # PartitionSpec omits trailing unsharded dims: P(('f0','f1'),) means
    # seq/hidden replicated
    spec = tuple(sh.spec) + (None,) * (3 - len(sh.spec))
    if spec[1] is not None or spec[2] is not None:
        return None
    spec0 = spec[0]
    if not spec0:
        return None
    axes = (spec0,) if isinstance(spec0, str) else tuple(spec0)
    # recurrent weights must be replicated (hidden-TP shards the 4h dim)
    wsh = getattr(model, "_param_sharding", {}).get(op.name, {})
    for k, s_ in wsh.items():
        if k.startswith("wh") and any(a is not None for a in s_.spec):
            return None
    nsh = 1
    for a in axes:
        nsh *= mesh.shape[a]
    # global-trace check: under the cost model's standalone measurement
    # the array is already LOCAL-shaped and must not be re-sharded
    if b != op.inputs[0].shape[0] or b % nsh != 0:
        return None
    from .pallas.lstm_kernel import resident_scan_ok
    if not resident_scan_ok(model, b // nsh, hidden, seq, local=True):
        return None
    return axes, nsh


def _resident_route_ok(model, op, b, hidden, seq) -> bool:
    """Single predicate for "the VMEM-resident kernel will carry this
    op's scan" — single-chip direct call OR per-shard DP shard_map.
    Used by apply() routing AND the cost-model hooks so they cannot
    drift."""
    from .pallas.lstm_kernel import resident_scan_ok
    return (resident_scan_ok(model, b, hidden, seq)
            or _dp_route(model, op, b, hidden, seq) is not None)


@functools.lru_cache(maxsize=1)
def _target_vmem_default() -> int:
    """Fallback target VMEM for candidate pricing when the caller does
    not thread a spec through (memoized — this sits in the MCMC inner
    loop)."""
    from ..search.cost_model import TPUSpec
    return TPUSpec.detect().vmem_bytes


def _resident_route_ok_candidate(model, b, hidden, seq, pc,
                                 vmem_bytes: int = 0) -> bool:
    """Residency under a CANDIDATE config, for strategy search: backend-
    independent (an offline CPU search must price the scan the way it
    will run on the TPU target — ADVICE r4) and judged against `pc`
    rather than the currently-compiled sharding. Eligible iff the
    candidate is pure batch-DP (hidden/seq unsharded; hidden-TP shards
    wh, which the resident kernel cannot carry) and the per-shard shape
    passes the same alignment/VMEM test against the TARGET chip
    (`vmem_bytes`, threaded from the cost model's TPUSpec so a
    user-injected spec is honored)."""
    if not getattr(model.config, "pallas_lstm", True):
        return False
    degs = tuple(pc.degrees) + (1,) * (3 - len(pc.degrees))
    if any(d > 1 for d in degs[1:3]):
        return False
    parts = max(degs[0], 1)
    if b % parts:
        return False
    from .pallas.lstm_kernel import scan_shape_fits
    return scan_shape_fits(model, b // parts, hidden, seq,
                           vmem_bytes=vmem_bytes or _target_vmem_default())


def _recurrent_scan(model, xproj, whc, cdt, op=None):
    """The serial part of an LSTM layer: scan gate pre-activations
    `xproj` (b, s, 4h) with recurrent weights `whc`. Routes to the
    VMEM-resident pallas kernel when eligible — round-4 measurement
    found the lax.scan cell WEIGHT-STREAM-BOUND (~27 of ~32 us/iter is
    re-streaming wh from HBM; XLA does not pin scan weights), which the
    kernel removes. Under a >1-device mesh with pure batch DP the
    kernel runs per-shard inside shard_map (each shard's rows are
    independent — exact). Fallback: plain lax.scan (same math, same
    i,f,g,o order)."""
    b, s, h4 = xproj.shape
    h = h4 // 4
    from .pallas.lstm_kernel import lstm_scan, resident_scan_ok
    if resident_scan_ok(model, b, h, s):
        # the kernel is time-major (grid dim 0 = time; TPU block
        # alignment wants (b, 4h) as the trailing dims)
        ys = lstm_scan(jnp.swapaxes(xproj, 0, 1), whc)
        return jnp.swapaxes(ys, 0, 1)
    route = _dp_route(model, op, b, h, s)
    if route is not None:
        axes, _ = route
        import inspect

        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map as _shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map as _shard_map
        # the replication-check kwarg was renamed check_rep -> check_vma
        _ckw = ({"check_vma": False}
                if "check_vma" in inspect.signature(_shard_map).parameters
                else {"check_rep": False})

        def local(xp, w):
            ys = lstm_scan(jnp.swapaxes(xp, 0, 1), w)
            return jnp.swapaxes(ys, 0, 1)

        return _shard_map(
            local, mesh=model.mesh,
            in_specs=(P(axes, None, None), P(None, None)),
            out_specs=P(axes, None, None), **_ckw)(xproj, whc)

    def cell(carry, xp):
        hprev, cprev = carry
        gates = xp + jnp.dot(hprev.astype(cdt), whc,
                             preferred_element_type=jnp.float32)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * cprev + i * g
        hcur = o * jnp.tanh(c)
        return (hcur, c), hcur

    zeros = jnp.zeros((b, h), jnp.float32)
    (_, _), hs = lax.scan(cell, (zeros, zeros),
                          jnp.swapaxes(xproj, 0, 1))  # (s, b, h)
    return jnp.swapaxes(hs, 0, 1)


def _lstm_candidate_configs(hidden, num_devices, feasible_degrees):
    """batch DP x hidden TP; the seq dim must stay whole for the scan
    (shared by LSTM and LSTMStack so the enumerations cannot drift)."""
    out = []
    for ds in feasible_degrees:
        for dh in feasible_degrees:
            if ds * dh <= num_devices and hidden % max(dh, 1) == 0:
                out.append(ParallelConfig((ds, 1, dh)))
    return out


class LSTM(Op):
    """input (batch, seq, in_dim) -> output (batch, seq, hidden) and the
    final hidden state is discarded (sequence-to-sequence layer form).
    Gate order i,f,g,o (torch convention, for golden tests)."""

    type_name = "LSTM"

    def __init__(self, model, input_tensor, hidden: int,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        if input_tensor.num_dims != 3:
            raise ValueError("LSTM expects (batch, seq, in_dim)")
        b, s, d = input_tensor.shape
        self.in_dim = d
        self.hidden = int(hidden)
        self.outputs = [self._make_output((b, s, self.hidden))]

    def param_defs(self) -> Dict[str, ParamDef]:
        h, d = self.hidden, self.in_dim
        return {
            "wx": ParamDef((d, 4 * h), jnp.float32, DEFAULT_KERNEL_INIT()),
            "wh": ParamDef((h, 4 * h), jnp.float32, DEFAULT_KERNEL_INIT()),
            "bias": ParamDef((4 * h,), jnp.float32, ZeroInitializer()),
        }

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs  # (b, s, d)
        cdt = self.model.compute_dtype
        wx, wh, bias = params["wx"], params["wh"], params["bias"]
        # precompute input projections for the whole sequence in one big
        # MXU matmul, then scan only the recurrent part
        xproj = jnp.einsum("bsd,dk->bsk", x.astype(cdt), wx.astype(cdt),
                           preferred_element_type=jnp.float32) + bias
        # cast the recurrent weights ONCE outside the loop: a cast inside
        # the body would re-stream the (h, 4h) matrix every timestep if
        # XLA declines to hoist it (16 MB/step at reference scale)
        hs = _recurrent_scan(self.model, xproj, wh.astype(cdt), cdt,
                             op=self)
        return [hs.astype(x.dtype)]

    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        return _lstm_candidate_configs(self.hidden, num_devices,
                                       feasible_degrees)

    def param_axes(self, pc: ParallelConfig, out_axes,
                   raw_pc=None):
        ch = out_axes[2] if len(out_axes) >= 3 else ()
        # gate matrices are (.., 4h): sharding 4h on the hidden axes keeps
        # each device's gate slice local (i/f/g/o interleave is fine since
        # split(4) is along the same sharded dim)
        return {"wx": ((), ch), "wh": ((), ch), "bias": (ch,)}

    def param_shard_shapes(self, pc: ParallelConfig, ndev=None):
        dc = pc.degrees[2] if len(pc.degrees) > 2 else 1
        shapes = {n_: list(d.shape) for n_, d in self.param_defs().items()}
        if dc > 1:
            for n_ in shapes:
                shapes[n_][-1] = max(shapes[n_][-1] // dc, 1)
        return {n_: tuple(v) for n_, v in shapes.items()}

    def flops_per_sample(self) -> float:
        s = self.inputs[0].shape[1]
        return 2.0 * s * 4 * self.hidden * (self.in_dim + self.hidden)

    def sequential_steps(self, pc=None, vmem_bytes: int = 0) -> int:
        # the recurrent scan: one serial iteration per sequence position
        return int(self.inputs[0].shape[1])

    def scan_weights_resident(self, pc=None, vmem_bytes: int = 0) -> bool:
        b, s, _ = self.inputs[0].shape
        if pc is not None:
            return _resident_route_ok_candidate(self.model, b, self.hidden,
                                                s, pc, vmem_bytes)
        return _resident_route_ok(self.model, self, b, self.hidden, s)

    def scan_param_stream_bytes(self) -> int:
        # only the recurrent matrix rides inside the loop; wx/bias are
        # hoisted into one sequence-wide projection (apply())
        return self.hidden * 4 * self.hidden * 4


class LSTMStack(Op):
    """N stacked LSTM layers fused into ONE scan.

    Stacking N separate LSTM ops runs N scans of `seq` iterations each —
    N x seq serial steps, each paying the fixed lax.scan iteration
    latency that dominates small-batch RNNs (~300 us/iteration measured
    at NMT scale vs ~15 us of gemm). Fusing the layers into one scan
    body does the SAME math (layer l at time t consumes layer l-1's
    output at time t, computed earlier in the same iteration) in seq
    iterations total — the serial latency is paid once per timestep, not
    once per layer per timestep. The reference reaches for per-cell
    device placement for this (nmt/rnn.h:58-63); on TPU the lever is
    iteration count, not placement.

    input (batch, seq, in_dim) -> output (batch, seq, hidden) of the top
    layer. Gate order i,f,g,o per layer (torch convention).
    """

    type_name = "LSTMStack"

    def __init__(self, model, input_tensor, hidden: int, num_layers: int,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        if input_tensor.num_dims != 3:
            raise ValueError("LSTMStack expects (batch, seq, in_dim)")
        if num_layers < 1:
            raise ValueError("num_layers must be >= 1")
        b, s, d = input_tensor.shape
        self.in_dim = d
        self.hidden = int(hidden)
        self.num_layers = int(num_layers)
        self.outputs = [self._make_output((b, s, self.hidden))]

    def param_defs(self) -> Dict[str, ParamDef]:
        h = self.hidden
        defs = {}
        for layer in range(self.num_layers):
            d = self.in_dim if layer == 0 else h
            defs[f"wx{layer}"] = ParamDef((d, 4 * h), jnp.float32,
                                          DEFAULT_KERNEL_INIT())
            defs[f"wh{layer}"] = ParamDef((h, 4 * h), jnp.float32,
                                          DEFAULT_KERNEL_INIT())
            defs[f"bias{layer}"] = ParamDef((4 * h,), jnp.float32,
                                            ZeroInitializer())
        return defs

    def apply(self, params, xs, *, training=False, rng=None):
        (x,) = xs  # (b, s, d)
        cdt = self.model.compute_dtype
        h, L = self.hidden, self.num_layers
        b, s, _ = x.shape
        if _resident_route_ok(self.model, self, b, h, s):
            # layer-by-layer with the VMEM-resident kernel: EVERY
            # layer's input projection hoists to one big sequence-wide
            # MXU matmul (the fused single-scan form must project deep
            # layers inside the loop, re-streaming their wx every
            # iteration — r4 measurement showed that stream, not the
            # iteration count, is what the scan pays for)
            cur = x
            for l in range(L):
                xp = jnp.einsum(
                    "bsd,dk->bsk", cur.astype(cdt),
                    params[f"wx{l}"].astype(cdt),
                    preferred_element_type=jnp.float32) \
                    + params[f"bias{l}"]
                cur = _recurrent_scan(self.model, xp,
                                      params[f"wh{l}"].astype(cdt), cdt,
                                      op=self)
            return [cur.astype(x.dtype)]
        # layer 0's input projection still happens as ONE big MXU matmul
        # outside the loop; deeper layers' inputs are produced inside the
        # iteration and project there
        xproj0 = jnp.einsum("bsd,dk->bsk", x.astype(cdt),
                            params["wx0"].astype(cdt),
                            preferred_element_type=jnp.float32) \
            + params["bias0"]
        b = x.shape[0]
        whc = [params[f"wh{l}"].astype(cdt) for l in range(L)]
        wxc = [None] + [params[f"wx{l}"].astype(cdt) for l in range(1, L)]
        biases = [None] + [params[f"bias{l}"] for l in range(1, L)]
        zeros = jnp.zeros((b, h), jnp.float32)
        carry0 = tuple((zeros, zeros) for _ in range(L))

        def cell(carry, xp0):
            new_carry = []
            inp = None   # layer l>0 input = layer l-1's fresh h
            for l in range(L):
                hprev, cprev = carry[l]
                if l == 0:
                    gates = xp0
                else:
                    gates = jnp.dot(inp.astype(cdt), wxc[l],
                                    preferred_element_type=jnp.float32) \
                        + biases[l]
                gates = gates + jnp.dot(hprev.astype(cdt), whc[l],
                                        preferred_element_type=jnp.float32)
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                           jax.nn.sigmoid(o))
                g = jnp.tanh(g)
                c = f * cprev + i * g
                hcur = o * jnp.tanh(c)
                new_carry.append((hcur, c))
                inp = hcur
            return tuple(new_carry), inp

        _, hs = lax.scan(cell, carry0, jnp.swapaxes(xproj0, 0, 1))
        return [jnp.swapaxes(hs, 0, 1).astype(x.dtype)]

    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        return _lstm_candidate_configs(self.hidden, num_devices,
                                       feasible_degrees)

    def param_axes(self, pc: ParallelConfig, out_axes, raw_pc=None):
        ch = out_axes[2] if len(out_axes) >= 3 else ()
        # deep layers' wx contract over the hidden dim, which the TP
        # sharding splits: keep those replicated (only layer 0's input
        # dim is sharding-free); wh/bias shard their gate columns
        axes = {}
        for layer in range(self.num_layers):
            axes[f"wx{layer}"] = ((), ch) if layer == 0 else ((), ())
            axes[f"wh{layer}"] = ((), ch)
            axes[f"bias{layer}"] = (ch,)
        return axes

    def param_shard_shapes(self, pc: ParallelConfig, ndev=None):
        dc = pc.degrees[2] if len(pc.degrees) > 2 else 1
        shapes = {n_: list(d.shape)
                  for n_, d in self.param_defs().items()}
        if dc > 1:
            for n_ in shapes:
                if n_.startswith("wx") and n_ != "wx0":
                    continue
                shapes[n_][-1] = max(shapes[n_][-1] // dc, 1)
        return {n_: tuple(v) for n_, v in shapes.items()}

    def flops_per_sample(self) -> float:
        s = self.inputs[0].shape[1]
        h = self.hidden
        total = 4 * h * (self.in_dim + h)
        total += (self.num_layers - 1) * 4 * h * (h + h)
        return 2.0 * s * total

    def sequential_steps(self, pc=None, vmem_bytes: int = 0) -> int:
        # one fused scan of seq iterations — or, on the resident-kernel
        # path, num_layers scans of seq iterations each (the overhead
        # floor is ~10 us/iteration either way; weight traffic decides)
        s = int(self.inputs[0].shape[1])
        if self.scan_weights_resident(pc, vmem_bytes):
            return s * self.num_layers
        return s

    def scan_weights_resident(self, pc=None, vmem_bytes: int = 0) -> bool:
        b, s, _ = self.inputs[0].shape
        if pc is not None:
            return _resident_route_ok_candidate(self.model, b, self.hidden,
                                                s, pc, vmem_bytes)
        return _resident_route_ok(self.model, self, b, self.hidden, s)

    def scan_param_stream_bytes(self) -> int:
        # fused single-scan form: every layer's wh rides in the loop,
        # plus deep layers' wx (their inputs are produced inside the
        # iteration; only layer 0's projection hoists)
        h = self.hidden
        wh = self.num_layers * h * 4 * h * 4
        wx_deep = (self.num_layers - 1) * h * 4 * h * 4
        return wh + wx_deep
