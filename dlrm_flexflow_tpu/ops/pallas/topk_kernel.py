"""Pallas TPU chunked MIPS scoring kernel: int8 matmul → running top-k.

The retrieval index (retrieve/index.py) stores item-tower output
embeddings as PR-14 ``QuantTable`` codes + per-row fp32 scales, and the
maximum-inner-product search scores queries directly AGAINST THE CODES:

    score[b, r] = int32( q_codes[b] · codes[r] ) * (scales[r] * q_scales[b])

— an int8×int8 dot with a dequant-free int32 accumulate on the MXU and
ONE fp32 rescale at the end, so scoring bandwidth pays quantized bytes
(the same codec already pays for memory, exchange, and publishes; this
is where it pays a fourth time). The kernel streams the item block in
chunks and carries a running top-k (scores + ids) in VMEM across grid
steps; the merged result NEVER materializes the full (B, R) score
matrix in HBM.

Ordering contract (the merge-exactness goldens pin this): top-k is by
score DESCENDING with ties broken by id ASCENDING. The integer dot is
exact and the rescale is one fp32 multiply in a fixed order, so the
same (codes, scales, query) produce bit-identical scores on every
shard, every backend — which is what makes the sharded heap-merge
(retrieve/index.py) provably identical to a single-machine exact scan.

Off-TPU the plain-XLA/numpy oracle (``mips_topk_reference``) is the
fallback — same math, same ordering, bit-identical results; the CPU
tier-1 suite runs that path (or the kernel under ``interpret=True``).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
# int8 sublane granule: item chunks pad their row count up to this
_INT8_SUBLANES = 32
# sentinel id for empty/padded top-k slots (trimmed by callers)
PAD_ID = np.int32(2 ** 31 - 1)
NEG_INF = np.float32(-np.inf)


def supports(dim: int) -> bool:
    """True if the compiled kernel handles this embedding width (the
    MXU wants whole int8 lane tiles; anything else routes the oracle)."""
    return dim % _LANES == 0


# ---------------------------------------------------------------------
# shared scoring math — the oracle IS the contract
# ---------------------------------------------------------------------
def quantize_query(q: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-row int8 quantization of a query batch (the same
    codec the index rows use, quant/codec.py): (B, d) fp32 ->
    ((B, d) int8 codes, (B,) fp32 scales). A 1-D query is promoted to a
    batch of one."""
    from ...quant.codec import quantize_rows_np
    arr = np.asarray(q, np.float32)
    if arr.ndim == 1:
        arr = arr[None, :]
    codes, scales = quantize_rows_np(arr, "int8")
    return codes, scales


def score_rows_np(q_codes: np.ndarray, q_scales: np.ndarray,
                  codes: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """(B, R) fp32 scores: exact int32 code dot, one fp32 rescale.

    The multiply order (row scale × query scale first, then the dot) is
    part of the exactness contract — the Pallas kernel computes the
    same expression in the same order."""
    dot = q_codes.astype(np.int32) @ codes.astype(np.int32).T    # (B, R)
    comb = (scales.astype(np.float32)[None, :]
            * q_scales.astype(np.float32)[:, None])              # (B, R)
    return dot.astype(np.float32) * comb


def topk_select_np(scores: np.ndarray, ids: np.ndarray, k: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k of each row by (score desc, id asc): (B, k') scores and
    int64 ids, k' = min(k, R). fp32 negation is exact, so the lexsort
    key order matches the kernel's selection order bit-for-bit."""
    scores = np.asarray(scores, np.float32)
    ids = np.asarray(ids, np.int64)
    kk = min(int(k), scores.shape[1])
    out_s = np.empty((scores.shape[0], kk), np.float32)
    out_i = np.empty((scores.shape[0], kk), np.int64)
    for b in range(scores.shape[0]):
        order = np.lexsort((ids, -scores[b]))[:kk]
        out_s[b] = scores[b][order]
        out_i[b] = ids[order]
    return out_s, out_i


def mips_topk_reference(q_codes: np.ndarray, q_scales: np.ndarray,
                        codes: np.ndarray, scales: np.ndarray,
                        k: int, base: int = 0
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """The exact-scan oracle: score every row, sort, take k. ``base``
    offsets the returned ids into a global row space (a shard scoring
    its [lo, hi) slice passes base=lo)."""
    scores = score_rows_np(q_codes, q_scales, codes, scales)
    ids = base + np.arange(codes.shape[0], dtype=np.int64)
    return topk_select_np(scores, ids, k)


# ---------------------------------------------------------------------
# the Pallas kernel
# ---------------------------------------------------------------------
def _topk_kernel(K: int, C: int, n_rows: int,
                 q_ref, qscale_ref, codes_ref, scales_ref,
                 out_s_ref, out_i_ref, run_s, run_i):
    """One grid step scores a (C, d) item chunk against every query and
    folds it into the running (B, K) top-k carried in VMEM scratch.

    The merge is a K-round selection: take the max score (ties to the
    LOWEST id), emit it, deactivate it — exactly the oracle's
    (score desc, id asc) lexsort order, so the compiled path and the
    fallback are bit-identical."""
    step = pl.program_id(0)
    nsteps = pl.num_programs(0)
    B = q_ref.shape[0]

    @pl.when(step == 0)
    def _():
        run_s[:] = jnp.full((B, K), NEG_INF, jnp.float32)
        run_i[:] = jnp.full((B, K), PAD_ID, jnp.int32)

    # int8 × int8 → int32 on the MXU; dequant-free accumulate
    dot = lax.dot_general(q_ref[:], codes_ref[:],
                          (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)      # (B, C)
    comb = scales_ref[:].reshape(1, C) * qscale_ref[:]           # (B, C)
    scores = dot.astype(jnp.float32) * comb
    row_ids = (step * C
               + lax.broadcasted_iota(jnp.int32, (B, C), 1))
    # rows past the real table (chunk padding) never win
    scores = jnp.where(row_ids < n_rows, scores, NEG_INF)

    cand_s = jnp.concatenate([run_s[:], scores], axis=1)         # (B, K+C)
    cand_i = jnp.concatenate([run_i[:], row_ids], axis=1)
    for j in range(K):
        m = jnp.max(cand_s, axis=1, keepdims=True)
        elig = cand_s == m
        pick = jnp.min(jnp.where(elig, cand_i, PAD_ID), axis=1,
                       keepdims=True)
        run_s[:, j:j + 1] = m
        run_i[:, j:j + 1] = pick
        cand_s = jnp.where(elig & (cand_i == pick), NEG_INF, cand_s)

    @pl.when(step == nsteps - 1)
    def _():
        out_s_ref[:] = run_s[:]
        out_i_ref[:] = run_i[:]


def _pallas_topk(q_codes, q_scales, codes, scales, k, chunk, interpret):
    B, d = q_codes.shape
    R = codes.shape[0]
    C = max(_INT8_SUBLANES,
            ((min(chunk, R) + _INT8_SUBLANES - 1)
             // _INT8_SUBLANES) * _INT8_SUBLANES)
    Rp = ((R + C - 1) // C) * C
    codes_p = jnp.zeros((Rp, d), jnp.int8).at[:R].set(
        jnp.asarray(codes, jnp.int8))
    scales_p = jnp.zeros((Rp, 1), jnp.float32).at[:R, 0].set(
        jnp.asarray(scales, jnp.float32))
    out_s, out_i = pl.pallas_call(
        functools.partial(_topk_kernel, int(k), C, R),
        grid=(Rp // C,),
        in_specs=[
            pl.BlockSpec((B, d), lambda i: (0, 0)),              # queries
            pl.BlockSpec((B, 1), lambda i: (0, 0)),              # q scales
            pl.BlockSpec((C, d), lambda i: (i, 0)),              # chunk
            pl.BlockSpec((C, 1), lambda i: (i, 0)),              # scales
        ],
        out_specs=[
            pl.BlockSpec((B, int(k)), lambda i: (0, 0)),
            pl.BlockSpec((B, int(k)), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, int(k)), jnp.float32),
            jax.ShapeDtypeStruct((B, int(k)), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, int(k)), jnp.float32),
            pltpu.VMEM((B, int(k)), jnp.int32),
        ],
        interpret=interpret,
    )(jnp.asarray(q_codes, jnp.int8),
      jnp.asarray(q_scales, jnp.float32).reshape(B, 1),
      codes_p, scales_p)
    return np.asarray(out_s), np.asarray(out_i)


def mips_topk(q_codes: np.ndarray, q_scales: np.ndarray,
              codes: np.ndarray, scales: np.ndarray, k: int,
              base: int = 0, chunk: int = 512,
              use_pallas: Optional[bool] = None,
              interpret: bool = False
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k MIPS over one quantized row block.

    q_codes  : (B, d) int8 query codes (quantize_query)
    q_scales : (B,) fp32 query row scales
    codes    : (R, d) int8 item codes, scales (R,) fp32 (QuantTable)
    returns  : ((B, k') fp32 scores, (B, k') int64 global ids),
               k' = min(k, R), ordered (score desc, id asc).

    Routing: the compiled Pallas path needs a TPU backend and a lane-
    aligned width (``supports``); everything else — the CPU tier-1
    suite included — runs the bit-identical oracle. ``interpret=True``
    forces the kernel through the Pallas interpreter (kernel-parity
    tests)."""
    q_codes = np.asarray(q_codes, np.int8)
    if q_codes.ndim == 1:
        q_codes = q_codes[None, :]
    q_scales = np.asarray(q_scales, np.float32).reshape(-1)
    R = codes.shape[0]
    if R == 0:
        B = q_codes.shape[0]
        return (np.empty((B, 0), np.float32), np.empty((B, 0), np.int64))
    if use_pallas is None:
        use_pallas = interpret or (jax.default_backend() == "tpu"
                                   and supports(q_codes.shape[1]))
    if not use_pallas:
        return mips_topk_reference(q_codes, q_scales, codes, scales,
                                   k, base)
    kk = min(int(k), R)
    out_s, out_i = _pallas_topk(q_codes, q_scales, codes, scales,
                                kk, chunk, interpret)
    return out_s, base + out_i.astype(np.int64)
