"""Pallas TPU embedding-bag kernel.

The reference implements embedding lookup with a custom CUDA gather forward
and an atomicAdd scatter-add backward (reference: src/ops/embedding.cu:173-224)
plus an AVX2 CPU embedding-bag path (src/ops/embedding_avx2.cc). The TPU has
no atomics and gathers are HBM-bandwidth bound, so the design here is:

- forward: a Pallas kernel that keeps the table in HBM and streams exactly
  the needed rows into VMEM with double-buffered async DMA (two row slots,
  the next row's DMA in flight while the current row is accumulated) — the
  TPU analog of the AVX2 embedding-bag blocked loads. Indices arrive via
  scalar prefetch so row addresses are known before the body runs.
  Mosaic requires HBM row slices to be exactly one (1, 128) lane tile, so
  a row of width dim = k*128 is streamed as k chunk-DMAs against a
  (rows*k, 128) view of the table; tables whose dim is not a multiple of
  128 fall back to the XLA gather (`embedding_bag_reference`).
- backward: no atomics — sort the flat indices and segment-sum the incoming
  gradients (indices_are_sorted lets XLA lower it as a linear pass), which
  replaces the reference's atomicAdd scatter.

`embedding_bag` is a custom_vjp function usable both standalone and from
ops/embedding.py. On non-TPU backends pass interpret=True (tests do) or use
`embedding_bag_reference`, the plain-XLA equivalent and test oracle.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# samples per grid step: one float32 sublane tile
_TILE_B = 8
_LANES = 128
# outstanding row DMAs: random 512 B reads are latency-bound, so keep a
# deep pipeline of in-flight fetches rather than classic double buffering
_SLOTS = 8
# scatter-kernel block: the update DMA pipeline drains at each grid-step
# boundary, so the block size IS the outstanding-write depth; 64 keeps
# the random-write pipeline full (8 left the update ~3x slower per row
# than the gather, r5 calibration) at a modest 32 KB VMEM cost
_SCATTER_B = 64


def supports(dim: int) -> bool:
    """True if the Pallas path handles this table width."""
    return dim % _LANES == 0


def _bag_kernel(bag: int, k: int, idx_ref, table_ref, out_ref, row_buf,
                sems):
    """One grid step = _TILE_B samples.

    table_ref is the (rows*k, 128) chunk view resident in HBM; row_buf has
    _SLOTS (1, 128) VMEM slots holding a deep pipeline of in-flight
    fetches (DMA j+_SLOTS-1 starts before chunk j is consumed).
    """
    tb = out_ref.shape[0]
    total = tb * bag * k
    base = pl.program_id(0) * tb * bag

    def dma(j, slot):
        # j enumerates (sample, chunk, bag) as ((s*k + c)*bag + b); the
        # chunk of table row idx[s, b] lives at view row idx*k + c
        s_c, b = j // bag, j % bag
        s, c = s_c // k, s_c % k
        view_row = idx_ref[base + s * bag + b] * k + c
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(view_row, 1), :], row_buf.at[slot],
            sems.at[slot])

    depth = min(_SLOTS - 1, total)
    for j in range(depth):
        dma(j, j % _SLOTS).start()
    for s in range(tb):                # static unroll: all bounds small
        for c in range(k):
            acc = jnp.zeros((1, _LANES), jnp.float32)
            for b in range(bag):
                j = (s * k + c) * bag + b
                if j + depth < total:
                    dma(j + depth, (j + depth) % _SLOTS).start()
                dma(j, j % _SLOTS).wait()
                acc = acc + row_buf[j % _SLOTS].astype(jnp.float32)
            out_ref[pl.ds(s, 1), c * _LANES:(c + 1) * _LANES] = \
                acc.astype(out_ref.dtype)


def _pallas_forward(table: jax.Array, indices: jax.Array,
                    interpret: bool) -> jax.Array:
    """(rows, dim) × int(batch, bag) -> (batch, dim) sum-aggregated."""
    batch, bag = indices.shape
    rows, dim = table.shape
    if not supports(dim):
        raise ValueError(f"pallas embedding_bag needs dim % {_LANES} == 0, "
                         f"got {dim}; use embedding_bag_reference")
    k = dim // _LANES
    padded = ((batch + _TILE_B - 1) // _TILE_B) * _TILE_B
    idx_flat = jnp.zeros((padded * bag,), jnp.int32)
    idx_flat = idx_flat.at[: batch * bag].set(
        indices.astype(jnp.int32).reshape(-1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(padded // _TILE_B,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((_TILE_B, dim), lambda i, idx: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((_SLOTS, 1, _LANES), table.dtype),
            pltpu.SemaphoreType.DMA((_SLOTS,)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_bag_kernel, bag, k),
        out_shape=jax.ShapeDtypeStruct((padded, dim), table.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx_flat, table.reshape(rows * k, _LANES))
    return out[:batch]


def embedding_bag_reference(table, indices, aggr: str = "sum"):
    """Plain-XLA oracle/fallback: gather + reduce over the bag dim."""
    rows = jnp.take(table, indices.astype(jnp.int32), axis=0)
    if aggr == "avg":
        return jnp.mean(rows, axis=-2)
    return jnp.sum(rows, axis=-2)


def _primal(table, indices, aggr, interpret):
    out = _pallas_forward(table, indices, interpret)
    if aggr == "avg":
        out = out / indices.shape[-1]
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def embedding_bag(table, indices, aggr: str = "sum",
                  interpret: bool = False):
    """Embedding bag with a Pallas forward and sorted-segment-sum backward.

    table   : (rows, dim) float, dim % 128 == 0
    indices : (batch, bag) int
    returns : (batch, dim), sum or mean over the bag.
    """
    return _primal(table, indices, aggr, interpret)


def _fwd(table, indices, aggr, interpret):
    # zero-size residual whose static shape/dtype carry the table spec
    spec = jnp.zeros((table.shape[0], 0), table.dtype)
    return _primal(table, indices, aggr, interpret), (indices, spec)


def _bwd(aggr, interpret, res, g):
    indices, spec = res
    batch, bag = indices.shape
    gg = jnp.repeat(g, bag, axis=0).astype(jnp.float32)  # (batch*bag, dim)
    if aggr == "avg":
        gg = gg / bag
    flat = indices.astype(jnp.int32).reshape(-1)
    order = jnp.argsort(flat)
    dtable = jax.ops.segment_sum(
        gg[order], flat[order], num_segments=spec.shape[0],
        indices_are_sorted=True).astype(spec.dtype)
    # integer indices get a float0 cotangent
    return dtable, np.zeros(indices.shape, dtype=jax.dtypes.float0)


embedding_bag.defvjp(_fwd, _bwd)


# ---- quantized-storage gather (quant/: int8/fp8 rows, row-wise scales) ----
# The table lives in HBM at the STORAGE dtype (1 B/elem) and is
# dequantized INSIDE the kernel: each row chunk streams into VMEM as a
# quantized (1, 128) tile and is scaled during accumulation, so HBM
# moves 1/4 the bytes of the fp32 gather. The fp32 row scales ride
# beside the row tiles via scalar prefetch (SMEM — one scalar read per
# accumulated row; VMEM-blocking the scales would need a second DMA
# pipeline for 4 B payloads). Policy-driven: ops route here when their
# QuantPolicy stores int8/fp8 (quant.effective_policy), exactly like the
# fp32 kernel routes via _pallas_ok.


def _bag_kernel_quant(bag: int, k: int, idx_ref, scale_ref, table_ref,
                      out_ref, row_buf, sems):
    """Quantized twin of _bag_kernel: same deep DMA pipeline over the
    (rows*k, 128) chunk view, but row_buf holds STORAGE-dtype tiles and
    the accumulate applies the row's scale (dequant-in-VMEM)."""
    tb = out_ref.shape[0]
    total = tb * bag * k
    base = pl.program_id(0) * tb * bag

    def dma(j, slot):
        s_c, b = j // bag, j % bag
        s, c = s_c // k, s_c % k
        view_row = idx_ref[base + s * bag + b] * k + c
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(view_row, 1), :], row_buf.at[slot],
            sems.at[slot])

    depth = min(_SLOTS - 1, total)
    for j in range(depth):
        dma(j, j % _SLOTS).start()
    for s in range(tb):                # static unroll: all bounds small
        for c in range(k):
            acc = jnp.zeros((1, _LANES), jnp.float32)
            for b in range(bag):
                j = (s * k + c) * bag + b
                if j + depth < total:
                    dma(j + depth, (j + depth) % _SLOTS).start()
                dma(j, j % _SLOTS).wait()
                scale = scale_ref[idx_ref[base + s * bag + b]]
                acc = acc + row_buf[j % _SLOTS].astype(jnp.float32) * scale
            out_ref[pl.ds(s, 1), c * _LANES:(c + 1) * _LANES] = \
                acc.astype(out_ref.dtype)


def embedding_bag_quant(q_table: jax.Array, scales: jax.Array,
                        indices: jax.Array, aggr: str = "sum",
                        interpret: bool = False) -> jax.Array:
    """Embedding bag over a QUANTIZED table with in-kernel dequant.

    q_table : (rows, dim) int8 / float8_e4m3fn, dim % 128 == 0
    scales  : (rows,) fp32 row scales (symmetric codec, quant/codec.py)
    indices : (batch, bag) int
    returns : (batch, dim) fp32, sum or mean over the bag —
              bit-identical to gathering the DEQUANTIZED rows
              (``embedding_bag_quant_reference``, the test oracle).
    """
    batch, bag = indices.shape
    rows, dim = q_table.shape
    if not supports(dim):
        raise ValueError(f"pallas embedding_bag_quant needs dim % "
                         f"{_LANES} == 0, got {dim}; use "
                         f"embedding_bag_quant_reference")
    k = dim // _LANES
    padded = ((batch + _TILE_B - 1) // _TILE_B) * _TILE_B
    idx_flat = jnp.zeros((padded * bag,), jnp.int32)
    idx_flat = idx_flat.at[: batch * bag].set(
        indices.astype(jnp.int32).reshape(-1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(padded // _TILE_B,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((_TILE_B, dim), lambda i, idx, scl: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((_SLOTS, 1, _LANES), q_table.dtype),
            pltpu.SemaphoreType.DMA((_SLOTS,)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_bag_kernel_quant, bag, k),
        out_shape=jax.ShapeDtypeStruct((padded, dim), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx_flat, scales.astype(jnp.float32),
      q_table.reshape(rows * k, _LANES))
    out = out[:batch]
    if aggr == "avg":
        out = out / bag
    return out


def embedding_bag_quant_reference(q_table, scales, indices,
                                  aggr: str = "sum"):
    """Plain-XLA oracle/fallback: dequantize the gathered rows, then the
    bag reduce — the contract embedding_bag_quant must match bitwise
    (fp32 accumulate in both)."""
    idx = indices.astype(jnp.int32)
    rows = (jnp.take(q_table, idx, axis=0).astype(jnp.float32)
            * jnp.take(scales.astype(jnp.float32), idx, axis=0)[..., None])
    if aggr == "avg":
        return jnp.mean(rows, axis=-2)
    return jnp.sum(rows, axis=-2)


def scatter_supports(dim: int) -> bool:
    """Row widths the scatter-add kernel handles: a whole number of lane
    tiles, or an exact divisor of one tile."""
    return dim % _LANES == 0 or _LANES % dim == 0


def _scatter_unique_kernel(idx_ref, upd_ref, tbl_ref, out_ref, bufs,
                           rsems, wsems):
    """One grid step applies _SCATTER_B tile updates, pipelined.

    PRECONDITION (established by scatter_add_rows' dedup pre-pass): all
    view-row targets with row >= 0 are DISTINCT, so the _SCATTER_B (64)
    RMWs of a block are independent: issue all reads, then add+write-back,
    then drain. row < 0 marks a padding slot and is skipped. The reference
    needed atomicAdd for this (embedding.cu:173-224); here distinctness
    replaces atomicity.
    """
    i = pl.program_id(0)

    def rd(s, row):
        return pltpu.make_async_copy(
            out_ref.at[pl.ds(row, 1), :], bufs.at[s], rsems.at[s])

    def wr(s, row):
        return pltpu.make_async_copy(
            bufs.at[s], out_ref.at[pl.ds(row, 1), :], wsems.at[s])

    for s in range(_SCATTER_B):            # static unroll: issue all reads
        row = idx_ref[i * _SCATTER_B + s]

        @pl.when(row >= 0)
        def _():
            rd(s, row).start()
    for s in range(_SCATTER_B):            # add + async write-back
        row = idx_ref[i * _SCATTER_B + s]

        @pl.when(row >= 0)
        def _():
            rd(s, row).wait()
            bufs[s] = (bufs[s] + upd_ref[pl.ds(s, 1), :]).astype(bufs.dtype)
            wr(s, row).start()
    for s in range(_SCATTER_B):            # drain before the next block
        row = idx_ref[i * _SCATTER_B + s]

        @pl.when(row >= 0)
        def _():
            wr(s, row).wait()


def scatter_add_rows(table: jax.Array, indices: jax.Array,
                     updates: jax.Array,
                     interpret: bool = False) -> jax.Array:
    """table.at[indices].add(updates) for (rows, dim) tables — a Pallas
    in-place RMW kernel with an XLA dedup pre-pass.

    XLA's TPU scatter lowers to a serialized update loop that costs
    hundreds of ms for a few thousand rows on a multi-GB table. Here:
    (1) updates are expressed as (view_row, 128-lane tile) pairs — k
    chunks per row for wide tables, rotated d-wide slices for narrow ones;
    (2) duplicates are combined by sort + segment-sum (the sorted-segment
    trick that replaces the reference's atomicAdd backward); (3) a Pallas
    kernel streams the distinct tiles through a pipelined
    read-modify-write, touching only the updated bytes of HBM.

    table   : (rows, dim) float32
    indices : (n,) int — duplicates allowed
    updates : (n, dim) — same width as the table
    """
    rows, dim = table.shape
    (n,) = indices.shape
    if not scatter_supports(dim):
        return table.at[indices].add(updates.astype(table.dtype))
    indices = indices.astype(jnp.int32)
    updates = updates.astype(table.dtype)
    if dim % _LANES == 0:
        k = dim // _LANES
        view = table.reshape(rows * k, _LANES)
        # (n, dim) -> (n*k, 128) chunk tiles at view rows idx*k + c
        tile_rows = (indices[:, None] * k
                     + jnp.arange(k, dtype=jnp.int32)[None, :]).reshape(-1)
        tile_upds = updates.reshape(n * k, _LANES)
    else:
        r_per_tile = _LANES // dim
        if rows % r_per_tile:
            # padding the view would copy the whole table — not worth it
            return table.at[indices].add(updates)
        view = table.reshape(rows // r_per_tile, _LANES)
        tile_rows, tile_upds = _pack_tile_updates(indices, updates, dim,
                                                  updates.dtype)
    out = _dedup_and_scatter(view, tile_rows, tile_upds, interpret)
    return out.reshape(-1, dim)[:rows]


def scatter_add_rows_packed(view: jax.Array, indices: jax.Array,
                            updates: jax.Array, dim: int,
                            interpret: bool = False) -> jax.Array:
    """Scatter d-wide row updates into an ALREADY-PACKED (vrows, 128) view
    (the lane-packed parameter layout of the fused embedding ops —
    128 // dim unpacked rows per view row). Avoids the whole-table layout
    transposes XLA inserts when a narrow (rows, d) table is reshaped at
    the kernel boundary.

    view    : (vrows, 128) — packed table, 128 % dim == 0
    indices : (n,) int in UNPACKED row space — duplicates allowed
    updates : (n, dim)
    """
    tile_rows, tile_upds = _pack_tile_updates(indices, updates, dim,
                                              view.dtype)
    return _dedup_and_scatter(view, tile_rows, tile_upds, interpret)


def _pack_tile_updates(indices, updates, dim, dtype):
    """(n,) unpacked-row indices + (n, dim) updates -> (tile_rows,
    tile_upds (n, 128)): the packed-layout lane-placement math shared by
    the RMW and write-only scatters (tile = idx // r, lane offset =
    (idx % r)·d).

    The lane placement selects among the r = 128/d STATIC rotations of
    each padded row by a one-hot mask — a dynamic per-row `roll`
    (vmap(jnp.roll)) lowers to a per-row dynamic lane permute that alone
    cost ~8 ms for 8k rows on v5e (measured r5: it was the entire
    DLRM-family sparse-update bottleneck, ~85% of the train step). For
    VERY narrow tables (r > 16, i.e. dim <= 8) the static unroll emits up
    to 128 one-hot selects, inflating the HLO and compile time faster
    than the runtime win pays back — those fall back to the dynamic
    roll."""
    r_per_tile = _LANES // dim
    indices = indices.astype(jnp.int32)
    tile_rows = indices // r_per_tile
    padded = jnp.pad(updates.astype(dtype), ((0, 0), (0, _LANES - dim)))
    if r_per_tile == 1:
        return tile_rows, padded
    slot = indices % r_per_tile                       # (n,)
    if r_per_tile > 16:
        # low-dim fallback: one dynamic lane roll per row instead of r
        # unrolled one-hot selects (compile-time guard; see docstring)
        shift = (slot * dim).astype(jnp.int32)
        return tile_rows, jax.vmap(jnp.roll)(padded, shift)
    out = None
    for s in range(r_per_tile):
        rolled = jnp.roll(padded, s * dim, axis=1)    # static lane rotate
        # select, not multiply: 0 * NaN would smear a non-finite update
        # into the other unpacked rows sharing this tile
        sel = jnp.where((slot == s)[:, None], rolled,
                        jnp.zeros_like(rolled))
        out = sel if out is None else out + sel
    return tile_rows, out


def _dedup_tile_updates(tile_rows, tile_upds):
    """Combine same-tile updates so a scatter kernel sees DISTINCT rows:
    sort → segment-sum → per-segment target row (-1 marks invalid/pad
    slots) → pad to a _SCATTER_B multiple. Returns
    (target (m,), summed (m, 128), rep (m,), m) where rep[s] is one
    original position whose update landed in segment s (for callers that
    need a representative forward tile)."""
    m = tile_rows.shape[0]
    order = jnp.argsort(tile_rows)
    srows = tile_rows[order]
    supds = tile_upds[order]
    first = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                             srows[1:] != srows[:-1]])
    seg = jnp.cumsum(first) - 1                      # (m,) segment ids
    summed = jax.ops.segment_sum(supds, seg, num_segments=m,
                                 indices_are_sorted=True)
    target = jax.ops.segment_max(srows, seg, num_segments=m,
                                 indices_are_sorted=True)
    rep = jax.ops.segment_max(order, seg, num_segments=m,
                              indices_are_sorted=True)
    num_unique = seg[-1] + 1
    valid = jnp.arange(m) < num_unique
    target = jnp.where(valid, target, -1).astype(jnp.int32)
    # empty segments get INT_MIN from segment_max; mask to a safe index so
    # downstream takes never depend on fill behavior (their rows carry
    # target=-1 and are skipped by the kernels regardless)
    rep = jnp.where(valid, rep, 0)

    pad_n = (-m) % _SCATTER_B
    if pad_n:
        target = jnp.pad(target, (0, pad_n), constant_values=-1)
        summed = jnp.pad(summed, ((0, pad_n), (0, 0)))
        rep = jnp.pad(rep, (0, pad_n))
        m += pad_n
    return target, summed, rep, m


def _dedup_and_scatter(view, tile_rows, tile_upds, interpret):
    target, summed, _, m = _dedup_tile_updates(tile_rows, tile_upds)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m // _SCATTER_B,),
        in_specs=[
            pl.BlockSpec((_SCATTER_B, _LANES), lambda i, idx: (i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((_SCATTER_B, 1, _LANES), view.dtype),
            pltpu.SemaphoreType.DMA((_SCATTER_B,)),
            pltpu.SemaphoreType.DMA((_SCATTER_B,)),
        ],
    )
    return pl.pallas_call(
        _scatter_unique_kernel,
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
        interpret=interpret,
    )(target, summed.astype(view.dtype), view)


def _scatter_write_kernel(idx_ref, val_ref, tbl_ref, out_ref, wsems):
    """Write-ONLY scatter: out[row] = val for _SCATTER_B distinct rows per
    grid step (row < 0 skipped). No read DMA: callers that kept the
    forward-gathered tiles compute new = fwd_tile + summed_update in XLA
    and this kernel just lands the rows — half the random-HBM traffic of
    the RMW form (the update side of the reference's atomicAdd backward,
    embedding.cu:173-224, with distinctness + precomputed values replacing
    atomicity)."""
    i = pl.program_id(0)
    for s in range(_SCATTER_B):            # static unroll: issue all writes
        row = idx_ref[i * _SCATTER_B + s]

        @pl.when(row >= 0)
        def _():
            pltpu.make_async_copy(
                val_ref.at[pl.ds(s, 1), :], out_ref.at[pl.ds(row, 1), :],
                wsems.at[s]).start()
    for s in range(_SCATTER_B):            # drain before the next block
        row = idx_ref[i * _SCATTER_B + s]

        @pl.when(row >= 0)
        def _():
            pltpu.make_async_copy(
                val_ref.at[pl.ds(s, 1), :], out_ref.at[pl.ds(row, 1), :],
                wsems.at[s]).wait()


def scatter_write_rows_packed(view: jax.Array, indices: jax.Array,
                              updates: jax.Array, fwd_tiles: jax.Array,
                              dim: int,
                              interpret: bool = False) -> jax.Array:
    """Sparse-SGD update WITHOUT the RMW read: the caller passes the
    forward-gathered packed tiles (one per lookup, same order as
    `indices`), so each unique target tile's new value is
    fwd_tile + sum(updates landing in it), computed in XLA, and the
    Pallas kernel performs pure writes.

    view      : (vrows, 128) packed table (donated/aliased)
    indices   : (n,) int in UNPACKED row space — duplicates allowed
    updates   : (n, dim) pre-scaled deltas (e.g. -lr * row_cotangent)
    fwd_tiles : (n, 128) the tile each lookup read in the forward pass
    """
    tile_rows, tile_upds = _pack_tile_updates(indices, updates, dim,
                                              view.dtype)
    target, summed, rep, m = _dedup_tile_updates(tile_rows, tile_upds)
    # any duplicate's forward tile is the same pre-update value, so the
    # representative original position's tile stands in for the segment
    vals = (jnp.take(fwd_tiles, rep, axis=0).astype(view.dtype)
            + summed.astype(view.dtype))
    return scatter_write_tiles(view, target, vals, interpret=interpret)


def scatter_write_tiles(view: jax.Array, target: jax.Array,
                        vals: jax.Array,
                        interpret: bool = False) -> jax.Array:
    """Pure-write scatter of whole (1, 128) tiles at DISTINCT view rows.

    PRECONDITIONS (the caller establishes them, e.g. via
    _dedup_tile_updates): targets are distinct; target < 0 marks a pad
    slot to skip; len(target) is a _SCATTER_B multiple. Used by the write-
    only sparse-SGD update and by the stateful (momentum/Adam) sparse
    update, which writes the new weight AND state tiles this way.

    view   : (vrows, 128) (donated/aliased)
    target : (m,) int32, m % _SCATTER_B == 0
    vals   : (m, 128) new tile values
    """
    m = target.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m // _SCATTER_B,),
        in_specs=[
            pl.BlockSpec((_SCATTER_B, _LANES), lambda i, idx: (i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((_SCATTER_B,)),
        ],
    )
    return pl.pallas_call(
        _scatter_write_kernel,
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
        interpret=interpret,
    )(target, vals.astype(view.dtype), view)


def sharded_scatter_add_packed(mesh, row_axes, view, indices, updates,
                               dim: int, interpret: bool = False):
    """Multi-chip form of scatter_add_rows_packed: the packed (vrows, 128)
    view is row-block sharded over `row_axes` of `mesh`; indices/updates
    are replicated. Under shard_map each device masks the updates to its
    row block (masked slots get row = -1, which the kernel skips) and
    runs the single-chip RMW kernel on its local block — the multi-chip
    analog of the reference's per-device atomicAdd into its own table
    replica partition (embedding.cu:173-224).

    view    : (vrows, 128) global packed table
    indices : (n,) int32 in UNPACKED row space, replicated
    updates : (n, dim), replicated
    """
    import inspect

    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map as _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map
    # the replication-check kwarg was renamed check_rep -> check_vma
    _params = inspect.signature(_shard_map).parameters
    _ckw = {"check_vma": False} if "check_vma" in _params else \
        {"check_rep": False}

    def smap(f, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **_ckw)

    r_per_tile = _LANES // dim
    vrows = view.shape[0]
    nshards = 1
    for a in row_axes:
        nshards *= mesh.shape[a]
    block = vrows // nshards             # packed rows per shard

    def local_update(tbl_shard, idx, upd):
        # linear shard index over the row axes
        import jax as _jax
        sid = jnp.zeros((), jnp.int32)
        for a in row_axes:
            sid = sid * mesh.shape[a] + _jax.lax.axis_index(a)
        lo = sid * block * r_per_tile          # unpacked-row lower bound
        hi = lo + block * r_per_tile
        local = idx - lo
        in_block = (idx >= lo) & (idx < hi)
        local = jnp.where(in_block, local, -(r_per_tile + 1))
        return scatter_add_rows_packed(tbl_shard, local, upd, dim,
                                       interpret=interpret)

    return smap(
        local_update,
        in_specs=(P(tuple(row_axes)), P(), P()),
        out_specs=P(tuple(row_axes)),
    )(view, indices.astype(jnp.int32), updates)


def stacked_embedding_bag(tables, indices, aggr: str = "sum",
                          interpret: bool = False):
    """Fused multi-table bag on the Pallas kernel.

    tables  : (T, rows, dim)
    indices : (batch, T, bag)
    returns : (batch, T, dim)

    The T tables are viewed as one (T*rows, dim) table and indices are
    offset by t*rows — the fused-table trick that turns the reference's
    per-table kernel launches (one Embedding op per DLRM table) into a
    single streaming kernel.
    """
    T, rows, dim = tables.shape
    batch = indices.shape[0]
    offs = (jnp.arange(T, dtype=jnp.int32) * rows)[None, :, None]
    flat_idx = (indices.astype(jnp.int32) + offs).reshape(batch * T, -1)
    out = embedding_bag(tables.reshape(T * rows, dim), flat_idx, aggr,
                        interpret)
    return out.reshape(batch, T, dim)
