from .embedding_kernel import (embedding_bag, embedding_bag_reference,
                               stacked_embedding_bag, supports)

__all__ = ["embedding_bag", "embedding_bag_reference",
           "stacked_embedding_bag", "supports"]
