from .embedding_kernel import (embedding_bag, embedding_bag_reference,
                               stacked_embedding_bag, supports)
from .topk_kernel import (mips_topk, mips_topk_reference, quantize_query,
                          score_rows_np, topk_select_np)
from .topk_kernel import supports as topk_supports

__all__ = ["embedding_bag", "embedding_bag_reference",
           "stacked_embedding_bag", "supports",
           "mips_topk", "mips_topk_reference", "quantize_query",
           "score_rows_np", "topk_select_np", "topk_supports"]
