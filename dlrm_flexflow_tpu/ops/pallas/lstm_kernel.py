"""VMEM-resident LSTM scan kernel.

Round-4 calibration found the LSTM cell WEIGHT-STREAM-BOUND: of the
~32 us/iteration an NMT-sized cell (b64, h1024, bf16) costs under
lax.scan, ~27 us is re-streaming the (h, 4h) recurrent matrix from HBM —
XLA does not keep scan weights resident in VMEM (BENCHMARKS.md r4).
This kernel pins them: the grid iterates the time dimension (TPU grid
steps run in order), the recurrent weights use a CONSTANT index_map so
pallas keeps their block in VMEM across all steps, and the (b, h)
hidden/cell carries live in VMEM scratch. Per-iteration HBM traffic
drops to the small x-projection block in and h/c blocks out.

The backward pass is a second reverse-order kernel (same residency
trick, wh AND wh^T resident) that RECOMPUTES the gates from the stored
h/c residuals and emits per-step gate cotangents dz; the weight gradient
is then ONE stacked gemm outside the kernel (exactly how XLA's scan vjp
structures it — r4 calibration's 1.25x-fwd backward finding).

Gate order i, f, g, o (torch convention, matching ops/rnn.py).
Reference analog: the NMT runtime's cuDNN LSTM (nmt/lstm.cu:1) — cuDNN
keeps weights on-chip across the sequence the same way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gates(gates, cprev):
    h4 = gates.shape[-1] // 4
    i = jax.nn.sigmoid(gates[:, :h4])
    f = jax.nn.sigmoid(gates[:, h4:2 * h4])
    g = jnp.tanh(gates[:, 2 * h4:3 * h4])
    o = jax.nn.sigmoid(gates[:, 3 * h4:])
    c = f * cprev + i * g
    return i, f, g, o, c


def _fwd_kernel(xp_ref, wh_ref, ys_ref, cs_ref, h_s, c_s):
    i0 = pl.program_id(0)

    @pl.when(i0 == 0)
    def _():
        h_s[...] = jnp.zeros_like(h_s)
        c_s[...] = jnp.zeros_like(c_s)

    hprev = h_s[...]
    gates = xp_ref[0, :, :] + jnp.dot(
        hprev.astype(wh_ref.dtype), wh_ref[...],
        preferred_element_type=jnp.float32)
    _, _, _, o, c = _gates(gates, c_s[...])
    h = o * jnp.tanh(c)
    h_s[...] = h
    c_s[...] = c
    ys_ref[0, :, :] = h
    if cs_ref is not None:
        cs_ref[0, :, :] = c


def _run_fwd(xproj, wh, interpret, with_residuals=True):
    # TIME-MAJOR (T, b, 4h): TPU blocks must keep the last two dims
    # (sublane, lane) aligned — the time dim rides the grid as dim 0.
    # with_residuals=False (the no-gradient primal) skips the (T, b, h)
    # cell-state output nothing would read.
    T, b, h4 = xproj.shape
    h = h4 // 4
    blk = pl.BlockSpec((1, b, h), lambda i: (i, 0, 0))
    shp = jax.ShapeDtypeStruct((T, b, h), jnp.float32)
    kernel = (_fwd_kernel if with_residuals else
              (lambda xp, w, ys, h_s, c_s:
               _fwd_kernel(xp, w, ys, None, h_s, c_s)))
    out = pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, b, h4), lambda i: (i, 0, 0)),
            pl.BlockSpec(wh.shape, lambda i: (0, 0)),   # VMEM-resident
        ],
        out_specs=[blk, blk] if with_residuals else blk,
        out_shape=[shp, shp] if with_residuals else shp,
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),
            pltpu.VMEM((b, h), jnp.float32),
        ],
        interpret=interpret,
    )(xproj, wh)
    return out if with_residuals else (out, None)


def _bwd_kernel(xp_ref, wh_ref, whT_ref, dys_ref, hprev_ref, cprev_ref,
                cs_ref, dzs_ref, dh_s, dc_s):
    i0 = pl.program_id(0)

    @pl.when(i0 == 0)
    def _():
        dh_s[...] = jnp.zeros_like(dh_s)
        dc_s[...] = jnp.zeros_like(dc_s)

    hprev = hprev_ref[0, :, :]
    cprev = cprev_ref[0, :, :]
    gates = xp_ref[0, :, :] + jnp.dot(
        hprev.astype(wh_ref.dtype), wh_ref[...],
        preferred_element_type=jnp.float32)
    i, f, g, o, _ = _gates(gates, cprev)
    c = cs_ref[0, :, :]
    tanh_c = jnp.tanh(c)
    dh = dys_ref[0, :, :] + dh_s[...]
    dc = dc_s[...] + dh * o * (1.0 - tanh_c * tanh_c)
    di = dc * g * i * (1.0 - i)
    df = dc * cprev * f * (1.0 - f)
    dg = dc * i * (1.0 - g * g)
    do = dh * tanh_c * o * (1.0 - o)
    dz = jnp.concatenate([di, df, dg, do], axis=1)
    dzs_ref[0, :, :] = dz
    dh_s[...] = jnp.dot(dz.astype(whT_ref.dtype), whT_ref[...],
                        preferred_element_type=jnp.float32)
    dc_s[...] = dc * f


def _run_bwd(xproj, wh, hs_prev, cs_prev, cs, dys, interpret):
    T, b, h4 = xproj.shape
    h = h4 // 4
    whT = jnp.swapaxes(wh, 0, 1)
    rev = lambda i: (T - 1 - i, 0, 0)
    blk_h = pl.BlockSpec((1, b, h), rev)
    dzs = pl.pallas_call(
        _bwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, b, h4), rev),
            pl.BlockSpec(wh.shape, lambda i: (0, 0)),    # resident
            pl.BlockSpec(whT.shape, lambda i: (0, 0)),   # resident
            blk_h, blk_h, blk_h, blk_h,
        ],
        out_specs=pl.BlockSpec((1, b, h4), rev),
        out_shape=jax.ShapeDtypeStruct((T, b, h4), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),
            pltpu.VMEM((b, h), jnp.float32),
        ],
        interpret=interpret,
    )(xproj, wh, whT, dys, hs_prev, cs_prev, cs)
    return dzs


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def lstm_scan(xproj, wh, interpret=False):
    """ys = LSTM-scan over time of gate pre-activations `xproj`
    (T, b, 4h) float32, TIME-MAJOR (x @ wx + bias, hoisted by the
    caller) with recurrent weights `wh` (h, 4h), zero initial state.
    Returns (T, b, h) float32 hidden states."""
    ys, _ = _run_fwd(xproj, wh, interpret, with_residuals=False)
    return ys


def _vjp_fwd(xproj, wh, interpret):
    ys, cs = _run_fwd(xproj, wh, interpret)
    return ys, (xproj, wh, ys, cs)


def _vjp_bwd(interpret, res, dys):
    xproj, wh, hs, cs = res
    zeros = jnp.zeros_like(hs[:1])
    hs_prev = jnp.concatenate([zeros, hs[:-1]], axis=0)
    cs_prev = jnp.concatenate([zeros, cs[:-1]], axis=0)
    dzs = _run_bwd(xproj, wh, hs_prev, cs_prev, cs,
                   dys.astype(jnp.float32), interpret)
    # dW is ONE stacked gemm over all timesteps (no serial dependence)
    dwh = jnp.einsum("tbh,tbk->hk", hs_prev, dzs,
                     preferred_element_type=jnp.float32)
    return dzs, dwh.astype(wh.dtype)


lstm_scan.defvjp(_vjp_fwd, _vjp_bwd)


def _device_vmem_bytes() -> int:
    """VMEM capacity of the attached TPU core. Known generations by
    device_kind; a conservative 16 MiB floor otherwise (the guide's
    generic per-core figure) so an eligibility decision made for an
    unknown chip under-claims rather than failing Mosaic compilation
    with a VMEM OOM."""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return 16 * 1024 * 1024
    for tag in ("v4", "v5", "v6", "v7"):
        if tag in kind:
            return 128 * 1024 * 1024
    return 16 * 1024 * 1024


def resident_scan_ok(model, batch: int, hidden: int, seq: int,
                     local: bool = False) -> bool:
    """Whether the VMEM-resident kernel path applies: TPU, lane-aligned
    hidden, sublane-aligned batch, and recurrent weights that fit VMEM
    residency comfortably. The budget is sized for the BACKWARD kernel,
    which pins wh AND whT simultaneously, at the model's actual
    compute-dtype width (fp32 doubles it), PLUS the per-step streamed
    blocks (xp/dz at b×4h, h/c residual and output blocks at b×h,
    double-buffered by the pipeline) and the fp32 carry scratch —
    against the ATTACHED device's VMEM with 40% headroom for Mosaic
    temps, not a flat constant (an eligible-looking large-hidden config
    on a 16 MiB-VMEM generation must fall back to lax.scan instead of
    dying in Mosaic compilation).

    `local=False` additionally requires a single-device mesh (a direct
    pallas call cannot run inside GSPMD); `local=True` checks per-SHARD
    eligibility for the shard_map DP route (ops/rnn.py:_dp_shard_axes),
    where `batch` is the per-shard batch."""
    if not getattr(model.config, "pallas_lstm", True):
        return False
    if jax.default_backend() != "tpu":
        return False
    if not local:
        mesh = getattr(model, "mesh", None)
        if mesh is not None and mesh.size > 1:
            return False
    return scan_shape_fits(model, batch, hidden, seq)


def scan_shape_fits(model, batch: int, hidden: int, seq: int,
                    vmem_bytes: int = 0) -> bool:
    """Alignment + VMEM-budget test alone (no backend/mesh gating) —
    shared by the runtime route predicate and the strategy search's
    backend-independent candidate predicate. `vmem_bytes` overrides the
    attached device's VMEM (search prices for the TARGET chip)."""
    itemsize = jnp.dtype(getattr(model.config, "jnp_compute_dtype",
                                 jnp.bfloat16)).itemsize
    resident = 2 * hidden * 4 * hidden * itemsize   # bwd: wh + whT
    # per-grid-step blocks: xp/dz (b,4h) + ~4 (b,h) blocks, x2 for the
    # pipeline's double buffering; carries are fp32 scratch
    blocks = 2 * (batch * 4 * hidden + 4 * batch * hidden) * itemsize
    blocks += 2 * batch * hidden * 4
    budget = 0.6 * (vmem_bytes or _device_vmem_bytes())
    return (hidden % 128 == 0 and batch % 8 == 0 and seq >= 2
            and resident + blocks <= budget)
