"""Pallas TPU fused gather→dot-interaction→top-MLP kernel.

DLRM's "dot" interaction (models/dlrm.py interact_features, reference
dlrm.cc:49-65) lowers as four HLO ops — gather, batched X·Xᵀ, a
strictly-lower-triangle index_select, and the first top-MLP matmul — and
the (B, F, F) pairwise-dot tensor between them round-trips HBM twice even
though only F(F-1)/2 of its F² entries are ever read. This kernel fuses
the whole chain per batch tile so Z = X·Xᵀ lives (F_pad, F_pad) in VMEM
and is consumed by the first top-MLP layer before the next tile starts:
the [B, F, F] buffer never exists in HBM (analysis/hlo_audit.py FLX515
pins that on the lowered HLO).

Structure per grid step (_TILE_B samples):

- gather: the embedding table stays in HBM; the T rows a sample needs
  stream into VMEM with the same deep async-DMA pipeline as
  embedding_kernel._bag_kernel (indices via scalar prefetch, (1, 128)
  chunk DMAs against a (rows*k, 128) view, bag-summed on arrival) and
  land in an (F_pad, d) X buffer under the sample's bottom-MLP row.
- interaction: Z = X·Xᵀ on the MXU, fp32 accumulate, (F_pad, F_pad) in
  registers/VMEM only.
- top-MLP first layer folded in WITHOUT materializing the tril vector:
  y = bottom·W_bot + Σ_f Z[f]·M_f + bias, where M is the tril half of
  the layer weight scattered to (F_pad·F_pad, H) row positions (i·F_pad+j
  for the strictly-lower pairs, zero elsewhere) — a host-side transform
  of the dense weight (`scatter_tril_weight`), so the tril select becomes
  part of the matmul instead of a gather.

The quantized twin (`fused_interaction_quant`) mirrors
embedding_bag_quant: the table lives in HBM at int8/fp8 storage width and
rows are dequantized during the X-buffer accumulate (row scales via
scalar prefetch), so the gather moves 1/4 the bytes.

`fused_interaction` carries a custom_vjp whose backward is plain XLA
(the backward pass re-materializes g_Z — fusing it is out of scope; the
FLX515 audit targets the forward/serving lowering). On non-TPU backends
pass interpret=True (tests do) or use `fused_interaction_reference`,
the unfused jnp oracle.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# samples per grid step: one float32 sublane tile
_TILE_B = 8
_LANES = 128
# outstanding row DMAs (same latency-bound reasoning as embedding_kernel)
_SLOTS = 8
# fp32 sublane granule: X/Z pad F (= T+1 feature rows) up to this
_SUBLANES = 8


def supports(dim: int) -> bool:
    """True if the fused kernel handles this feature width (the gather
    streams (1, 128) lane tiles, like the embedding-bag kernel)."""
    return dim % _LANES == 0


def _pad_features(F: int) -> int:
    return ((F + _SUBLANES - 1) // _SUBLANES) * _SUBLANES


def tril_pairs(F: int):
    """The strictly-lower-triangle (i, j) pairs in DLRM's interaction
    order (models/dlrm.py: ``for i in range(F) for j in range(i)``)."""
    return [(i, j) for i in range(F) for j in range(i)]


def scatter_tril_weight(w_tril: jax.Array, F: int) -> jax.Array:
    """(P, H) tril half of the first top-MLP weight -> (F_pad², H) matrix
    M with row i·F_pad+j = w_tril[p(i,j)] for strictly-lower pairs and
    zero elsewhere, so tril-select + matmul becomes vec(Z)·M."""
    P, H = w_tril.shape
    pairs = tril_pairs(F)
    if P != len(pairs):
        raise ValueError(f"tril weight has {P} rows, F={F} needs "
                         f"{len(pairs)}")
    Fp = _pad_features(F)
    rows = np.array([i * Fp + j for i, j in pairs], dtype=np.int32)
    return jnp.zeros((Fp * Fp, H), w_tril.dtype).at[rows].set(w_tril)


def _interaction_kernel(T: int, bag: int, k: int, F: int, relu: bool,
                        idx_ref, table_ref, bottom_ref, wbot_ref, m_ref,
                        bias_ref, out_ref, xbuf, row_buf, sems):
    """One grid step = _TILE_B samples through gather→Z=X·Xᵀ→first layer.

    table_ref is the (rows*k, 128) chunk view resident in HBM; xbuf is
    the (F_pad, d) per-sample feature stack (row 0 = bottom-MLP output,
    rows 1..T = bag-summed embedding rows, rows F.. = zero padding);
    row_buf/sems run the deep DMA pipeline, crossing sample boundaries
    freely — fetched chunks land in slots, the accumulate into xbuf
    happens at wait time, before the slot is reused.
    """
    tb = out_ref.shape[0]
    Fp = xbuf.shape[0]
    d = xbuf.shape[1]
    total = tb * T * k * bag
    base = pl.program_id(0) * tb * T * bag

    def dma(j, slot):
        # j enumerates (sample, table, chunk, bag) as (((s*T+t)*k+c)*bag+b)
        stc, b = j // bag, j % bag
        st, c = stc // k, stc % k
        view_row = idx_ref[base + st * bag + b] * k + c
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(view_row, 1), :], row_buf.at[slot],
            sems.at[slot])

    depth = min(_SLOTS - 1, total)
    for j in range(depth):
        dma(j, j % _SLOTS).start()
    for s in range(tb):                # static unroll: all bounds small
        xbuf[pl.ds(0, 1), :] = bottom_ref[pl.ds(s, 1), :]
        for t in range(T):
            for c in range(k):
                acc = jnp.zeros((1, _LANES), jnp.float32)
                for b in range(bag):
                    j = ((s * T + t) * k + c) * bag + b
                    if j + depth < total:
                        dma(j + depth, (j + depth) % _SLOTS).start()
                    dma(j, j % _SLOTS).wait()
                    acc = acc + row_buf[j % _SLOTS].astype(jnp.float32)
                xbuf[pl.ds(1 + t, 1), c * _LANES:(c + 1) * _LANES] = acc
        if Fp > F:
            xbuf[pl.ds(F, Fp - F), :] = jnp.zeros((Fp - F, d), jnp.float32)
        # Z = X·Xᵀ, (F_pad, F_pad) — in VMEM only, never written out
        x = xbuf[:]
        z = lax.dot_general(x, x, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        y = jnp.dot(bottom_ref[pl.ds(s, 1), :], wbot_ref[:],
                    preferred_element_type=jnp.float32)
        # f = 0 (the bottom row) has no strictly-lower pairs; its M rows
        # are zero — skip it statically
        for f in range(1, F):
            y = y + jnp.dot(z[f:f + 1, :],
                            m_ref[f * Fp:(f + 1) * Fp, :],
                            preferred_element_type=jnp.float32)
        y = y + bias_ref[:]
        if relu:
            y = jnp.maximum(y, 0.0)
        out_ref[pl.ds(s, 1), :] = y


def _prep_inputs(indices, bottom, w, d: int, F: int):
    """Shared wrapper plumbing: flatten/pad indices and bottom to a
    whole number of _TILE_B tiles, split the first-layer weight into its
    bottom half and tril-scatter matrix."""
    batch = bottom.shape[0]
    idx = indices.astype(jnp.int32)
    if idx.ndim == 2:
        idx = idx[:, :, None]
    T, bag = idx.shape[1], idx.shape[2]
    if T + 1 != F:
        raise ValueError(f"indices carry {T} tables but F={F}")
    P = len(tril_pairs(F))
    if w.shape[0] != d + P:
        raise ValueError(f"first-layer weight expects {d + P} input "
                         f"features (d={d} + {P} pairs), got {w.shape[0]}")
    padded = ((batch + _TILE_B - 1) // _TILE_B) * _TILE_B
    idx_flat = jnp.zeros((padded * T * bag,), jnp.int32)
    idx_flat = idx_flat.at[: batch * T * bag].set(idx.reshape(-1))
    bot = jnp.zeros((padded, d), jnp.float32)
    bot = bot.at[:batch].set(bottom.astype(jnp.float32))
    w_bot = w[:d].astype(jnp.float32)
    m = scatter_tril_weight(w[d:].astype(jnp.float32), F)
    return idx_flat, bot, w_bot, m, padded, T, bag


def _pallas_fused(table, indices, bottom, w, bias, relu, interpret):
    batch = bottom.shape[0]
    rows, d = table.shape
    if not supports(d):
        raise ValueError(f"pallas fused_interaction needs dim % {_LANES} "
                         f"== 0, got {d}; use fused_interaction_reference")
    F = indices.shape[1] + 1
    Fp = _pad_features(F)
    k = d // _LANES
    H = w.shape[1]
    idx_flat, bot, w_bot, m, padded, T, bag = _prep_inputs(
        indices, bottom, w, d, F)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(padded // _TILE_B,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),             # table (HBM)
            pl.BlockSpec((_TILE_B, d), lambda i, idx: (i, 0)),
            pl.BlockSpec((d, H), lambda i, idx: (0, 0)),   # w_bot
            pl.BlockSpec((Fp * Fp, H), lambda i, idx: (0, 0)),  # M
            pl.BlockSpec((1, H), lambda i, idx: (0, 0)),   # bias
        ],
        out_specs=pl.BlockSpec((_TILE_B, H), lambda i, idx: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((Fp, d), jnp.float32),
            pltpu.VMEM((_SLOTS, 1, _LANES), table.dtype),
            pltpu.SemaphoreType.DMA((_SLOTS,)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_interaction_kernel, T, bag, k, F, relu),
        out_shape=jax.ShapeDtypeStruct((padded, H), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx_flat, table.reshape(rows * k, _LANES), bot, w_bot, m,
      bias.astype(jnp.float32).reshape(1, H))
    return out[:batch]


def fused_interaction_reference(table, indices, bottom, w, bias,
                                relu: bool = True):
    """Unfused jnp oracle/fallback: gather → stack → X·Xᵀ → tril →
    concat → first top-MLP layer, fp32 throughout — the composition the
    kernel must match (and exactly what interact_features + the first
    create_mlp dense build as separate ops)."""
    idx = indices.astype(jnp.int32)
    if idx.ndim == 2:
        idx = idx[:, :, None]
    batch, T, _ = idx.shape
    F = T + 1
    emb = jnp.sum(jnp.take(table, idx, axis=0).astype(jnp.float32), axis=2)
    x = jnp.concatenate(
        [bottom.astype(jnp.float32)[:, None, :], emb], axis=1)  # (b, F, d)
    z = lax.dot_general(x, x, (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32)     # (b, F, F)
    sel = np.array([i * F + j for i, j in tril_pairs(F)], dtype=np.int32)
    zt = z.reshape(batch, F * F)[:, sel]
    cat = jnp.concatenate([bottom.astype(jnp.float32), zt], axis=1)
    y = (jnp.dot(cat, w.astype(jnp.float32),
                 preferred_element_type=jnp.float32)
         + bias.astype(jnp.float32))
    return jnp.maximum(y, 0.0) if relu else y


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def fused_interaction(table, indices, bottom, w, bias,
                      relu: bool = True, interpret: bool = False):
    """Fused gather→dot-interaction→first-top-MLP-layer.

    table   : (rows, d) float, d % 128 == 0 — T tables concatenated
              row-wise, indices pre-offset into the concatenated space
    indices : (batch, T) or (batch, T, bag) int
    bottom  : (batch, d) bottom-MLP output
    w       : (d + F(F-1)/2, H) first top-MLP weight (F = T+1)
    bias    : (H,)
    returns : (batch, H) fp32, optionally relu'd.
    """
    return _pallas_fused(table, indices, bottom, w, bias, relu, interpret)


def _fused_fwd(table, indices, bottom, w, bias, relu, interpret):
    out = _pallas_fused(table, indices, bottom, w, bias, relu, interpret)
    # zero-size spec carries the table's static shape/dtype for backward
    spec = jnp.zeros((table.shape[0], 0), table.dtype)
    idx = indices.astype(jnp.int32)
    if idx.ndim == 2:
        idx = idx[:, :, None]
    emb = jnp.sum(jnp.take(table, idx, axis=0).astype(jnp.float32), axis=2)
    return out, (spec, indices, idx, emb, bottom, w, out)


def _fused_bwd(relu, interpret, res, g):
    """Plain-XLA backward of the fused composition (the forward-only
    fusion is the perf claim; backward re-materializes g_Z)."""
    spec, indices, idx, emb, bottom, w, y = res
    batch, T, bag = idx.shape
    F = T + 1
    d = bottom.shape[1]
    x = jnp.concatenate(
        [bottom.astype(jnp.float32)[:, None, :], emb], axis=1)
    z = lax.dot_general(x, x, (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32)
    sel = np.array([i * F + j for i, j in tril_pairs(F)], dtype=np.int32)
    zt = z.reshape(batch, F * F)[:, sel]
    cat = jnp.concatenate([bottom.astype(jnp.float32), zt], axis=1)

    g = g.astype(jnp.float32)
    if relu:
        g = jnp.where(y > 0.0, g, 0.0)
    dw = jnp.dot(cat.T, g, preferred_element_type=jnp.float32)
    db = jnp.sum(g, axis=0)
    g_cat = jnp.dot(g, w.astype(jnp.float32).T,
                    preferred_element_type=jnp.float32)
    g_bottom = g_cat[:, :d]
    g_z_flat = jnp.zeros((batch, F * F), jnp.float32)
    g_z_flat = g_z_flat.at[:, sel].set(g_cat[:, d:])
    g_z = g_z_flat.reshape(batch, F, F)
    # dX = (g_Z + g_Zᵀ)·X
    dx = lax.dot_general(g_z + jnp.swapaxes(g_z, 1, 2), x,
                         (((2,), (1,)), ((0,), (0,))),
                         preferred_element_type=jnp.float32)
    g_bottom = g_bottom + dx[:, 0, :]
    # rows of one bag share the sample/table gradient (sum aggregation)
    g_rows = jnp.repeat(dx[:, 1:, :].reshape(batch * T, d), bag, axis=0)
    flat = idx.reshape(-1)
    order = jnp.argsort(flat)
    dtable = jax.ops.segment_sum(
        g_rows[order], flat[order], num_segments=spec.shape[0],
        indices_are_sorted=True).astype(spec.dtype)
    return (dtable, np.zeros(indices.shape, dtype=jax.dtypes.float0),
            g_bottom.astype(bottom.dtype), dw.astype(w.dtype), db)


fused_interaction.defvjp(_fused_fwd, _fused_bwd)


# ---- quantized-storage twin (int8/fp8 table, row-wise scales) ----------
# Same contract as embedding_bag_quant: the table lives in HBM at the
# STORAGE dtype, each (1, 128) chunk is dequantized during the X-buffer
# accumulate (scale via scalar prefetch), and the math from X on is
# identical to the fp32 kernel. Serving-path only — no vjp, matching
# embedding_bag_quant.


def _interaction_kernel_quant(T: int, bag: int, k: int, F: int, relu: bool,
                              idx_ref, scale_ref, table_ref, bottom_ref,
                              wbot_ref, m_ref, bias_ref, out_ref, xbuf,
                              row_buf, sems):
    tb = out_ref.shape[0]
    Fp = xbuf.shape[0]
    d = xbuf.shape[1]
    total = tb * T * k * bag
    base = pl.program_id(0) * tb * T * bag

    def dma(j, slot):
        stc, b = j // bag, j % bag
        st, c = stc // k, stc % k
        view_row = idx_ref[base + st * bag + b] * k + c
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(view_row, 1), :], row_buf.at[slot],
            sems.at[slot])

    depth = min(_SLOTS - 1, total)
    for j in range(depth):
        dma(j, j % _SLOTS).start()
    for s in range(tb):
        xbuf[pl.ds(0, 1), :] = bottom_ref[pl.ds(s, 1), :]
        for t in range(T):
            for c in range(k):
                acc = jnp.zeros((1, _LANES), jnp.float32)
                for b in range(bag):
                    j = ((s * T + t) * k + c) * bag + b
                    if j + depth < total:
                        dma(j + depth, (j + depth) % _SLOTS).start()
                    dma(j, j % _SLOTS).wait()
                    scale = scale_ref[idx_ref[base + (s * T + t) * bag + b]]
                    acc = acc + row_buf[j % _SLOTS].astype(jnp.float32) \
                        * scale
                xbuf[pl.ds(1 + t, 1), c * _LANES:(c + 1) * _LANES] = acc
        if Fp > F:
            xbuf[pl.ds(F, Fp - F), :] = jnp.zeros((Fp - F, d), jnp.float32)
        x = xbuf[:]
        z = lax.dot_general(x, x, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        y = jnp.dot(bottom_ref[pl.ds(s, 1), :], wbot_ref[:],
                    preferred_element_type=jnp.float32)
        for f in range(1, F):
            y = y + jnp.dot(z[f:f + 1, :],
                            m_ref[f * Fp:(f + 1) * Fp, :],
                            preferred_element_type=jnp.float32)
        y = y + bias_ref[:]
        if relu:
            y = jnp.maximum(y, 0.0)
        out_ref[pl.ds(s, 1), :] = y


def fused_interaction_quant(q_table, scales, indices, bottom, w, bias,
                            relu: bool = True, interpret: bool = False):
    """fused_interaction over a QUANTIZED table with in-kernel dequant.

    q_table : (rows, d) int8 / float8_e4m3fn, d % 128 == 0
    scales  : (rows,) fp32 row scales (symmetric codec, quant/codec.py)
    Everything else as fused_interaction; matches
    ``fused_interaction_quant_reference`` (dequantize-then-interact).
    """
    batch = bottom.shape[0]
    rows, d = q_table.shape
    if not supports(d):
        raise ValueError(f"pallas fused_interaction_quant needs dim % "
                         f"{_LANES} == 0, got {d}; use "
                         f"fused_interaction_quant_reference")
    F = indices.shape[1] + 1
    Fp = _pad_features(F)
    k = d // _LANES
    H = w.shape[1]
    idx_flat, bot, w_bot, m, padded, T, bag = _prep_inputs(
        indices, bottom, w, d, F)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(padded // _TILE_B,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((_TILE_B, d), lambda i, idx, scl: (i, 0)),
            pl.BlockSpec((d, H), lambda i, idx, scl: (0, 0)),
            pl.BlockSpec((Fp * Fp, H), lambda i, idx, scl: (0, 0)),
            pl.BlockSpec((1, H), lambda i, idx, scl: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_TILE_B, H), lambda i, idx, scl: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((Fp, d), jnp.float32),
            pltpu.VMEM((_SLOTS, 1, _LANES), q_table.dtype),
            pltpu.SemaphoreType.DMA((_SLOTS,)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_interaction_kernel_quant, T, bag, k, F, relu),
        out_shape=jax.ShapeDtypeStruct((padded, H), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx_flat, scales.astype(jnp.float32),
      q_table.reshape(rows * k, _LANES), bot, w_bot, m,
      bias.astype(jnp.float32).reshape(1, H))
    return out[:batch]


def fused_interaction_quant_reference(q_table, scales, indices, bottom,
                                      w, bias, relu: bool = True):
    """Oracle: dequantize the gathered rows, then the unfused
    composition — the contract fused_interaction_quant must match."""
    idx = indices.astype(jnp.int32)
    if idx.ndim == 2:
        idx = idx[:, :, None]
    deq = (jnp.take(q_table, idx, axis=0).astype(jnp.float32)
           * jnp.take(scales.astype(jnp.float32), idx, axis=0)[..., None])
    emb = jnp.sum(deq, axis=2)
    batch, T = idx.shape[0], idx.shape[1]
    F = T + 1
    x = jnp.concatenate(
        [bottom.astype(jnp.float32)[:, None, :], emb], axis=1)
    z = lax.dot_general(x, x, (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32)
    sel = np.array([i * F + j for i, j in tril_pairs(F)], dtype=np.int32)
    zt = z.reshape(batch, F * F)[:, sel]
    cat = jnp.concatenate([bottom.astype(jnp.float32), zt], axis=1)
    y = (jnp.dot(cat, w.astype(jnp.float32),
                 preferred_element_type=jnp.float32)
         + bias.astype(jnp.float32))
    return jnp.maximum(y, 0.0) if relu else y
