"""FusedDotInteraction operator — gather→X·Xᵀ→tril→first-top-MLP-layer
as ONE op.

DLRM's "dot" interaction builds as five graph ops (embedding gather,
stack-concat, BatchMatmul, tril IndexSelect, first top-MLP Linear) whose
(B, F, F) pairwise-dot tensor round-trips HBM between them. This op owns
the whole chain — the stacked embedding table, the first top-MLP layer's
weight/bias — and on TPU lowers it through the fused Pallas kernel
(ops/pallas/interaction_kernel.py), so the interaction tensor lives only
in VMEM (pinned by analysis/hlo_audit FLX515). Everywhere else (CPU mesh,
unsupported width, multi-chip GSPMD, host offload) it falls back to the
unfused jnp composition — same math, autodiff'd directly.

Opt-in: build_dlrm(..., fuse_interaction=True) replaces the five-op chain
with this op for uniform-table "dot" configs; the default graph is
unchanged. Batch-data-parallel only — the table is replicated (this is
the serving/small-table shape; row-sharded tables keep the unfused path
with the overlapped exchange).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp

from ..core.initializers import (DEFAULT_BIAS_INIT, DEFAULT_KERNEL_INIT,
                                 GlorotUniform)
from ..core.op import Op, ParamDef
from ..parallel.pconfig import ParallelConfig
from .common import apply_activation
from .pallas.interaction_kernel import (fused_interaction,
                                        fused_interaction_reference,
                                        supports, tril_pairs)


class FusedDotInteraction(Op):
    type_name = "FusedDotInteraction"

    def __init__(self, model, sparse_idx, bottom, num_entries: int,
                 out_dim: int, activation: str = "relu",
                 emb_initializer=None, kernel_initializer=None,
                 bias_initializer=None, name: Optional[str] = None):
        """sparse_idx: (batch, T, bag) int; bottom: (batch, d) —
        the bottom-MLP output. `num_entries` is rows PER TABLE (uniform
        tables, stacked row-wise like EmbeddingBagStacked); `out_dim` is
        the first top-MLP layer's width."""
        super().__init__(model, [sparse_idx, bottom], name)
        if sparse_idx.num_dims != 3:
            raise ValueError("FusedDotInteraction expects (batch, T, bag) "
                             "sparse indices")
        if bottom.num_dims != 2:
            raise ValueError("FusedDotInteraction expects a rank-2 "
                             "bottom-MLP input")
        batch, T, bag = sparse_idx.shape
        if bottom.shape[0] != batch:
            raise ValueError("batch dim mismatch between sparse and bottom")
        self.num_tables = int(T)
        self.num_entries = int(num_entries)
        self.bag = int(bag)
        self.in_dim = int(bottom.shape[1])          # d, the feature width
        self.out_dim = int(out_dim)                 # H, first layer width
        self.activation = activation
        F = self.num_tables + 1
        self.num_pairs = len(tril_pairs(F))
        self.emb_initializer = emb_initializer or GlorotUniform()
        self.kernel_initializer = kernel_initializer or DEFAULT_KERNEL_INIT()
        self.bias_initializer = bias_initializer or DEFAULT_BIAS_INIT()
        # tests flip this to route the Pallas kernel in interpreter mode
        # on non-TPU backends (the gate below stays backend-honest)
        self._interpret = False
        self.outputs = [self._make_output((batch, self.out_dim))]

    def param_defs(self) -> Dict[str, ParamDef]:
        return {
            "table": ParamDef(
                (self.num_tables * self.num_entries, self.in_dim),
                jnp.float32, self.emb_initializer),
            "kernel": ParamDef(
                (self.in_dim + self.num_pairs, self.out_dim),
                jnp.float32, self.kernel_initializer),
            "bias": ParamDef((self.out_dim,), jnp.float32,
                             self.bias_initializer),
        }

    def _use_pallas(self) -> bool:
        # same gate as the embedding kernels: opted in, TPU backend,
        # supported width, single-chip (under a >1-device mesh the op
        # runs inside GSPMD where a direct Pallas call cannot), not
        # host-offloaded
        from .embedding import _pallas_gate
        return _pallas_gate(self.model, self.name, supports(self.in_dim))

    def apply(self, params, xs, *, training=False, rng=None):
        idx, bottom = xs
        # per-table indices -> the concatenated row space (table t's rows
        # live at [t*rows, (t+1)*rows))
        gid = (idx.astype(jnp.int32)
               + (jnp.arange(self.num_tables, dtype=jnp.int32)
                  * self.num_entries)[None, :, None])
        relu = self.activation == "relu"
        if (self._use_pallas() or self._interpret) \
                and self.activation in ("relu", "none", None):
            out = fused_interaction(params["table"], gid, bottom,
                                    params["kernel"], params["bias"],
                                    relu, self._interpret)
        else:
            out = fused_interaction_reference(
                params["table"], gid, bottom, params["kernel"],
                params["bias"], relu=False)
            out = apply_activation(out, self.activation)
        return [out.astype(bottom.dtype)]

    # -- parallelization ---------------------------------------------------
    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        # batch-DP only: the fused chain keeps its table replicated
        out = []
        for d in feasible_degrees:
            if d <= num_devices:
                out.append(ParallelConfig((d, 1)))
        return out

    # -- cost model --------------------------------------------------------
    def flops_per_sample(self) -> float:
        F = self.num_tables + 1
        return (2.0 * F * F * self.in_dim
                + 2.0 * (self.in_dim + self.num_pairs) * self.out_dim)

    def random_hbm_rows(self, backward: bool = False,
                        raw: bool = False) -> float:
        # the gather half: one random table-row read per lookup (the
        # interaction/matmul half is covered by flops_per_sample)
        if backward:
            return 0.0
        batch = self.inputs[0].shape[0]
        return float(batch * self.num_tables * self.bag)
