"""dlrm_flexflow_tpu — a TPU-native distributed DNN training framework with
the capabilities of FlexFlow/DLRM-FlexFlow (reference: TravisDai/DLRM-FlexFlow).

The reference is a Legion/CUDA task-based MPMD system that auto-discovers
parallelization strategies in the SOAP search space. This framework provides
the same surface — FFModel graph builder, per-op parallelization strategies,
MCMC auto-parallelizer with an execution simulator, DLRM/CNN/NMT model zoo,
PyTorch-golden operator tests — re-designed for TPU: JAX/XLA/Pallas compute,
GSPMD sharding over `jax.sharding.Mesh`, ICI/DCN collectives instead of
Legion DMA/GASNet.
"""

from .config import FFConfig
from .core.model import AnomalyError, FFModel
from .utils.checkpoint import (CheckpointManager, restore_checkpoint,
                               save_checkpoint)
from .utils.delta import DeltaPublisher
from .core.optimizers import AdamOptimizer, Optimizer, SGDOptimizer
from .core.initializers import (ConstantInitializer, GlorotUniform,
                                NormInitializer, UniformInitializer,
                                ZeroInitializer)
from .core.tensor import Tensor
from .parallel.mesh import make_mesh
from .parallel.pconfig import ParallelConfig
from .parallel.distributed import MeshDegraded, MeshReturned
from .utils.watchdog import Deadline, StallReport, WorkerStalled
from .serve import (AutoscaleConfig, Autoscaler, DeadlineExceeded,
                    EmbeddingShardSet, Fleet, FleetRouter,
                    FleetUnavailable, InferenceEngine, Overloaded,
                    Prediction, ReplicaDown, RouterConfig, ServeConfig,
                    ShardDown, ShardTierConfig, ShardTierUnavailable,
                    SnapshotWatcher)

__version__ = "0.1.0"

__all__ = [
    "FFConfig", "FFModel", "Tensor", "AnomalyError",
    "CheckpointManager", "save_checkpoint", "restore_checkpoint",
    "DeltaPublisher",
    "Optimizer", "SGDOptimizer", "AdamOptimizer",
    "GlorotUniform", "ZeroInitializer", "UniformInitializer",
    "NormInitializer", "ConstantInitializer",
    "ParallelConfig", "make_mesh",
    "MeshDegraded", "MeshReturned", "WorkerStalled", "StallReport",
    "Deadline",
    "InferenceEngine", "ServeConfig", "Prediction", "Overloaded",
    "DeadlineExceeded", "SnapshotWatcher",
    "Fleet", "FleetRouter", "FleetUnavailable", "RouterConfig",
    "ReplicaDown", "Autoscaler", "AutoscaleConfig",
    "EmbeddingShardSet", "ShardTierConfig", "ShardDown",
    "ShardTierUnavailable",
]
