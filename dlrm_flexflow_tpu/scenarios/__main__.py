"""CLI for the online-learning scenarios:

    python -m dlrm_flexflow_tpu.scenarios --scenario drifting_zipf
    python -m dlrm_flexflow_tpu.scenarios --scenario diurnal --fast

Prints one JSON verdict (metrics + budgets + pass/fail) and exits 0
only when every budget held. ``--fast`` compresses the day to seconds
(the tier-1 smoke profile); the default profile paces requests by the
trace's interarrival times. ``--no-chaos`` drops the mid-day fault
window (replica outage + torn delta + feedback loss) for debugging a
failing budget without the noise."""

import argparse
import json
import sys

from ..data.replay import SCENARIOS
from .runner import run_scenario


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dlrm_flexflow_tpu.scenarios",
        description="closed-loop online-learning scenario runner")
    ap.add_argument("--scenario", choices=SCENARIOS,
                    default="drifting_zipf")
    ap.add_argument("--steps", type=int, default=None,
                    help="trace length (default 240, 48 with --fast)")
    ap.add_argument("--replicas", type=int, default=None,
                    help="initial fleet size (default 2, 1 with --fast)")
    ap.add_argument("--fast", action="store_true",
                    help="seconds-long smoke profile, no pacing sleeps")
    ap.add_argument("--replace-drift-threshold", type=float,
                    default=None, metavar="TV",
                    help="total-variation divergence that triggers an "
                         "online re-placement (default 0.35, or 0.30 "
                         "with --fast)")
    ap.add_argument("--feedback-spool", type=int, default=256,
                    metavar="N", help="feedback spool capacity in "
                    "batches (default 256)")
    ap.add_argument("--no-chaos", action="store_true",
                    help="skip the mid-scenario fault window")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    verdict = run_scenario(
        args.scenario, steps=args.steps, fast=args.fast,
        replicas=args.replicas,
        drift_threshold=args.replace_drift_threshold,
        feedback_spool=args.feedback_spool,
        chaos=not args.no_chaos, seed=args.seed)
    print(json.dumps(verdict, indent=2, default=str))
    return 0 if verdict["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
