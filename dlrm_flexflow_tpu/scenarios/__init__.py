"""End-to-end online-learning scenarios: a 24h traffic day compressed
into a budgeted pass/fail run (``python -m dlrm_flexflow_tpu.scenarios
--scenario drifting_zipf``). See ``runner.py`` for the harness and
``data/replay.py`` for the traces it drives."""

from .runner import ScenarioBudgets, run_scenario

__all__ = ["ScenarioBudgets", "run_scenario"]
