"""The closed-loop scenario harness: trainer + publisher + serving
fleet + autoscaler + re-placement controller driven through one
compressed traffic day, judged against explicit budgets.

One ``run_scenario`` call wires the WHOLE loop the rest of the repo
builds piecewise:

    TraceReplay ──requests──▶ FleetRouter ──▶ replicas (engines)
         │                        │                ▲
         │ simulated clicks       │ served scores  │ SnapshotWatcher
         ▼                        ▼                │ (delta chain)
    FeedbackSpool ──batches──▶ fit_stream ──▶ DeltaPublisher
                                            (trainer thread)

plus the two control loops riding the traffic: the SLO ``Autoscaler``
(fleet size) and the ``ReplacementController`` (live sketch vs searched
histogram → online hot/cold re-placement). Chaos lands mid-day through
``utils.faults`` (a replica outage, a torn delta, feedback-spool loss)
— the budgets below must hold WITH the chaos active, that's the point.

The judge is deliberately blunt: a scenario returns one dict with the
measured metrics, the budgets they were held to, and ``passed``. AUC is
computed rank-based (Mann–Whitney) over the second half of the day —
served scores against the simulated clicks the model never saw at
serve time — so "the model kept learning" is measured at the serving
edge, not from training loss. Freshness lag is the publisher's tip step
minus the slowest healthy replica's installed version; spool lag is the
landed-but-unconsumed feedback debt.

``fast=True`` compresses the day to seconds (tier-1 smoke: one replica,
no pacing sleeps, tiny model); the full profile paces requests by the
trace's interarrival times and is exercised by the slow test and the
``BENCH_SCENARIO=1`` bench gate.
"""

from __future__ import annotations

import tempfile
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from ..data.replay import FeedbackSpool, TraceReplay, scenario_spec
from ..utils import faults
from ..utils.logging import get_logger

log_scn = get_logger("scenarios")

# one tiny DLRM shape shared by every scenario: 4 × 64-row tables keeps
# compiles in the hundreds of ms on CPU while still giving the placement
# search real hot/cold structure to move
TABLES = 4
ROWS = 64
BAG = 2
DENSE_DIM = 4


@dataclass
class ScenarioBudgets:
    """What the day must hold to pass, chaos included."""

    auc_floor: float = 0.60          # serving-edge AUC, second half
    p99_ms: float = 2000.0           # client-observed, CPU-noise wide
    max_fleet: int = 4               # autoscaler cap = cost ceiling
    freshness_lag: int = 60          # publisher tip - slowest replica
    spool_lag: int = 64              # landed-but-unconsumed feedback
    replacements: Optional[int] = None   # exact count; None = don't judge
    failed: int = 0                  # client requests that raised. Zero.
    step_time_ratio: float = 2.0     # post-swap mean / pre-swap mean

    def judge(self, m: Dict[str, Any]) -> List[str]:
        bad = []
        if m["auc"] < self.auc_floor:
            bad.append(f"auc {m['auc']:.3f} < floor {self.auc_floor:g}")
        if m["p99_ms"] is not None and m["p99_ms"] > self.p99_ms:
            bad.append(f"p99 {m['p99_ms']:.1f} ms > {self.p99_ms:g} ms")
        if m["fleet_max"] > self.max_fleet:
            bad.append(f"fleet grew to {m['fleet_max']} > cap "
                       f"{self.max_fleet}")
        if m["freshness_lag"] > self.freshness_lag:
            bad.append(f"freshness lag {m['freshness_lag']} steps > "
                       f"{self.freshness_lag}")
        if m["spool_lag"] > self.spool_lag:
            bad.append(f"feedback spool lag {m['spool_lag']} > "
                       f"{self.spool_lag}")
        if self.replacements is not None and \
                m["replacements"] != self.replacements:
            bad.append(f"{m['replacements']} re-placements != expected "
                       f"{self.replacements}")
        if m["failed"] > self.failed:
            bad.append(f"{m['failed']} failed requests (budget "
                       f"{self.failed})")
        if m["step_time_ratio"] is not None and \
                m["step_time_ratio"] > self.step_time_ratio:
            bad.append(f"step time ratio {m['step_time_ratio']:.2f} > "
                       f"{self.step_time_ratio:g}")
        return bad


def default_budgets(scenario: str, fast: bool) -> ScenarioBudgets:
    b = ScenarioBudgets()
    if scenario == "drifting_zipf":
        # the churn must trigger EXACTLY one online re-placement
        b.replacements = 1
    else:
        # a QPS wave (diurnal) or flash crowd moves load, not the id
        # DISTRIBUTION — re-planning placement for it would be thrash
        b.replacements = 0
    if fast:
        b.p99_ms = 5000.0       # tier-1 machines are noisy
        # sub-ms steps + ~7 post-swap samples make the ratio a coarse
        # smoke check here; the paced profile holds the real bar
        b.step_time_ratio = 6.0
        b.auc_floor = 0.55      # the compressed day trains on ~10x
        # fewer clicks; untrained serves ~0.50, so this still proves
        # the loop learned
    return b


def auc_rank(labels: np.ndarray, scores: np.ndarray) -> float:
    """Rank-based (Mann–Whitney) AUC; 0.5 for degenerate label sets."""
    y = np.asarray(labels, np.float64).reshape(-1)
    s = np.asarray(scores, np.float64).reshape(-1)
    pos = int((y > 0.5).sum())
    neg = y.size - pos
    if pos == 0 or neg == 0:
        return 0.5
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(y.size, np.float64)
    ranks[order] = np.arange(1, y.size + 1)
    # midranks over score ties, else AUC depends on sort stability
    s_sorted = s[order]
    i = 0
    while i < y.size:
        j = i
        while j + 1 < y.size and s_sorted[j + 1] == s_sorted[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    return float((ranks[y > 0.5].sum() - pos * (pos + 1) / 2.0)
                 / (pos * neg))


def _build_model(seed: int):
    import dlrm_flexflow_tpu as ff
    from ..models.dlrm import DLRMConfig, build_dlrm
    from ..parallel.mesh import make_mesh

    dcfg = DLRMConfig(embedding_size=[ROWS] * TABLES,
                      embedding_bag_size=BAG,
                      sparse_feature_size=8,
                      mlp_bot=[DENSE_DIM, 16, 8],
                      mlp_top=[40, 16, 1])
    model = ff.FFModel(ff.FFConfig(batch_size=8, seed=seed))
    build_dlrm(model, dcfg)
    import jax
    model.compile(ff.SGDOptimizer(lr=0.3), "mean_squared_error",
                  ["mse"], mesh=make_mesh(devices=jax.devices()[:1]))
    model.init_layers()
    return model


def run_scenario(scenario: str, steps: Optional[int] = None,
                 fast: bool = False, replicas: Optional[int] = None,
                 drift_threshold: Optional[float] = None,
                 feedback_spool: int = 256,
                 budgets: Optional[ScenarioBudgets] = None,
                 chaos: bool = True, seed: int = 0,
                 checkpoint_dir: Optional[str] = None) -> Dict[str, Any]:
    """Run one scenario end to end; returns the verdict dict (see
    module docstring). Raises only on setup errors — a failing budget
    is a ``passed: False`` verdict, not an exception."""
    import dlrm_flexflow_tpu as ff
    from ..serve.replace import ReplaceConfig, ReplacementController

    steps = int(steps if steps is not None
                else (48 if fast else 240))
    replicas = int(replicas if replicas is not None else (1 if fast
                                                          else 2))
    budgets = budgets or default_budgets(scenario, fast)
    spec = scenario_spec(scenario, steps=steps, batch=8, seed=seed,
                         rows=ROWS)
    replay = TraceReplay(TABLES, ROWS, BAG, DENSE_DIM, spec)
    tmp = None
    if checkpoint_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="ff-scenario-")
        checkpoint_dir = tmp.name

    trainer = _build_model(seed=3)
    pub = ff.DeltaPublisher(trainer, checkpoint_dir,
                            row_delta_min_elems=0)

    # boot-time warm-up: a served model that has never seen the hotness
    # signal judges ~0.5 AUC no matter how well the loop works later.
    # An online system starts from SOME trained checkpoint; ours is a
    # short replay prefix trained synchronously, published as the base.
    warm_src = max(32, steps // 4)       # distinct prefix batches...
    warm = 6 * warm_src                  # ...epoched enough to learn
    trainer.fit_stream(
        lambda i: {**replay.request(i % warm_src),
                   "label": replay.labels(i % warm_src)},
        steps=warm, publisher=pub, publish_every=warm, verbose=False)

    spool = FeedbackSpool(capacity=feedback_spool)
    publish_every = 5 if fast else 10
    train_err: List[BaseException] = []

    def _train():
        try:
            trainer.fit_stream(spool.source, steps=None, publisher=pub,
                               publish_every=publish_every,
                               verbose=False)
        except BaseException as e:   # noqa: BLE001 — judged, not raised
            train_err.append(e)

    def _factory(i):
        return _build_model(seed=3)

    poll_s = 0.05 if fast else 0.25
    fleet = ff.Fleet.build(_factory, replicas,
                           ff.ServeConfig(max_batch=8,
                                          queue_capacity=1024,
                                          cache_rows=ROWS // 4,
                                          poll_s=poll_s))
    router = ff.FleetRouter(
        fleet, ff.RouterConfig(retries=4, cooldown_s=0.3,
                               health_interval_s=poll_s,
                               probe_deadline_s=30.0)).start()
    watchers = [ff.SnapshotWatcher(rep.engine, checkpoint_dir,
                                   poll_s=poll_s).start()
                for rep in fleet.replicas]
    scaler = ff.Autoscaler(
        router, ff.AutoscaleConfig(min_replicas=replicas,
                                   max_replicas=budgets.max_fleet,
                                   interval_s=poll_s,
                                   cooldown_s=4 * poll_s)).start()
    # the TV of two empirical sketches has a sampling-noise floor of
    # ~0.2 over this id space at a ~1k-draw window; real churn measures
    # ~0.45+. The compressed day needs the tighter threshold to catch
    # the churn before the trace ends; the paced day has draws to spare.
    if drift_threshold is None:
        drift_threshold = 0.30 if fast else 0.35
    rcfg = ReplaceConfig(
        drift_threshold=drift_threshold,
        sustain=2 if fast else 3,
        cooldown_s=2.0 if fast else 10.0,
        min_observations=512 if fast else 2048,
        window=1024 if fast else 4096,
        budget=0 if fast else 20, seed=seed)
    controller = ReplacementController(router, config=rcfg)
    # the reference distribution IS the warm-up prefix the served
    # placement was trained on — not a noisy first-live-window guess
    controller.seed_baseline(replay.request(i) for i in range(warm_src))

    trainer_t = threading.Thread(target=_train, daemon=True,
                                 name="ff-scenario-trainer")
    trainer_t.start()

    # chaos lands in one mid-day window: a finite replica outage (the
    # router must absorb it), one torn delta (the watcher must reject
    # and recover), and lossy feedback (the spool must keep feeding)
    chaos_lo, chaos_hi = int(steps * 0.55), int(steps * 0.70)
    plan = faults.FaultPlan(
        replica_down={1: 3} if replicas > 1 else {},
        torn_deltas=1, feedback_loss_p=0.05) if chaos else None

    failed = 0
    errors: List[str] = []
    judged: List[Any] = []          # (step, labels, scores)
    step_ms: List[float] = []
    fleet_max = replicas
    swap_step: Optional[int] = None
    timeout = 60.0 if fast else 30.0
    t_run = time.monotonic()
    chaos_ctx = None
    try:
        for i in range(steps):
            if plan is not None and i == chaos_lo:
                chaos_ctx = faults.active_plan(plan)
                chaos_ctx.__enter__()
                log_scn.info("chaos window open at step %d", i)
            if chaos_ctx is not None and i == chaos_hi:
                chaos_ctx.__exit__(None, None, None)
                chaos_ctx = None
                log_scn.info("chaos window closed at step %d", i)
            if not fast:
                time.sleep(min(spec.interarrival_s(i), 0.05))
            feats = replay.request(i)
            t0 = time.monotonic()
            scores = None
            try:
                pred = router.predict(feats, timeout=timeout)
                scores = np.asarray(pred.scores)
            except Exception as e:   # noqa: BLE001 — budgeted
                failed += 1
                errors.append(f"step {i}: {type(e).__name__}: {e}")
            step_ms.append(1e3 * (time.monotonic() - t0))
            if scores is not None:
                controller.observe(feats)
                if controller.tick() is not None and swap_step is None:
                    swap_step = i
                labels = replay.labels(i, feats)
                judged.append((i, labels, scores))
                spool.offer(feats, labels, scores=scores, step=i)
            fleet_max = max(fleet_max, len(fleet.replicas))
        # drain: let the trainer catch up and the tip propagate
        spool.close()
        trainer_t.join(timeout)
        deadline = time.monotonic() + (5.0 if fast else 15.0)
        tip = int(pub.stats()["last_step"] or 0)
        while time.monotonic() < deadline:
            vers = [int(rep.engine.version) for rep in fleet.replicas]
            if vers and min(vers) >= tip:
                break
            time.sleep(poll_s)
    finally:
        if chaos_ctx is not None:
            chaos_ctx.__exit__(None, None, None)
        controller.close()
        scaler.close()
        for w in watchers:
            w.stop()
        router.close()
        if tmp is not None:
            tmp.cleanup()

    # ---- the judge ---------------------------------------------------
    half = [(lab, sc) for s, lab, sc in judged if s >= steps // 2]
    labels = np.concatenate([l for l, _ in half]) if half else \
        np.zeros((0, 1))
    scores = np.concatenate([s.reshape(-1, 1) for _, s in half]) \
        if half else np.zeros((0, 1))
    rstats = router.stats()
    vers = [int(rep.engine.version) for rep in fleet.replicas]
    tip = int(pub.stats()["last_step"] or 0)
    sp = spool.stats()
    cstats = controller.stats()
    ratio = None
    if swap_step is not None:
        pre = step_ms[:swap_step][-20:]
        # skip the swap itself and the first post-swap dispatches (the
        # re-placed exec warms its AOT cache there); medians + a 1 ms
        # denominator floor keep sub-ms CPU steps from turning one
        # compile blip into a 30x "regression"
        post = step_ms[swap_step + 4:][:20]
        if pre and post:
            ratio = float(np.median(post) / max(np.median(pre), 1.0))
    metrics = {
        "auc": auc_rank(labels, scores),
        "p99_ms": rstats.get("p99_ms"),
        "fleet_max": fleet_max,
        "freshness_lag": max(0, tip - min(vers)) if vers else tip,
        "spool_lag": int(sp["lag"]),
        "replacements": int(cstats["replacements"]),
        "replace_report": cstats["last_report"],
        "failed": failed,
        "step_time_ratio": ratio,
        "swap_step": swap_step,
        "publisher_tip": tip,
        "replica_versions": vers,
        "spool": sp,
        "judged_requests": int(labels.size),
        "trainer_error": str(train_err[0]) if train_err else None,
        "wall_s": time.monotonic() - t_run,
    }
    failures = budgets.judge(metrics)
    if train_err:
        failures.append(f"trainer died: {train_err[0]}")
    verdict = {
        "scenario": scenario,
        "steps": steps,
        "fast": fast,
        "chaos": bool(chaos),
        "passed": not failures,
        "failures": failures,
        "metrics": metrics,
        "budgets": asdict(budgets),
        "errors": errors[:10],
    }
    log_scn.info("scenario %s: %s (%d steps in %.1fs, auc %.3f, "
                 "%d re-placement(s), %d failed)", scenario,
                 "PASS" if verdict["passed"] else "FAIL", steps,
                 metrics["wall_s"], metrics["auc"],
                 metrics["replacements"], failed)
    return verdict
