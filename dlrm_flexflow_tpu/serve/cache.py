"""LRU embedding-row cache for the serving read path.

Host-RESIDENT tables (``--host-tables`` / ZCM strategies — the >HBM DLRM
configuration) pay a numpy gather on the host for every lookup. Online
recommendation traffic is extremely skewed (a few hot users/items
dominate), so the serving engine caches per-sample lookup RESULTS: a
request whose categorical index tuple was seen recently skips the host
gather entirely and only the cold samples touch the table.

Keying is per (op, per-sample index row): the cached value is exactly
``op.host_lookup``'s output for that sample, so cache hits are
bit-identical to the uncached path (the lookup is row-wise across the
batch — each sample's bag gather/reduce never sees its neighbors).

The cache is dropped wholesale on every hot reload (`invalidate`): new
tables mean every cached row is stale. During serving the tables are
otherwise immutable (training scatters never run in the engine), so no
finer-grained invalidation is needed.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict

import numpy as np

from ..analysis.sanitizer import make_lock


class EmbeddingCache:
    """Bounded LRU of per-sample host-table lookup results.

    Thread-safe (the engine's batcher and a stats() reader may race);
    the table gather itself additionally serializes on the model's
    ``_host_lock`` at the call site, same as training's gather.
    """

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._d: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self._lock = make_lock("EmbeddingCache._lock", no_dispatch=True)
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def lookup(self, op, table_params, idx_np: np.ndarray) -> np.ndarray:
        """Per-sample-cached equivalent of
        ``op.host_lookup(table_params, idx_np)``: hit samples come from
        the cache, miss samples go through ONE sub-batch host_lookup and
        are inserted."""
        rows = int(idx_np.shape[0])
        vals = [None] * rows
        miss: list = []
        with self._lock:
            for i in range(rows):
                key = (op.name, idx_np[i].tobytes())
                v = self._d.get(key)
                if v is None:
                    miss.append(i)
                else:
                    self._d.move_to_end(key)
                    vals[i] = v
            self.hits += rows - len(miss)
            self.misses += len(miss)
        if miss:
            sub = op.host_lookup(table_params, idx_np[np.asarray(miss)])
            sub = np.asarray(sub)
            with self._lock:
                for j, i in enumerate(miss):
                    v = np.ascontiguousarray(sub[j])
                    vals[i] = v
                    self._d[(op.name, idx_np[i].tobytes())] = v
                    self._d.move_to_end((op.name, idx_np[i].tobytes()))
                while len(self._d) > self.capacity:
                    self._d.popitem(last=False)
        return np.stack(vals, axis=0)

    def invalidate(self) -> None:
        """Drop everything (hot reload replaced the tables)."""
        with self._lock:
            self._d.clear()
            self.invalidations += 1

    def __len__(self) -> int:
        return len(self._d)

    def stats(self) -> Dict[str, float]:
        total = self.hits + self.misses
        return {
            "size": len(self._d),
            "capacity": self.capacity,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": (self.hits / total) if total else 0.0,
            "invalidations": self.invalidations,
        }
