"""LRU embedding-row cache for the serving read path.

Host-RESIDENT tables (``--host-tables`` / ZCM strategies — the >HBM DLRM
configuration) pay a numpy gather on the host for every lookup. Online
recommendation traffic is extremely skewed (a few hot users/items
dominate), so the serving engine caches per-sample lookup RESULTS: a
request whose categorical index tuple was seen recently skips the host
gather entirely and only the cold samples touch the table.

Keying is per (op, per-sample index row): the cached value is exactly
``op.host_lookup``'s output for that sample, so cache hits are
bit-identical to the uncached path (the lookup is row-wise across the
batch — each sample's bag gather/reduce never sees its neighbors).

Invalidation has two granularities:

- a FULL hot reload (`invalidate`) drops everything — new tables mean
  every cached row is stale;
- an incremental DELTA reload (`invalidate_rows`) drops only the
  samples whose bag touched a dirtied table row: each entry records the
  host-table rows its value was gathered from
  (``op.host_delta_touched_rows``), so the hot working set survives a
  delta that rewrote a few thousand cold rows.

During serving the tables are otherwise immutable (training scatters
never run in the engine), so no finer-grained tracking is needed.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterable, Tuple

import numpy as np

from ..analysis.sanitizer import make_lock


class EmbeddingCache:
    """Bounded LRU of per-sample host-table lookup results.

    Thread-safe (the engine's batcher and a stats() reader may race);
    the table gather itself additionally serializes on the model's
    ``_host_lock`` at the call site, same as training's gather.
    """

    def __init__(self, capacity: int,
                 quant: "dict[str, str] | None" = None):
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        # key -> (value, dependency host-table rows | None); under a
        # quantized policy the value is (codes, scales, dtype) — ~4x
        # more cached rows per MB, dequantized on every hit
        self._d: "OrderedDict[tuple, Tuple[object, object]]" = \
            OrderedDict()
        # op name -> storage dtype ("int8"/"fp8", quant/): cached values
        # for those ops store quantized. insert() CANONICALIZES the miss
        # values it returns through the same codec, so a hit and the
        # miss that filled it return the SAME dequantized rows —
        # hit == miss stays structural, not approximate.
        self.quant = dict(quant or {})
        self._lock = make_lock("EmbeddingCache._lock", no_dispatch=True)
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.row_invalidations = 0

    @staticmethod
    def _thaw(stored):
        """Stored value -> fp32 rows (dequantize when quantized)."""
        if isinstance(stored, tuple):
            from ..quant.codec import dequantize_rows_np
            q, s, dt = stored
            return dequantize_rows_np(q, s, dt)
        return stored

    def stored_bytes(self) -> int:
        """Approximate bytes the cached values occupy — the rows-per-MB
        accounting the quant bench reports."""
        with self._lock:
            total = 0
            for stored, _deps in self._d.values():
                if isinstance(stored, tuple):
                    q, s, _dt = stored
                    total += (np.asarray(q).view(np.uint8).nbytes
                              + np.asarray(s).nbytes)
                else:
                    total += np.asarray(stored).nbytes
            return total

    def probe(self, op, idx_np: np.ndarray):
        """The read half of :meth:`lookup`: per-sample cache probe over
        a batch. Returns ``(vals, miss)`` — ``vals`` a list with the hit
        samples' cached values (``None`` at miss positions) and ``miss``
        the miss sample indices. Counts hits/misses. Split out so the
        shard tier can probe EVERY op first, batch all ops' misses into
        ONE shard fetch (per-shard version consistency is structural
        when each shard is read once per request), then :meth:`insert`
        what came back."""
        rows = int(idx_np.shape[0])
        vals = [None] * rows
        miss: list = []
        with self._lock:
            for i in range(rows):
                key = (op.name, idx_np[i].tobytes())
                hit = self._d.get(key)
                if hit is None:
                    miss.append(i)
                else:
                    self._d.move_to_end(key)
                    vals[i] = self._thaw(hit[0])
            self.hits += rows - len(miss)
            self.misses += len(miss)
        return vals, miss

    def insert(self, op, idx_np: np.ndarray, miss, sub: np.ndarray,
               ok=None) -> np.ndarray:
        """The write half of :meth:`lookup`: insert the miss samples'
        freshly-looked-up values. ``ok`` (optional bool per miss
        position) masks out samples that must NOT be cached — the shard
        tier passes False for samples assembled from DEGRADED default
        rows, so a shard outage never poisons the cache with
        placeholder embeddings that would outlive the outage.

        Returns the CANONICAL miss values callers must hand out: under
        a quantized policy (``quant[op.name]``) the cached value is
        codes + scales, so the returned values are the quantize-
        dequantize image — a later hit returns the same rows bitwise
        (hit == miss is the pinned contract)."""
        sub = np.asarray(sub)
        dt = self.quant.get(op.name)
        if dt:
            from ..quant.codec import (dequantize_rows_np,
                                       quantize_rows_np)
            q_all, s_all = quantize_rows_np(
                np.asarray(sub, np.float32), dt)
            sub = dequantize_rows_np(q_all, s_all, dt)
        # which host-table rows each missed sample's bag gathered —
        # recorded so a delta reload can invalidate ONLY the samples
        # a dirtied row feeds (None = unknown -> conservative drop)
        deps = {}
        if hasattr(op, "host_delta_touched_rows"):
            for j, i in enumerate(miss):
                if ok is None or ok[j]:
                    deps[i] = op.host_delta_touched_rows(idx_np[i:i + 1])
        with self._lock:
            for j, i in enumerate(miss):
                if ok is not None and not ok[j]:
                    continue
                if dt:
                    stored = (np.ascontiguousarray(q_all[j]),
                              np.ascontiguousarray(s_all[j]), dt)
                else:
                    stored = np.ascontiguousarray(sub[j])
                key = (op.name, idx_np[i].tobytes())
                self._d[key] = (stored, deps.get(i))
                self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)
        return sub

    def lookup(self, op, table_params, idx_np: np.ndarray) -> np.ndarray:
        """Per-sample-cached equivalent of
        ``op.host_lookup(table_params, idx_np)``: hit samples come from
        the cache, miss samples go through ONE sub-batch host_lookup and
        are inserted (canonicalized under a quantized policy, so hits
        and misses return the same rows)."""
        vals, miss = self.probe(op, idx_np)
        if miss:
            sub = np.asarray(
                op.host_lookup(table_params, idx_np[np.asarray(miss)]))
            sub = self.insert(op, idx_np, miss, sub)
            for j, i in enumerate(miss):
                vals[i] = np.ascontiguousarray(sub[j])
        return np.stack(vals, axis=0)

    def prewarm(self, op, table_params, idx_np: np.ndarray) -> int:
        """Warm the cache with per-sample index rows drawn from the
        EXPECTED traffic distribution (the engine samples them from a
        published id-frequency histogram, --serve-cache-warm): each row
        inserts exactly what a real request would — the cached value is
        op.host_lookup's output — so warm hits stay bit-identical to
        cold lookups and the old-or-new-never-mixed reload semantics
        are untouched (a pre-warmed entry invalidates like any other).
        Returns how many NEW entries the warm-up inserted. Stat-neutral:
        hits/misses keep describing real traffic only, so a warm
        replica's hit RATE is comparable to a cold one's."""
        with self._lock:
            h0, m0 = self.hits, self.misses
        before = len(self)
        self.lookup(op, table_params, idx_np)
        with self._lock:
            self.hits, self.misses = h0, m0
        return len(self) - before

    def invalidate(self) -> None:
        """Drop everything (hot reload replaced the tables)."""
        with self._lock:
            self._d.clear()
            self.invalidations += 1

    def invalidate_rows(self, op_name: str,
                        dirty_rows: Iterable[int]) -> int:
        """Drop only the entries of ``op_name`` whose gathered bag
        intersects ``dirty_rows`` (host-table flat row ids — the same
        ids a delta's ``hostparams`` row update carries). Entries with
        no recorded dependencies are dropped conservatively. Returns
        how many entries were evicted."""
        dirty = np.unique(np.asarray(list(dirty_rows)
                                     if not isinstance(dirty_rows,
                                                       np.ndarray)
                                     else dirty_rows).reshape(-1))
        if dirty.size == 0:
            return 0
        with self._lock:
            doomed = []
            for key, (_, deps) in self._d.items():
                if key[0] != op_name:
                    continue
                if deps is None or np.intersect1d(
                        np.asarray(deps), dirty,
                        assume_unique=False).size:
                    doomed.append(key)
            for key in doomed:
                del self._d[key]
            self.row_invalidations += len(doomed)
            return len(doomed)

    def __len__(self) -> int:
        return len(self._d)

    def stats(self) -> Dict[str, float]:
        total = self.hits + self.misses
        return {
            "size": len(self._d),
            "capacity": self.capacity,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": (self.hits / total) if total else 0.0,
            "invalidations": self.invalidations,
            "row_invalidations": self.row_invalidations,
        }
