"""Row-sharded serving tier: lookup shards behind stateless rankers.

The serving fleet (PR 6/12) is N replicas each holding FULL embedding
tables — a DLRM-Terabyte model cannot be served at all (ROADMAP item 1),
even though training row-shards the same tables at pod scale (PR 8,
``parallel/alltoall.py``). This module splits serving the same way the
training mesh does:

- **Ranker tier** — :class:`~.engine.InferenceEngine` replicas hold the
  (small) dense params only and resolve every sparse id through the
  shard tier; their per-ranker :class:`~.cache.EmbeddingCache` fronts
  the remote rows. Rankers are stateless with respect to tables
  (:meth:`EmbeddingShardSet.release_ranker_tables`), so a ranker costs
  dense params + cache, not tables × replicas.
- **Lookup tier** — an :class:`EmbeddingShardSet` of N
  :class:`EmbeddingShard` servers, each owning a contiguous row block
  of EVERY table's flat row space. The owner math is the training
  exchange's (``parallel.alltoall.shard_row_ranges`` /
  ``row_owners`` — the host-side statement of ``owner = id //
  rows_local``), so a serving plan's placement is by construction the
  one a row-sharded training mesh uses, and shardcheck's FLX507 audit
  verifies the tiling statically.

**Consistency is a version vector.** Every shard carries its own
version (the step of the last publish applied to it); every
:class:`~.engine.Prediction` is tagged with the per-shard versions its
lookups read. Old-or-new-never-mixed is enforced PER SHARD structurally:
all of a request's ops are batched into ONE locked lookup per shard, and
a publish applies to a shard atomically under the same lock — one
request can therefore never observe two versions of the same shard.
PR 10's delta chains publish per-shard: ``utils.delta
.split_host_rows_by_shard`` cuts a delta along the shard ranges, stamps
each slice with a CRC the owning shard recomputes before applying, and
each shard chains those CRCs (``shard_chain_crc``) — a publish touches
only owning shards; the others pay a version bump.

**Robustness is the headline.** Shard lookups run under a deadline with
bounded retry + exponential backoff and optional tail-latency hedging
(duplicate-after-delay, first result wins — the FleetRouter discipline
applied one tier down). Each shard sits behind the SAME circuit breaker
the fleet's replicas use (:class:`~.fleet.CircuitBreaker`:
HEALTHY→EJECTED→PROBING→HEALTHY); an ejected shard triggers **graceful
degradation** instead of failed requests — rankers serve cache hits
plus a per-table default row (the table's mean embedding) for misses,
responses are explicitly flagged ``degraded=True``, degraded answers
are counted in ``stats()``, and nothing degraded is ever inserted into
the cache (a shard outage must not outlive itself as poisoned cache
entries). ``degrade="fail"`` opts into failing instead. The
autoscaler's replace-dead path boots a replacement shard from the warm
cache (``utils.warmcache.ShardCache``), replays any publishes it missed
from the set's recent history, and re-admits it only on probe success.

Shards carry a **failure domain** label (``fd<k>``, round-robin over
``failure_domains``): stats group outages by domain so a rack-level
event reads as one domain dark, not N unrelated shard deaths.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from dataclasses import dataclass
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..analysis.sanitizer import make_lock
from ..obs import metrics as obsm
from ..obs import trace as obstrace
from ..parallel.alltoall import row_owners, shard_row_ranges
from ..utils import faults
from ..utils.delta import (ChainError, shard_chain_crc, shard_slice_crc,
                           split_host_rows_by_shard)
from ..utils.logging import get_logger
from ..utils.watchdog import Deadline
from .fleet import EJECTED, HEALTHY, PROBING, CircuitBreaker

log_shard = get_logger("serve.shardtier")


class ShardDown(RuntimeError):
    """This lookup shard is gone — a crash (``FF_FAULT_SHARD_DOWN``) or
    the circuit breaker refusing an ejected shard. Retryable up to the
    lookup budget; exhaustion degrades the response (or fails it under
    ``degrade="fail"``)."""

    def __init__(self, shard_id: Optional[int] = None, detail: str = ""):
        sid = "?" if shard_id is None else shard_id
        super().__init__(f"embedding shard {sid} is down"
                         + (f": {detail}" if detail else ""))
        self.shard_id = shard_id


class ShardLookupTimeout(TimeoutError):
    """A shard lookup missed its deadline (slow host, injected delay).
    Counts against the shard's circuit breaker like any other error."""


class ShardTierUnavailable(RuntimeError):
    """``degrade="fail"`` and a shard's lookup budget is spent — the
    request cannot be answered at full fidelity and the policy forbids
    default rows. The router retries / sheds like FleetUnavailable."""


@dataclass
class ShardTierConfig:
    """Lookup-tier knobs; ``from_config`` lifts the ``--serve-*``
    flags."""

    nshards: int = 2
    lookup_deadline_ms: float = 50.0  # per-shard-lookup budget
    #                                   (retries included)
    retries: int = 1                  # re-lookups after the first try
    backoff_ms: float = 2.0           # exponential retry backoff base
    hedge_ms: float = 0.0             # duplicate-after delay; 0 = off
    eject_after: int = 3              # consecutive errors -> ejection
    cooldown_s: float = 1.0           # ejection -> first probe
    probe_deadline_s: float = 5.0     # end-to-end probe budget
    replace_after: int = 2            # failed probes -> replace-dead
    degrade: str = "cache"            # cache (default rows) | fail
    failure_domains: int = 0          # spread shards over N domains
    transport: str = "inproc"         # inproc (method calls) | tcp

    def __post_init__(self):
        if self.nshards < 1:
            raise ValueError(f"nshards must be >= 1, got {self.nshards}")
        if self.degrade not in ("cache", "fail"):
            raise ValueError(
                f"degrade must be 'cache' or 'fail', got "
                f"{self.degrade!r}")
        if self.transport not in ("inproc", "tcp"):
            raise ValueError(
                f"transport must be 'inproc' or 'tcp', got "
                f"{self.transport!r}")

    @staticmethod
    def from_config(cfg) -> "ShardTierConfig":
        return ShardTierConfig(
            nshards=max(int(getattr(cfg, "serve_shards", 0)), 1),
            lookup_deadline_ms=float(
                getattr(cfg, "serve_lookup_deadline_ms", 50.0)),
            hedge_ms=float(getattr(cfg, "serve_hedge_ms", 0.0)),
            degrade=str(getattr(cfg, "serve_degrade", "cache")),
            transport=str(getattr(cfg, "serve_transport", "inproc")))


class FetchResult(NamedTuple):
    """One batched lookup's outcome: per-op row matrices aligned with
    the requested unique ids, which of those rows are degradation
    defaults, and the per-shard version vector actually read."""

    rows: Dict[str, np.ndarray]          # op -> (U, d) float32
    default_mask: Dict[str, np.ndarray]  # op -> (U,) bool
    versions: Dict[int, int]             # shard slot -> version read
    degraded: bool
    defaults_used: int


class TopKPartials(NamedTuple):
    """One retrieval fan-out's outcome: each answering shard's LOCAL
    top-k partial (globally-addressed ids), the version vector read,
    and which slots degraded out (their candidates are simply absent —
    degraded-not-failed)."""

    scores: Dict[int, np.ndarray]        # slot -> (B, k') float32
    ids: Dict[int, np.ndarray]           # slot -> (B, k') int64
    versions: Dict[int, int]             # shard slot -> version read
    degraded: bool
    dropped_slots: List[int]


def _table_bounds(op, flat_rows: int) -> List[Tuple[int, int]]:
    """Per-TABLE [lo, hi) regions of the op's flat row space (the
    per-table default rows are means over these regions)."""
    sizes = getattr(op, "table_sizes", None)
    if sizes is not None:                       # concat: ragged tables
        offs = list(op._offsets)
        return [(o, o + s) for o, s in zip(offs, sizes)]
    tables = int(getattr(op, "num_tables", 1))
    rows = flat_rows // max(tables, 1)
    return [(t * rows, (t + 1) * rows) for t in range(tables)]


def _parse_address(addr) -> Tuple[str, int]:
    """``"host:port"`` / ``(host, port)`` -> ``(host, port)``, loudly."""
    if isinstance(addr, (tuple, list)) and len(addr) == 2:
        return str(addr[0]), int(addr[1])
    s = str(addr)
    host, sep, port = s.rpartition(":")
    if not sep or not host or not port.isdigit():
        raise ValueError(f"shard address {addr!r} is not host:port")
    return host, int(port)


def _tier_layout(model, nshards: int) -> Dict[str, Any]:
    """Slice ``model``'s host tables into the tier's static layout —
    everything about the geometry that is NOT a live shard: per-op slot
    ranges, flat row counts, row widths, per-table bounds + default
    (mean) rows, the quantized-storage map, the per-slot row blocks,
    and the model fingerprint. ``build()`` turns this into in-process
    shards; ``seed_shard_cache()`` persists it so shard worker
    PROCESSES and ``connect()`` can boot without the model."""
    host_ops = getattr(model, "_host_resident_list", None)
    if not host_ops:
        raise ValueError(
            "the shard tier serves host-resident embedding tables; "
            "compile the model with host_resident_tables=True "
            "(--host-tables). Device-resident tables already "
            "row-shard on the training mesh (param_degree)")
    version = int(getattr(model, "_step", 0))
    ranges_by_op: Dict[str, list] = {}
    flat_rows: Dict[str, int] = {}
    defaults: Dict[str, np.ndarray] = {}
    bounds: Dict[str, List[Tuple[int, int]]] = {}
    dims: Dict[str, int] = {}
    slot_blocks: List[Dict[str, np.ndarray]] = \
        [dict() for _ in range(nshards)]
    # quantized storage policies: the shard tier stores the QUANTIZED
    # representation (codes + row scales) of policy ops — the
    # rows-per-MB lever; defaults/means come from the same dequantized
    # image every lookup serves
    qmap = {name: pol.dtype for name, pol in
            (getattr(model, "quant_policies", dict)() or {}).items()
            if getattr(pol, "is_quantized", False)}
    from ..quant.codec import fake_quant_np
    for op in host_ops:
        kern = model.host_params[op.name]["kernel"]
        flat = np.ascontiguousarray(
            kern.reshape(-1, kern.shape[-1]), np.float32)
        if op.name in qmap:
            flat = fake_quant_np(flat, qmap[op.name])
        R = int(flat.shape[0])
        ranges = shard_row_ranges(R, nshards)
        ranges_by_op[op.name] = ranges
        flat_rows[op.name] = R
        dims[op.name] = int(flat.shape[1])
        tb = _table_bounds(op, R)
        bounds[op.name] = tb
        # the degradation fallback: each table's mean embedding — a
        # neutral "average row" answer, not zeros (zeros shift a
        # trained model's score distribution far more)
        defaults[op.name] = np.stack(
            [flat[lo:hi].mean(axis=0) if hi > lo
             else np.zeros(flat.shape[1], np.float32)
             for lo, hi in tb]).astype(np.float32)
        for slot, (lo, hi) in enumerate(ranges):
            slot_blocks[slot][op.name] = flat[lo:hi].copy()
    from ..utils.checkpoint import config_fingerprint
    return {
        "version": version,
        "ranges_by_op": ranges_by_op,
        "flat_rows": flat_rows,
        "defaults": defaults,
        "bounds": bounds,
        "dims": dims,
        "slot_blocks": slot_blocks,
        "qmap": qmap,
        "fingerprint": config_fingerprint(model),
    }


def _layout_meta(layout: Dict[str, Any], nshards: int,
                 domains: List[str]) -> Dict[str, Any]:
    """The JSON-safe tier geometry the warm cache's meta sidecar
    persists (float32 values survive the JSON double round trip
    exactly)."""
    return {
        "nshards": int(nshards),
        "version": int(layout["version"]),
        "fingerprint": layout["fingerprint"],
        "flat_rows": {k: int(v)
                      for k, v in layout["flat_rows"].items()},
        "dims": {k: int(v) for k, v in layout["dims"].items()},
        "ranges": {k: [[int(lo), int(hi)] for lo, hi in v]
                   for k, v in layout["ranges_by_op"].items()},
        "bounds": {k: [[int(lo), int(hi)] for lo, hi in v]
                   for k, v in layout["bounds"].items()},
        "defaults": {k: [[float(x) for x in row] for row in v]
                     for k, v in layout["defaults"].items()},
        "quant": dict(layout["qmap"]),
        "domains": list(domains),
    }


class EmbeddingShard:
    """One lookup server: a contiguous row block of every table.

    ``sid`` is the shard's unique identity (fault hooks and logs key on
    it; a replacement gets a fresh one); ``slot`` is the row-range it
    owns (stable across replacement — the version vector is keyed by
    slot). All reads and writes serialize on the shard's own lock, so a
    lookup observes exactly one version and a publish applies atomically
    between lookups — the per-shard never-mixed contract is structural,
    not cooperative.
    """

    def __init__(self, sid: int, slot: int,
                 blocks: Dict[str, np.ndarray],
                 ranges: Dict[str, Tuple[int, int]],
                 version: int = 0, chain_crc: int = 0,
                 domain: str = "", quant: Optional[Dict[str, str]] = None):
        self.sid = int(sid)
        self.slot = int(slot)
        self.domain = domain
        # quantized storage policy (quant/): ops listed here hold their
        # block as a QuantTable (codes + row scales, ~4x rows per MB)
        # and their lookups SHIP the quantized payload — the ranker
        # dequantizes (EmbeddingShardSet.fetch)
        self.quant = dict(quant or {})
        self._blocks = {k: self._wrap_block(k, v)
                        for k, v in blocks.items()}
        self._ranges = {k: (int(lo), int(hi))
                        for k, (lo, hi) in ranges.items()}
        self._lock = make_lock(f"EmbeddingShard._lock[{sid}]",
                               no_dispatch=True)
        self._version = int(version)
        self._chain_crc = int(chain_crc) & 0xFFFFFFFF
        self.lookups = 0
        self.rows_served = 0
        self.publishes_applied = 0
        self.apply_rejects = 0
        self.last_reject = ""
        # retrieval-index blocks riding this shard (attach_block):
        # op names whose block answers topk(), plus the previous
        # (block, version) snapshot a publish displaced — what the
        # FF_FAULT_INDEX_STALE drill serves
        self._index_ops: set = set()
        self._prev_index: Dict[str, Tuple[Any, int]] = {}

    def _wrap_block(self, op_name: str, arr):
        """fp32 array -> QuantTable under the op's policy (arrays
        already quantized — a warm-cache boot — pass through)."""
        from ..quant.store import QuantTable
        if isinstance(arr, QuantTable):
            return arr
        dt = self.quant.get(op_name)
        if dt:
            return QuantTable.from_dense(np.asarray(arr, np.float32), dt)
        return np.ascontiguousarray(arr, np.float32)

    @property
    def version(self) -> int:
        return self._version

    @property
    def chain_crc(self) -> int:
        return self._chain_crc

    def hbm_bytes(self) -> int:
        # QuantTable.nbytes counts codes + scales — the stored bytes
        return int(sum(b.nbytes for b in self._blocks.values()))

    def owned_range(self, op_name: str) -> Tuple[int, int]:
        return self._ranges[op_name]

    # --- read path -----------------------------------------------------
    def lookup(self, requests: Dict[str, np.ndarray]
               ) -> Tuple[Dict[str, np.ndarray], int]:
        """Serve every op's requested rows in ONE locked read; returns
        ``({op: (k, d) rows}, version)``. The whole request sees one
        version of this shard — a concurrent publish lands entirely
        before or entirely after it."""
        # fault hooks OUTSIDE the lock: an injected slow lookup must
        # stall this caller, never a concurrent publish
        faults.maybe_lookup_delay(self.sid)
        if faults.take_shard_down(self.sid):
            raise ShardDown(self.sid, "fault injection")
        from ..quant.store import QuantTable
        out = {}
        served = 0
        with self._lock:
            ver = self._version
            for op_name, ids in requests.items():
                lo, hi = self._ranges[op_name]
                g = np.asarray(ids, np.int64)
                if g.size and (int(g.min()) < lo or int(g.max()) >= hi):
                    raise ValueError(
                        f"shard {self.sid} (slot {self.slot}) asked for "
                        f"rows outside its [{lo}, {hi}) range of "
                        f"{op_name!r}")
                blk = self._blocks[op_name]
                if isinstance(blk, QuantTable):
                    # the WIRE payload is quantized — codes + scales +
                    # dtype; the ranker boundary dequantizes
                    q, s = blk.take(g - lo)
                    out[op_name] = (q, s, blk.dtype)
                else:
                    out[op_name] = blk[g - lo]
                served += int(g.size)
            self.lookups += 1
            self.rows_served += served
        return out, ver

    # --- the retrieval-index surface (retrieve/index.py) ----------------
    def attach_block(self, op_name: str, block, lo: int, hi: int) -> None:
        """Install an EXTRA row block on this shard — the retrieval
        index rides the ranking substrate here: the block is addressed,
        published to, and versioned exactly like a table block (one
        shard lock, one version, one chain), so a publish that touches
        ranking rows AND index rows lands atomically on both."""
        from ..quant.store import QuantTable
        if "/" in op_name:
            raise ValueError(f"attach_block: op name {op_name!r} may "
                             f"not contain '/' (publish keys split on "
                             f"it)")
        if not isinstance(block, QuantTable) or block.dtype != "int8":
            raise ValueError(
                f"attach_block: the index block for {op_name!r} must be "
                f"an int8 QuantTable (the MIPS kernel scores int8 "
                f"codes), got {type(block).__name__}")
        if block.shape[0] != int(hi) - int(lo):
            raise ValueError(
                f"attach_block: {op_name!r} block has {block.shape[0]} "
                f"rows for range [{lo}, {hi})")
        with self._lock:
            self._blocks[op_name] = block
            self._ranges[op_name] = (int(lo), int(hi))
            self._index_ops.add(op_name)
            self.quant[op_name] = "int8"

    def topk(self, op_name: str, q_codes: np.ndarray,
             q_scales: np.ndarray, k: int
             ) -> Tuple[np.ndarray, np.ndarray, int]:
        """Local MIPS top-k over this shard's [lo, hi) slice of the
        index: ``((B, k') fp32 scores, (B, k') int64 GLOBAL ids,
        version)``, ordered (score desc, id asc). One locked read — the
        answer sees exactly one index version, so the ranker-side merge
        never mixes versions within a shard."""
        # fault hooks OUTSIDE the lock (same discipline as lookup)
        faults.maybe_lookup_delay(self.sid)
        if faults.take_shard_down(self.sid) or \
                faults.take_topk_drop(self.sid):
            raise ShardDown(self.sid, "fault injection")
        stale = faults.take_index_stale(self.sid)
        from ..ops.pallas.topk_kernel import mips_topk
        with self._lock:
            blk = self._blocks.get(op_name)
            ver = self._version
            if op_name not in self._index_ops or blk is None:
                raise ValueError(f"shard {self.sid} has no retrieval "
                                 f"index {op_name!r} attached")
            lo, _hi = self._ranges[op_name]
            if stale and op_name in self._prev_index:
                # the stale drill: answer from the index the last
                # publish displaced — degraded-not-garbage (candidates
                # are real rows, just one version behind)
                blk, ver = self._prev_index[op_name]
            scores, ids = mips_topk(q_codes, q_scales,
                                    np.asarray(blk.q), blk.scales,
                                    k, base=lo)
            self.lookups += 1
            self.rows_served += int(ids.size)
        return scores, ids, ver

    # --- write path (publishes) ----------------------------------------
    def apply_publish(self, sub: Optional[Dict[str, Any]],
                      version: int,
                      expect_crc: Optional[int] = None) -> bool:
        """Apply one publish's slice for this shard atomically. ``sub``
        None = the publish touched no row this shard owns (version bump
        + chain link only). The slice CRC is recomputed here and must
        match ``expect_crc`` (split-time): corruption between the
        publisher and this shard is a reject-with-reason — the shard
        keeps its old (consistent) version and LAGS, which the watcher's
        catch-up path repairs. Idempotent: a version at or below the
        shard's is a no-op (every ranker's watcher routes the same
        publish here)."""
        slice_crc = 0
        if sub is not None:
            slice_crc = shard_slice_crc(sub)
            if expect_crc is not None and slice_crc != expect_crc:
                reason = (
                    f"publish {version} slice CRC {slice_crc} != "
                    f"declared {expect_crc} (corrupt in transit)")
                with self._lock:
                    self.apply_rejects += 1
                    self.last_reject = reason
                raise ChainError(reason)
        with self._lock:
            if int(version) <= self._version:
                return False
            from ..quant.store import QuantTable
            if sub is not None and self._index_ops:
                # snapshot each touched index block BEFORE the publish
                # lands: the FF_FAULT_INDEX_STALE drill answers from
                # this displaced (block, version) pair
                touched = {key.split("/")[1]
                           for part in ("rows", "full")
                           for key in sub.get(part, {})}
                for op_name in touched & self._index_ops:
                    self._prev_index[op_name] = (
                        self._blocks[op_name].copy(), self._version)
            if sub is not None:
                for key, (idx, vals) in sub.get("rows", {}).items():
                    op_name = key.split("/")[1]
                    lo, hi = self._ranges[op_name]
                    g = np.asarray(idx, np.int64)
                    if g.size and (int(g.min()) < lo
                                   or int(g.max()) >= hi):
                        self.apply_rejects += 1
                        self.last_reject = (
                            f"publish {version} routes rows outside "
                            f"this shard's [{lo}, {hi}) range of "
                            f"{op_name!r}")
                        raise ChainError(self.last_reject)
                    block = self._blocks[op_name]
                    if isinstance(block, QuantTable):
                        # re-quantize per row — the codec is
                        # idempotent, so rows published from quantized
                        # storage land bit-identically
                        block.set_rows(g - lo, vals)
                    else:
                        block[g - lo] = vals
                for key, arr in sub.get("full", {}).items():
                    op_name = key.split("/")[1]
                    lo, hi = self._ranges[op_name]
                    block = self._blocks[op_name]
                    if tuple(arr.shape) != tuple(block.shape):
                        self.apply_rejects += 1
                        self.last_reject = (
                            f"publish {version} full slice for "
                            f"{op_name!r} has shape {arr.shape}, shard "
                            f"block is {block.shape}")
                        raise ChainError(self.last_reject)
                    if isinstance(block, QuantTable):
                        block.set_all(arr)
                    else:
                        block[...] = arr
            self._chain_crc = shard_chain_crc(self._chain_crc,
                                              int(version), slice_crc)
            self._version = int(version)
            self.publishes_applied += 1
        return True

    def install_blocks(self, blocks: Dict[str, np.ndarray],
                       version: int, chain_crc: int = 0) -> bool:
        """Full replacement (a full-snapshot reload / warm-cache boot):
        new blocks, fresh chain anchor. No-op below the current
        version."""
        with self._lock:
            if int(version) < self._version:
                return False
            for k, v in blocks.items():
                if k not in self._ranges:
                    raise ValueError(f"shard {self.sid} owns no range "
                                     f"of {k!r}")
            new_blocks = {k: self._wrap_block(k, v)
                          for k, v in blocks.items()}
            # a full table reload does not evict an attached retrieval
            # index the snapshot never carried
            for k in self._index_ops:
                if k not in new_blocks and k in self._blocks:
                    new_blocks[k] = self._blocks[k]
            self._blocks = new_blocks
            self._version = int(version)
            self._chain_crc = int(chain_crc) & 0xFFFFFFFF
        return True

    def blocks_copy(self) -> Tuple[Dict[str, np.ndarray], int, int]:
        """(blocks copy, version, chain crc) — one consistent snapshot
        for the warm cache (QuantTable blocks stay quantized: the cache
        persists codes + scales bit-exactly)."""
        with self._lock:
            return ({k: v.copy() for k, v in self._blocks.items()},
                    self._version, self._chain_crc)

    def stats(self) -> Dict[str, Any]:
        return {
            "sid": self.sid,
            "slot": self.slot,
            "domain": self.domain,
            "version": self._version,
            "chain_crc": self._chain_crc,
            "lookups": self.lookups,
            "rows_served": self.rows_served,
            "publishes_applied": self.publishes_applied,
            "apply_rejects": self.apply_rejects,
            "last_reject": self.last_reject,
            "hbm_bytes": self.hbm_bytes(),
        }

    # --- the process boundary ------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Expose this shard's serving surface (lookup / publish /
        install / probe / stats) on a wire socket; returns the started
        :class:`~.transport.ShardServer` (its ``address`` carries the
        OS-assigned port when ``port=0``)."""
        from .transport import ShardServer
        return ShardServer(self, host=host, port=port).start()

    def serve_forever(self, host: str = "127.0.0.1",
                      port: int = 0) -> None:
        """Run this shard as a blocking socket server — the body of a
        shard OS process (``python -m dlrm_flexflow_tpu.serve.
        shard_server``)."""
        from .transport import ShardServer
        ShardServer(self, host=host, port=port).serve_forever()


class ShardReplica(CircuitBreaker):
    """One :class:`EmbeddingShard` behind the fleet's circuit-breaker
    state machine — a shard outage reads exactly like a replica outage:
    eject on consecutive errors, probe after cooldown, re-admit only on
    probe success. ``rid`` is the shard's unique sid."""

    KIND = "shard"

    def __init__(self, shard: EmbeddingShard, state: str = HEALTHY):
        super().__init__(shard.sid, state=state)
        self.shard = shard
        # consecutive failed probes since ejection — the replace-dead
        # trigger (a shard that keeps failing probes is gone, not slow)
        self.probe_failures = 0

    @property
    def sid(self) -> int:
        return self.shard.sid

    @property
    def slot(self) -> int:
        return self.shard.slot

    def stats(self) -> Dict[str, Any]:
        out = self.breaker_stats()
        out["probe_failures"] = self.probe_failures
        out.update(self.shard.stats())
        return out


class EmbeddingShardSet:
    """The lookup tier: N shards tiling every host table's flat row
    space, plus the routing, retry/hedging, degradation, publish
    fan-out, and replace-dead machinery over them. One set serves every
    ranker in the fleet."""

    # recent publishes retained for replacement catch-up: a replacement
    # shard booting from a slightly-stale warm-cache entry replays what
    # it missed from here instead of forcing a full reload
    HISTORY = 64

    def __init__(self, shards: List[ShardReplica],
                 config: ShardTierConfig,
                 ranges_by_op: Dict[str, list],
                 flat_rows: Dict[str, int],
                 defaults: Dict[str, np.ndarray],
                 bounds: Dict[str, List[Tuple[int, int]]],
                 dims: Dict[str, int],
                 fingerprint: str = "",
                 cache=None):
        if not shards:
            raise ValueError("a shard set needs at least one shard")
        self.config = config
        self.shards = shards                 # copy-on-write list
        self.nshards = len(shards)
        self._ranges = ranges_by_op          # op -> [(lo, hi)] per slot
        self._flat_rows = flat_rows          # op -> total flat rows
        self._defaults = defaults            # op -> (tables, d) mean rows
        self._bounds = bounds                # op -> per-table [lo, hi)
        self._dims = dims                    # op -> row width
        # op -> quantized storage dtype (set by build(); replacements
        # re-wrap their warm-cache blocks under the same policy)
        self._quant: Dict[str, str] = {
            k: v for r in shards
            for k, v in getattr(r.shard, "quant", {}).items()}
        self.fingerprint = fingerprint
        self._cache = cache                  # utils.warmcache.ShardCache
        self._set_lock = make_lock("EmbeddingShardSet._set_lock")
        # publishes serialize here so every shard sees the same order
        # (the chain CRC is order-sensitive by design)
        self._apply_lock = make_lock("EmbeddingShardSet._apply_lock",
                                     no_dispatch=True)
        self._version = max(r.shard.version for r in shards)
        self._installed_any = False
        self._history: List[Tuple[int, Dict[int, Optional[dict]]]] = []
        self._next_sid = max(r.sid for r in shards) + 1
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * self.nshards),
            thread_name_prefix="ff-shard-lookup")
        self._closed = False
        # counters (stats lock — fetch runs on every batcher thread)
        self._m_lock = make_lock("EmbeddingShardSet._m_lock")
        # bounded fetch-latency window (obs Reservoir; scrapeable as
        # ff_shard_fetch_latency_ms when --obs on) — the lookup tier's
        # own p99, distinct from the ranker's end-to-end number
        self._fetch_ms = obsm.latency_reservoir(
            "ff_shard_fetch_latency_ms",
            "one batched lookup round across the owning shards",
            maxlen=2048)
        obsm.register_collector(self._obs_collect)
        self._fetches = 0
        self._degraded_fetches = 0
        self._defaults_used = 0
        self._retries = 0
        self._hedges = 0
        self._timeouts = 0
        self._failed_fetches = 0
        self.replacements = 0
        self.replace_rejects = 0
        self.last_replace_reject = ""
        # retrieval-index surface (attach_index / topk_partials)
        self._index_op: Optional[str] = None
        self._topk_queries = 0
        self._topk_degraded = 0

    # --- construction --------------------------------------------------
    @classmethod
    def build(cls, model, nshards: int,
              config: Optional[ShardTierConfig] = None,
              cache_dir: Optional[str] = None) -> "EmbeddingShardSet":
        """Slice ``model``'s host-resident tables into ``nshards`` row
        shards (the training exchange's owner math). The model keeps its
        tables until :meth:`release_ranker_tables` frees them."""
        config = config or ShardTierConfig(nshards=nshards)
        if config.nshards != nshards:
            config.nshards = nshards
        lay = _tier_layout(model, nshards)
        ranges_by_op = lay["ranges_by_op"]
        qmap = lay["qmap"]
        version = lay["version"]
        cache = None
        if cache_dir:
            from ..utils.warmcache import ShardCache
            cache = ShardCache(cache_dir,
                               fingerprint=lay["fingerprint"])
        ndomains = max(int(config.failure_domains), 0)
        domains = [f"fd{slot % ndomains}" if ndomains else ""
                   for slot in range(nshards)]
        shards = []
        for slot in range(nshards):
            shard = EmbeddingShard(
                slot, slot, lay["slot_blocks"][slot],
                {name: ranges_by_op[name][slot] for name in ranges_by_op},
                version=version, domain=domains[slot], quant=qmap)
            shards.append(ShardReplica(shard))
        out = cls(shards, config, ranges_by_op, lay["flat_rows"],
                  lay["defaults"], lay["bounds"], lay["dims"],
                  fingerprint=lay["fingerprint"], cache=cache)
        out._quant = qmap
        out._persist_all()
        if cache is not None:
            # the meta sidecar lets shard PROCESSES and connect() boot
            # this geometry without the model
            cache.put_meta(nshards, _layout_meta(lay, nshards, domains))
        log_shard.info(
            "shard set built: %d shard(s) x %d table op(s), "
            "%.1f MB/shard (largest), version %d", nshards,
            len(ranges_by_op),
            max(r.shard.hbm_bytes() for r in shards) / 1e6, version)
        return out

    @staticmethod
    def seed_shard_cache(model, nshards: int, cache_dir: str,
                         config: Optional[ShardTierConfig] = None):
        """Slice ``model`` ONCE and persist every slot's blocks plus
        the tier-geometry meta sidecar into ``cache_dir`` — the boot
        source for shard worker processes
        (``python -m dlrm_flexflow_tpu.serve.shard_server``) and for
        :meth:`connect`, neither of which ever sees the model. Returns
        the :class:`~..utils.warmcache.ShardCache`."""
        from ..quant.store import QuantTable
        from ..utils.warmcache import ShardCache
        config = config or ShardTierConfig(nshards=nshards)
        lay = _tier_layout(model, nshards)
        cache = ShardCache(cache_dir, fingerprint=lay["fingerprint"])
        qmap = lay["qmap"]
        for slot in range(nshards):
            blocks = {}
            for op_name, arr in lay["slot_blocks"][slot].items():
                dt = qmap.get(op_name)
                # persist the same representation a live shard holds:
                # quantized ops as codes + scales (bit-exact with the
                # fake-quanted slice), dense ops as fp32
                blocks[op_name] = (QuantTable.from_dense(arr, dt)
                                   if dt else arr)
            cache.put(nshards, slot, blocks, lay["version"], 0)
        ndomains = max(int(config.failure_domains), 0)
        domains = [f"fd{slot % ndomains}" if ndomains else ""
                   for slot in range(nshards)]
        cache.put_meta(nshards, _layout_meta(lay, nshards, domains))
        return cache

    @classmethod
    def connect(cls, addresses: List[Any],
                config: Optional[ShardTierConfig] = None,
                cache_dir: Optional[str] = None,
                meta: Optional[Dict[str, Any]] = None
                ) -> "EmbeddingShardSet":
        """Build the lookup tier over shard PROCESSES: one
        :class:`~.transport.RemoteShard` per ``host:port`` (or
        ``(host, port)``) address, slot = list position. The tier
        geometry comes from ``meta`` or the ``cache_dir`` meta sidecar
        (:meth:`seed_shard_cache`); each shard is probed once at
        connect time, so an unreachable process fails fast here rather
        than on the first request. With ``cache_dir``, replace-dead
        stays available: a killed shard process is replaced by an
        IN-PROCESS warm-cache boot (a warm standby serving that slot
        until operations restore the process)."""
        from .transport import WireClient, RemoteShard
        if not addresses:
            raise ValueError("connect() needs at least one shard "
                             "address")
        nshards = len(addresses)
        config = config or ShardTierConfig(nshards=nshards,
                                           transport="tcp")
        config.nshards = nshards
        cache = None
        if cache_dir:
            from ..utils.warmcache import ShardCache
            cache = ShardCache(cache_dir)
        if meta is None:
            if cache is None:
                raise ValueError(
                    "connect() needs the tier geometry: pass meta= or "
                    "cache_dir= (seed it with seed_shard_cache)")
            meta = cache.get_meta(nshards)
            if meta is None:
                raise ValueError(
                    f"no tier meta for {nshards} shard(s) in "
                    f"{cache_dir!r}: {cache.last_reject or 'missing'} "
                    f"— run seed_shard_cache first")
        if cache is not None:
            cache.fingerprint = str(meta.get("fingerprint", ""))
        ranges_by_op = {k: [(int(lo), int(hi)) for lo, hi in v]
                        for k, v in meta["ranges"].items()}
        flat_rows = {k: int(v) for k, v in meta["flat_rows"].items()}
        dims = {k: int(v) for k, v in meta["dims"].items()}
        bounds = {k: [(int(lo), int(hi)) for lo, hi in v]
                  for k, v in meta["bounds"].items()}
        defaults = {k: np.asarray(v, np.float32)
                    for k, v in meta["defaults"].items()}
        qmap = {str(k): str(v)
                for k, v in (meta.get("quant") or {}).items()}
        domains = list(meta.get("domains") or [""] * nshards)
        lookup_s = max(config.lookup_deadline_ms / 1e3, 0.001)
        shards = []
        for slot, addr in enumerate(addresses):
            host, port = _parse_address(addr)
            client = WireClient(
                (host, port), seam="lookup", retries=config.retries,
                backoff_ms=config.backoff_ms,
                default_deadline_s=max(10.0, lookup_s),
                name=f"shard{slot}")
            remote = RemoteShard(
                slot, slot, client, domain=domains[slot], quant=qmap,
                lookup_deadline_s=lookup_s)
            remote.refresh()   # fail fast on an unreachable process
            shards.append(ShardReplica(remote))
        out = cls(shards, config, ranges_by_op, flat_rows, defaults,
                  bounds, dims,
                  fingerprint=str(meta.get("fingerprint", "")),
                  cache=cache)
        out._quant = qmap
        log_shard.info(
            "shard set connected: %d remote shard(s) over tcp, "
            "version %d", nshards, out.version)
        return out

    @staticmethod
    def release_ranker_tables(model) -> int:
        """Free a ranker model's host tables (the point of the split:
        rankers are stateless, tables live once, in the shard tier).
        Returns the bytes released. The serving gather never touches
        ``host_params`` once a shard set is attached; training such a
        model again requires a fresh restore."""
        freed = 0
        for op in getattr(model, "_host_resident_list", []) or []:
            tbl = model.host_params.get(op.name)
            if not tbl:
                continue
            for name, arr in list(tbl.items()):
                freed += int(getattr(arr, "nbytes", 0))
                tbl[name] = np.zeros((0,) + arr.shape[1:], arr.dtype)
        model._host_tables_released = True
        return freed

    # --- lifecycle -----------------------------------------------------
    def close(self) -> None:
        self._closed = True
        obsm.unregister_collector(self._obs_collect)
        # wait=False: an abandoned (injected-delay) lookup must not
        # wedge close; the worker threads exit when their task returns
        self._pool.shutdown(wait=False)
        for rep in self.shards:
            closer = getattr(rep.shard, "close", None)
            if closer is not None:
                closer()   # a RemoteShard's connection pool

    def __enter__(self) -> "EmbeddingShardSet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- routing helpers -----------------------------------------------
    def _by_slot(self) -> Dict[int, ShardReplica]:
        return {r.slot: r for r in self.shards}

    @property
    def version(self) -> int:
        return self._version

    def min_version(self) -> Optional[int]:
        """Oldest version among non-ejected shards — the serving
        version FLOOR the watcher's catch-up path keys on (a lagging
        replacement keeps the chain replaying until it has caught up).
        None when every shard is ejected."""
        alive = [r.shard.version for r in self.shards
                 if r.state != EJECTED]
        return min(alive) if alive else None

    def degraded_now(self) -> bool:
        """True while any shard is out of the routable set — responses
        may be carrying default rows right now."""
        return any(r.state != HEALTHY for r in self.shards)

    def _default_rows(self, op_name: str, ids: np.ndarray) -> np.ndarray:
        """Per-table default rows for flat ids (the degradation fill)."""
        tb = self._bounds[op_name]
        starts = np.asarray([lo for lo, _ in tb], np.int64)
        t = np.clip(np.searchsorted(starts, np.asarray(ids, np.int64),
                                    side="right") - 1,
                    0, len(tb) - 1)
        return self._defaults[op_name][t]

    # --- the lookup path -----------------------------------------------
    def fetch(self, plan: Dict[str, np.ndarray],
              deadline_s: Optional[float] = None,
              degrade: Optional[str] = None) -> FetchResult:
        """Resolve every op's UNIQUE flat row ids in one round: group by
        owning shard, one deadline-bounded lookup per shard (all ops
        batched — the per-shard consistency unit), retry + hedge per
        policy, degrade to per-table default rows where the budget is
        spent. The deadline bounds EACH shard's lookup (retries
        included), not the whole fetch — one slow shard must degrade
        itself, never burn the budget of the shards behind it in the
        iteration order. ``plan`` maps op name -> 1-D unique flat
        ids."""
        cfg = self.config
        t_fetch = time.perf_counter()
        if deadline_s is None:
            deadline_s = cfg.lookup_deadline_ms / 1e3
        degrade = degrade or cfg.degrade
        rows: Dict[str, np.ndarray] = {}
        mask: Dict[str, np.ndarray] = {}
        per_slot: Dict[int, Dict[str, Tuple[np.ndarray, np.ndarray]]] = {}
        for op_name, u in plan.items():
            u = np.asarray(u, np.int64)
            rows[op_name] = np.empty((u.size, self._dims[op_name]),
                                     np.float32)
            mask[op_name] = np.zeros(u.size, bool)
            owners = row_owners(u, self._flat_rows[op_name], self.nshards)
            for slot in np.unique(owners):
                m = owners == slot
                per_slot.setdefault(int(slot), {})[op_name] = \
                    (np.flatnonzero(m), u[m])
        versions: Dict[int, int] = {}
        degraded = False
        defaults_used = 0
        by_slot = self._by_slot()
        # hedging needs a duplicate lookup RACING the first — that (and
        # only that) is worth the worker-pool hand-off. Without it the
        # lookups run inline on the caller: an in-process gather is
        # microseconds, and the pool's submit/wait round trip would BE
        # the latency (the deadline is still enforced — a lookup that
        # returns past it is discarded as a timeout, exactly as the
        # pool path would have abandoned it)
        use_pool = self.config.hedge_ms > 0
        first = {}
        if use_pool:
            # first attempts for every involved healthy shard go out
            # together — one parallel round trip in the common case
            for slot, reqs in per_slot.items():
                rep = by_slot.get(slot)
                if rep is not None and rep.state == HEALTHY \
                        and not self._closed:
                    first[slot] = self._pool.submit(
                        rep.shard.lookup, {k: ids for k, (_, ids) in
                                           reqs.items()})
        for slot, reqs in per_slot.items():
            rep = by_slot.get(slot)
            got = None
            if rep is not None and rep.state == HEALTHY \
                    and not self._closed:
                dl = Deadline(deadline_s)
                try:
                    if use_pool:
                        got = self._await_lookup(rep, reqs,
                                                 first.get(slot), dl)
                    else:
                        got = self._lookup_inline(rep, reqs, dl)
                except Exception as e:   # noqa: BLE001 — budget spent
                    if degrade == "fail":
                        with self._m_lock:
                            self._failed_fetches += 1
                        raise ShardTierUnavailable(
                            f"shard {rep.sid} (slot {slot}, domain "
                            f"{rep.shard.domain or 'n/a'}) lookup "
                            f"failed and --serve-degrade=fail: "
                            f"{type(e).__name__}: {e}") from e
            elif degrade == "fail":
                with self._m_lock:
                    self._failed_fetches += 1
                raise ShardTierUnavailable(
                    f"shard slot {slot} is "
                    f"{rep.state if rep else 'missing'} and "
                    f"--serve-degrade=fail")
            if got is not None:
                resp, ver = got
                versions[slot] = ver
                for op_name, (pos, _ids) in reqs.items():
                    val = resp[op_name]
                    if isinstance(val, tuple):
                        # THE ranker-boundary dequant: the shard
                        # shipped codes + row scales (the quantized
                        # wire payload, ~1/4 the fp32 bytes)
                        from ..quant.store import dequantize_payload
                        val = dequantize_payload(*val)
                    rows[op_name][pos] = val
            else:
                # graceful degradation: per-table default rows, flagged
                degraded = True
                for op_name, (pos, ids) in reqs.items():
                    rows[op_name][pos] = self._default_rows(op_name, ids)
                    mask[op_name][pos] = True
                    defaults_used += int(ids.size)
        with self._m_lock:
            self._fetches += 1
            if degraded:
                self._degraded_fetches += 1
                self._defaults_used += defaults_used
        self._fetch_ms.observe(1e3 * (time.perf_counter() - t_fetch))
        return FetchResult(rows, mask, versions, degraded, defaults_used)

    def _lookup_inline(self, rep: ShardReplica, reqs, dl: Deadline):
        """The no-hedge lookup path: call the shard on THIS thread with
        the same deadline/retry/breaker semantics as the pooled path. A
        result arriving after the deadline is discarded as a timeout —
        rows a deadline-bound caller would never have waited for must
        not sneak in just because the call happened to return."""
        cfg = self.config
        request = {k: ids for k, (_, ids) in reqs.items()}
        attempt = 0
        while True:
            err: Optional[BaseException] = None
            try:
                got = rep.shard.lookup(request)
                if dl.expired():
                    with self._m_lock:
                        self._timeouts += 1
                    err = ShardLookupTimeout(
                        f"shard {rep.sid} lookup returned after its "
                        f"{dl.seconds * 1e3:.0f} ms deadline "
                        f"({dl.elapsed() * 1e3:.0f} ms)")
                else:
                    rep.record_success()
                    return got
            except Exception as e:   # noqa: BLE001 — ShardDown etc.
                err = e
            if rep.record_error(err, cfg.eject_after):
                rep.eject(f"{cfg.eject_after} consecutive lookup "
                          f"errors, last: {err}")
            attempt += 1
            if (attempt > cfg.retries or dl.expired()
                    or rep.state != HEALTHY or self._closed):
                raise err
            with self._m_lock:
                self._retries += 1
            time.sleep(min((cfg.backoff_ms / 1e3) * (2 ** (attempt - 1)),
                           max(dl.remaining(), 0.0)))

    def _await_lookup(self, rep: ShardReplica, reqs, fut, dl: Deadline):
        """Wait on one shard's lookup under the shared deadline, with
        bounded retry (exponential backoff) and optional hedging
        (duplicate-after-delay, first result wins). Every failure feeds
        the shard's circuit breaker; crossing the threshold ejects it."""
        cfg = self.config
        request = {k: ids for k, (_, ids) in reqs.items()}
        attempt = 0
        while True:
            futs = [fut] if fut is not None else \
                [self._pool.submit(rep.shard.lookup, request)]
            fut = None
            if cfg.hedge_ms > 0:
                done, _ = wait(futs, timeout=min(
                    cfg.hedge_ms / 1e3, max(dl.remaining(), 0.0)))
                if not done and not self._closed:
                    futs.append(self._pool.submit(rep.shard.lookup,
                                                  request))
                    with self._m_lock:
                        self._hedges += 1
            done, _ = wait(futs, timeout=max(dl.remaining(), 0.0),
                           return_when=FIRST_COMPLETED)
            err: Optional[BaseException] = None
            for f in done:
                e = f.exception()
                if e is None:
                    rep.record_success()
                    return f.result()
                err = e
            if err is None:
                with self._m_lock:
                    self._timeouts += 1
                err = ShardLookupTimeout(
                    f"shard {rep.sid} lookup missed its "
                    f"{dl.seconds * 1e3:.0f} ms deadline "
                    f"(waited {dl.elapsed() * 1e3:.0f} ms)")
            if rep.record_error(err, cfg.eject_after):
                rep.eject(f"{cfg.eject_after} consecutive lookup "
                          f"errors, last: {err}")
            attempt += 1
            if (attempt > cfg.retries or dl.expired()
                    or rep.state != HEALTHY or self._closed):
                raise err
            with self._m_lock:
                self._retries += 1
            time.sleep(min((cfg.backoff_ms / 1e3) * (2 ** (attempt - 1)),
                           max(dl.remaining(), 0.0)))

    # --- the retrieval-index surface (retrieve/index.py) ---------------
    def attach_index(self, op_name: str, table) -> None:
        """Attach a retrieval index to this shard set as ONE MORE
        quantized table: rows split over the same slots by the same
        owner math, published to through the same
        ``split_host_rows_by_shard`` routing (delta key
        ``hostparams/<op_name>/kernel``), versioned by the same
        per-shard chain. One publish therefore advances ranking tables
        AND the index from one manifest, and old-or-new-never-mixed
        holds for retrieval because a shard's topk answer reads the
        same single version its lookups do.

        ``table`` is the full (n_items, d) index — an int8
        ``QuantTable`` of item-tower output embeddings, or an fp32
        array to quantize here."""
        from ..quant.store import QuantTable
        if not isinstance(table, QuantTable):
            table = QuantTable.from_dense(
                np.asarray(table, np.float32), "int8")
        rows, dim = int(table.shape[0]), int(table.shape[1])
        ranges = shard_row_ranges(rows, self.nshards)
        with self._apply_lock:
            by_slot = self._by_slot()
            for slot, (lo, hi) in enumerate(ranges):
                rep = by_slot.get(slot)
                if rep is None:
                    continue
                from ..quant.store import QuantTable as QT
                # .copy(), not ascontiguousarray: contiguous slices come
                # back as VIEWS, and a shard must own its rows — the
                # caller keeping (and mutating) the full table must not
                # bleed into published shard state
                block = QT(table.q[lo:hi].copy(),
                           table.scales[lo:hi].copy(), "int8")
                rep.shard.attach_block(op_name, block, lo, hi)
            self._ranges[op_name] = [(int(lo), int(hi))
                                     for lo, hi in ranges]
            self._flat_rows[op_name] = rows
            self._dims[op_name] = dim
            self._bounds[op_name] = [(0, rows)]
            self._defaults[op_name] = np.zeros((1, dim), np.float32)
            self._quant[op_name] = "int8"
            self._index_op = op_name
            self._persist_all()

    def topk_partials(self, q_codes: np.ndarray, q_scales: np.ndarray,
                      k: int, deadline_s: Optional[float] = None,
                      degrade: Optional[str] = None) -> TopKPartials:
        """Fan one quantized query batch out to every shard's local
        top-k and collect the partials the ranker-side merge consumes.
        Same robustness discipline as :meth:`fetch` — per-shard
        deadline, breaker feedback, ejection — but degradation DROPS
        the dead shard's candidates (flagged) instead of substituting
        defaults: a retrieval answer with a missing shard is a correct
        top-k over the rows that answered."""
        if self._index_op is None:
            raise ShardTierUnavailable(
                "no retrieval index attached (attach_index)")
        op_name = self._index_op
        cfg = self.config
        if deadline_s is None:
            deadline_s = cfg.lookup_deadline_ms / 1e3
        degrade = degrade or cfg.degrade
        scores: Dict[int, np.ndarray] = {}
        ids: Dict[int, np.ndarray] = {}
        versions: Dict[int, int] = {}
        dropped: List[int] = []
        futs = {}
        for rep in list(self.shards):
            if rep.state == HEALTHY and not self._closed:
                futs[rep.slot] = (rep, self._pool.submit(
                    rep.shard.topk, op_name, q_codes, q_scales, k))
        for rep in list(self.shards):
            slot = rep.slot
            got = None
            if slot in futs:
                dl = Deadline(deadline_s)
                _, fut = futs[slot]
                done, _p = wait([fut], timeout=max(dl.remaining(), 0.0))
                err: Optional[BaseException] = None
                if done:
                    err = fut.exception()
                    if err is None:
                        got = fut.result()
                        rep.record_success()
                else:
                    with self._m_lock:
                        self._timeouts += 1
                    err = ShardLookupTimeout(
                        f"shard {rep.sid} topk missed its "
                        f"{dl.seconds * 1e3:.0f} ms deadline")
                if err is not None:
                    if rep.record_error(err, cfg.eject_after):
                        rep.eject(f"{cfg.eject_after} consecutive "
                                  f"lookup errors, last: {err}")
                    if degrade == "fail":
                        with self._m_lock:
                            self._failed_fetches += 1
                        raise ShardTierUnavailable(
                            f"shard {rep.sid} (slot {slot}) topk failed "
                            f"and --serve-degrade=fail: "
                            f"{type(err).__name__}: {err}") from err
            elif degrade == "fail":
                with self._m_lock:
                    self._failed_fetches += 1
                raise ShardTierUnavailable(
                    f"shard slot {slot} is {rep.state} and "
                    f"--serve-degrade=fail")
            if got is not None:
                scores[slot], ids[slot], versions[slot] = got
            else:
                dropped.append(slot)
        with self._m_lock:
            self._topk_queries += 1
            if dropped:
                self._topk_degraded += 1
        return TopKPartials(scores, ids, versions, bool(dropped),
                            dropped)

    # --- publish fan-out (driven by the rankers' install paths) --------
    def apply_delta(self, payload: Dict[str, Any], version: int) -> int:
        """Route one delta publish's host-table updates to their owning
        shards (``split_host_rows_by_shard``), each slice CRC-validated
        by its shard and applied atomically; shards the publish does not
        touch get the version bump + chain link only. Idempotent per
        shard (every ranker's watcher calls this for the same publish).
        Returns how many shards applied row work."""
        with obstrace.span("publish/shard-apply", version=int(version)), \
                self._apply_lock:
            if int(version) <= self._version and self._installed_any:
                # fast path: the whole set already has this publish
                # (another ranker routed it) UNLESS a replacement lags
                if not self.lagging_slots():
                    return 0
            subs = split_host_rows_by_shard(payload, self._ranges)
            applied = 0
            for rep in list(self.shards):
                if rep.state == EJECTED:
                    # a crashed lookup server receives nothing; it
                    # comes back STALE and the probe refuses admission
                    # until the watcher's catch-up (or replace-dead)
                    # has brought it to the tip
                    continue
                sub = subs.get(rep.slot)
                try:
                    if rep.shard.apply_publish(
                            sub, version,
                            None if sub is None else sub.get("crc")):
                        applied += 1 if sub is not None else 0
                except ChainError as e:
                    # the shard keeps its old consistent version and
                    # LAGS; min_version() drops and the watcher's
                    # catch-up replays the chain until it heals
                    log_shard.warning(
                        "shard %d rejected publish %d: %s — shard "
                        "lags at version %d", rep.sid, version, e,
                        rep.shard.version)
            self._version = max(self._version, int(version))
            self._installed_any = True
            self._history.append((int(version), subs))
            del self._history[:-self.HISTORY]
            self._persist_all()
        return applied

    def install_full(self, host_params: Dict[str, Dict[str, np.ndarray]],
                     version: int) -> bool:
        """Full-snapshot reload: reslice every table onto its shards.
        Resets each shard's chain anchor (a full IS a new base).
        Idempotent per version."""
        with self._apply_lock:
            if int(version) <= self._version and self._installed_any \
                    and not self.lagging_slots():
                return False
            for rep in list(self.shards):
                if rep.state == EJECTED:
                    continue   # same skip as apply_delta
                blocks = {}
                for op_name, ranges in self._ranges.items():
                    tbl = host_params.get(op_name)
                    if tbl is None:
                        continue
                    kern = tbl["kernel"]
                    flat = np.asarray(kern).reshape(-1, kern.shape[-1])
                    if flat.shape[0] != self._flat_rows[op_name]:
                        # a released ranker's 0-row stub (canary
                        # rollback state) or a foreign geometry: never
                        # slice THAT over real shard blocks
                        log_shard.warning(
                            "install_full: %r has %d flat rows, the "
                            "shard tier serves %d — table skipped "
                            "(released-ranker stub or foreign "
                            "snapshot)", op_name, flat.shape[0],
                            self._flat_rows[op_name])
                        continue
                    lo, hi = ranges[rep.slot]
                    blocks[op_name] = flat[lo:hi].copy()
                if blocks:
                    rep.shard.install_blocks(blocks, version)
                else:
                    # nothing of the shard's in this snapshot (stub /
                    # foreign tables): version bump only, rows stand
                    rep.shard.apply_publish(None, version)
            self._version = max(self._version, int(version))
            self._installed_any = True
            self._history.clear()
            self._persist_all()
        return True

    def lagging_slots(self) -> List[int]:
        """Slots whose shard version trails the set tip (a rejected
        slice or a stale replacement) — what the watcher's catch-up
        repairs."""
        return [r.slot for r in self.shards
                if r.state != EJECTED and r.shard.version < self._version]

    def _persist_all(self) -> None:
        """Warm-cache every shard's current blocks (the replace-dead
        boot source). Best-effort; a failed put costs a replacement a
        cold rebuild, nothing else."""
        if self._cache is None:
            return
        for rep in self.shards:
            if rep.state == EJECTED:
                continue   # don't clobber the entry with stale blocks
            if not getattr(rep.shard, "supports_persist", True):
                # a REMOTE shard's blocks live in its own process; its
                # boot source is the seeded cache, not our copy
                continue
            blocks, ver, crc = rep.shard.blocks_copy()
            self._cache.put(self.nshards, rep.slot, blocks, ver, crc)

    # --- health: probe, re-admit, replace-dead -------------------------
    def probe(self, rep: ShardReplica) -> bool:
        """End-to-end admission probe: a real lookup of each table's
        first owned row through the real path, under the probe deadline,
        PLUS a freshness check — a shard is only re-admitted at the
        set's current version (serving stale-but-consistent rows from a
        re-admitted shard would silently rewind the version vector)."""
        cfg = self.config
        rep.begin_probe()
        request = {}
        for op_name, ranges in self._ranges.items():
            lo, hi = ranges[rep.slot]
            if hi > lo:
                request[op_name] = np.asarray([lo], np.int64)
        try:
            fut = self._pool.submit(rep.shard.lookup, request)
            _resp, ver = fut.result(cfg.probe_deadline_s)
            if ver < self._version:
                raise ChainError(
                    f"shard is at version {ver}, set tip is "
                    f"{self._version} (stale — needs catch-up before "
                    f"admission)")
        except Exception as e:   # noqa: BLE001 — stay ejected
            rep.probe_failed(f"{type(e).__name__}: {e}")
            rep.probe_failures += 1
            return False
        rep.readmit()
        rep.probe_failures = 0
        return True

    def replace(self, slot: int) -> Optional[int]:
        """Replace-dead: boot a fresh shard for ``slot`` from the warm
        cache (``utils.warmcache.ShardCache``), replay any publishes the
        cached blocks predate from the set's history, and swap it in
        born-PROBING — it serves nothing until its admission probe
        succeeds. Returns the new sid, or None with the reject reason
        recorded (the set keeps degrading; nothing got worse)."""
        def _reject(reason: str) -> None:
            self.replace_rejects += 1
            self.last_replace_reject = reason
            log_shard.warning("shard replace(slot=%d) rejected: %s — "
                              "continuing degraded", slot, reason)

        if self._cache is None:
            _reject("no shard warm cache configured "
                    "(--compile-cache-dir)")
            return None
        got = self._cache.get(self.nshards, slot)
        if got is None:
            _reject(f"warm cache miss: "
                    f"{self._cache.last_reject or 'no entry'}")
            return None
        blocks, ver, chain_crc = got
        for op_name, ranges in self._ranges.items():
            lo, hi = ranges[slot]
            blk = blocks.get(op_name)
            if blk is None or blk.shape[0] != hi - lo:
                _reject(f"cached blocks have wrong geometry for "
                        f"{op_name!r} (got "
                        f"{None if blk is None else blk.shape}, "
                        f"want {hi - lo} rows)")
                return None
        with self._set_lock:
            sid = self._next_sid
            self._next_sid += 1
        old = self._by_slot().get(slot)
        domain = old.shard.domain if old is not None else ""
        shard = EmbeddingShard(
            sid, slot, blocks,
            {name: self._ranges[name][slot] for name in self._ranges},
            version=ver, chain_crc=chain_crc, domain=domain,
            quant=self._quant)
        with self._apply_lock:
            # replay what the cached blocks missed; the slice CRCs
            # re-validate each replayed publish
            for v, subs in self._history:
                if v > shard.version:
                    try:
                        sub = subs.get(slot)
                        shard.apply_publish(
                            sub, v, None if sub is None
                            else sub.get("crc"))
                    except ChainError as e:
                        _reject(f"catch-up replay of publish {v} "
                                f"failed: {e}")
                        return None
            if shard.version < self._version:
                _reject(f"cached blocks at version {shard.version} "
                        f"predate the retained history (tip "
                        f"{self._version}) — needs a full reload")
                return None
            fresh = ShardReplica(shard, state=PROBING)
            with self._set_lock:
                self.shards = [fresh if r.slot == slot else r
                               for r in self.shards]
                self.replacements += 1
        log_shard.warning(
            "shard slot %d replaced (%s -> sid %d) from the warm cache "
            "at version %d; awaiting admission probe",
            slot, "sid %d" % old.sid if old else "none", sid,
            shard.version)
        return sid

    def health_tick(self) -> List[Dict[str, Any]]:
        """One health pass (the autoscaler drives this, or the set's
        own health thread when serving without one): probe shards due
        for one, replace shards whose probes keep failing. Returns the
        actions taken."""
        cfg = self.config
        actions: List[Dict[str, Any]] = []
        for rep in list(self.shards):
            if rep.state == HEALTHY:
                continue
            if not rep.due_for_probe(cfg.cooldown_s):
                continue
            if rep.probe_failures >= cfg.replace_after \
                    and not rep.awaiting_admission:
                new_sid = self.replace(rep.slot)
                actions.append({"action": "shard-replace",
                                "slot": rep.slot, "old_sid": rep.sid,
                                "new_sid": new_sid})
                continue
            ok = self.probe(rep)
            actions.append({"action": "shard-probe", "slot": rep.slot,
                            "sid": rep.sid, "ok": ok})
        return actions

    def start_health(self, interval_s: float = 0.25
                     ) -> "EmbeddingShardSet":
        """Own health thread for shard-set deployments without an
        autoscaler (serve_dlrm single-engine mode). ff-named, daemon,
        stop-signalled and joined by :meth:`stop_health`."""
        if getattr(self, "_health_thread", None) is not None:
            return self
        self._health_stop = threading.Event()

        def _loop():
            while not self._health_stop.wait(interval_s):
                try:
                    self.health_tick()
                except Exception:   # noqa: BLE001 — health must outlive
                    log_shard.exception("shard health tick failed")

        self._health_thread = threading.Thread(
            target=_loop, daemon=True, name="ff-shard-health")
        self._health_thread.start()
        return self

    def stop_health(self) -> None:
        t = getattr(self, "_health_thread", None)
        if t is None:
            return
        self._health_stop.set()
        t.join(5.0)
        self._health_thread = None

    # --- plans + observability -----------------------------------------
    def serving_plan(self) -> Dict[str, Any]:
        """The static description shardcheck's FLX507 audit consumes:
        shard count, per-op flat row counts and ranges, per-shard
        residency, and whether rankers still hold full tables."""
        out = {
            "nshards": self.nshards,
            "flat_rows": dict(self._flat_rows),
            "ranges": {k: list(v) for k, v in self._ranges.items()},
            "shard_hbm_bytes": max(r.shard.hbm_bytes()
                                   for r in self.shards),
            "domains": sorted({r.shard.domain for r in self.shards
                               if r.shard.domain}),
        }
        if self._index_op is not None:
            out["retrieve_index"] = {
                "op": self._index_op,
                "rows": int(self._flat_rows[self._index_op]),
                "dim": int(self._dims[self._index_op]),
                "quant": self._quant.get(self._index_op, "int8"),
                "sharded": True,
            }
        return out

    def version_vector(self) -> Dict[int, int]:
        return {r.slot: r.shard.version for r in self.shards}

    def _obs_collect(self):
        """Registry collector: lookup-tier counters + per-shard health
        as scrapeable samples (same numbers stats() reports)."""
        yield "ff_shard_fetches_total", {}, self._fetches
        yield "ff_shard_degraded_fetches_total", {}, \
            self._degraded_fetches
        yield "ff_shard_defaults_used_total", {}, self._defaults_used
        yield "ff_shard_retries_total", {}, self._retries
        yield "ff_shard_timeouts_total", {}, self._timeouts
        yield "ff_shard_failed_fetches_total", {}, self._failed_fetches
        yield "ff_shard_replacements_total", {}, self.replacements
        yield "ff_shard_version_floor", {}, (self.min_version() or 0)
        for r in self.shards:
            yield ("ff_shard_healthy", {"slot": str(r.slot)},
                   1.0 if r.state == HEALTHY else 0.0)

    def stats(self) -> Dict[str, Any]:
        with self._m_lock:
            out = {
                "nshards": self.nshards,
                "version": self._version,
                "versions": self.version_vector(),
                "states": {r.slot: r.state for r in self.shards},
                "degraded_now": self.degraded_now(),
                "fetch_p50_ms": self._fetch_ms.percentile(50),
                "fetch_p99_ms": self._fetch_ms.percentile(99),
                "fetches": self._fetches,
                "degraded_fetches": self._degraded_fetches,
                "defaults_used": self._defaults_used,
                "topk_queries": self._topk_queries,
                "topk_degraded": self._topk_degraded,
                "retries": self._retries,
                "hedges": self._hedges,
                "timeouts": self._timeouts,
                "failed_fetches": self._failed_fetches,
                "replacements": self.replacements,
                "replace_rejects": self.replace_rejects,
                "last_replace_reject": self.last_replace_reject,
                "lagging_slots": self.lagging_slots(),
                "shards": {r.slot: r.stats() for r in self.shards},
            }
        domains = {}
        for r in self.shards:
            if r.shard.domain:
                d = domains.setdefault(r.shard.domain,
                                       {"shards": 0, "healthy": 0})
                d["shards"] += 1
                d["healthy"] += int(r.state == HEALTHY)
        if domains:
            out["failure_domains"] = domains
        if self._cache is not None:
            out["shard_cache"] = self._cache.stats()
        return out


# ---------------------------------------------------------------------
# feasibility accounting (the bench + shardcheck FLX507 share this)
# ---------------------------------------------------------------------
def serving_footprint(model, replicas: int, nshards: int = 0,
                      ranker_holds_tables: Optional[bool] = None
                      ) -> Dict[str, Any]:
    """Static per-process residency of a serving deployment: what one
    RANKER replica and (when sharded) one LOOKUP SHARD must hold. The
    replicated fleet's per-replica bytes include every table; the
    sharded tier's rankers drop to dense-only and each shard holds
    ~1/nshards of the tables — the terabyte-serving argument, stated in
    bytes."""
    dense = 0
    tables = 0
    host_ops = set(op.name for op in
                   getattr(model, "_host_resident_list", []) or [])
    for op in getattr(model, "ops", []):
        try:
            pb = float(op.param_bytes())
        except Exception:   # noqa: BLE001 — param-less ops
            continue
        if op.name in host_ops or hasattr(op, "host_lookup"):
            # tables at their effective STORED bytes: the shard tier
            # (and a replicated fleet's serving snapshot) holds the
            # quantized representation — int8 rows + fp32 row scales —
            # not the trainer's fp32 master (quant/policy.py)
            from ..quant.policy import param_storage_bytes
            try:
                shapes = {n: d.shape
                          for n, d in op.param_defs().items()}
                pb = float(param_storage_bytes(op, None, shapes))
            except Exception:   # noqa: BLE001 — keep the fp32 estimate
                pass
            tables += pb
        else:
            dense += pb
    if ranker_holds_tables is None:
        ranker_holds_tables = nshards <= 0 \
            and not getattr(model, "_host_tables_released", False)
    per_shard = (-(-int(tables) // nshards)) if nshards > 0 else 0
    ranker = dense + (tables if ranker_holds_tables else 0)
    return {
        "replicas": int(replicas),
        "nshards": int(nshards),
        "dense_bytes": int(dense),
        "table_bytes": int(tables),
        "ranker_bytes": int(ranker),
        "shard_bytes": int(per_shard),
        "fleet_table_bytes": int(tables * replicas
                                 if ranker_holds_tables else tables),
    }


def check_serving_feasible(model, replicas: int, hbm_bytes: float,
                           nshards: int = 0) -> Dict[str, Any]:
    """Admission check a serving launcher runs before boot: does each
    process fit its budget? Returns the footprint report augmented with
    ``feasible`` and ``reason``; the replicated fleet REJECTS a model
    whose tables exceed the per-replica budget, the sharded tier admits
    it as long as dense params and one shard's rows fit."""
    fp = serving_footprint(model, replicas, nshards)
    worst = max(fp["ranker_bytes"], fp["shard_bytes"])
    fp["hbm_bytes"] = int(hbm_bytes)
    fp["feasible"] = worst <= hbm_bytes
    if fp["feasible"]:
        fp["reason"] = ""
    elif nshards <= 0:
        fp["reason"] = (
            f"replicated fleet infeasible: each replica must hold "
            f"{fp['ranker_bytes'] / 1e6:.1f} MB (tables "
            f"{fp['table_bytes'] / 1e6:.1f} MB) against a "
            f"{hbm_bytes / 1e6:.1f} MB budget — shard the lookup tier "
            f"(--serve-shards)")
    else:
        fp["reason"] = (
            f"sharded tier infeasible at {nshards} shard(s): worst "
            f"process holds {worst / 1e6:.1f} MB against "
            f"{hbm_bytes / 1e6:.1f} MB — raise --serve-shards")
    return fp
