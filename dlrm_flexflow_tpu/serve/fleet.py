"""Serving fleet: replica lifecycle and circuit-breaker state machine.

One :class:`~.engine.InferenceEngine` is one device's worth of traffic
and a single point of failure. A fleet is N engines over data-parallel
params (one per device/host; in-process replicas for tests and the CPU
bench, one per host process in production), each wrapped in a
:class:`Replica` that tracks its health:

::

    HEALTHY --(eject_after consecutive errors,
               stale heartbeat, dead batcher)--> EJECTED
    EJECTED --(cooldown elapsed)------------------> PROBING
    PROBING --(probe succeeds)--------------------> HEALTHY
    PROBING --(probe fails)-----------------------> EJECTED

Ejection is the Clipper-style isolation move: the replica stops
receiving traffic, its still-queued futures are DRAINED (failed with a
typed ``ReplicaDown`` so the router's retry callbacks re-route them to
survivors), and only a successful end-to-end probe — a real request
through the real dispatch path, under a watchdog deadline — re-admits
it. One slow or crashed replica therefore costs retries, never answers.

Routing, retry/hedging policy, and the canary/shadow deployment
machinery live in :mod:`.router`; this module is the per-replica truth
the router acts on, plus fleet-wide ``stats()`` aggregation.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..analysis.sanitizer import make_lock
from ..utils.logging import get_logger
from .engine import InferenceEngine, ReplicaDown, percentile

log_fleet = get_logger("serve.fleet")

# replica states (plain strings: they go straight into stats() JSON)
HEALTHY = "healthy"
EJECTED = "ejected"
PROBING = "probing"


class CircuitBreaker:
    """The eject/probe/re-admit state machine, decoupled from what it
    guards.

    One instance wraps one failure-isolatable unit: a fleet
    :class:`Replica` (an engine), or the shard tier's lookup shards
    (``serve/shardtier.py`` wraps each :class:`~.shardtier
    .EmbeddingShard` in the SAME machine) — the whole serving stack
    speaks one health vocabulary, and a shard outage reads exactly like
    a replica outage in stats and logs. All transitions happen under the
    breaker's own lock; ``_on_eject`` is the subclass hook for
    unit-specific isolation work (a replica drains its queue there).
    """

    KIND = "unit"

    def __init__(self, rid: int, state: str = HEALTHY):
        self.rid = rid
        self.state = state
        # a freshly-grown unit is born PROBING (`state=PROBING`) and
        # carries this flag: it receives NO client traffic until the
        # end-to-end admission probe succeeds — a unit that boots broken
        # costs a probe failure, never a client error
        self.awaiting_admission = state == PROBING
        self._lock = make_lock(f"{type(self).__name__}._lock[{rid}]")
        self.consecutive_errors = 0
        self.ejected_at = 0.0
        self.last_error = ""
        # counters (monotonic, surfaced in stats)
        self.ejections = 0
        self.readmissions = 0
        self.probes = 0
        self.dispatch_errors = 0

    # --- circuit breaker ----------------------------------------------
    def record_success(self) -> None:
        with self._lock:
            self.consecutive_errors = 0

    def record_error(self, err: BaseException, eject_after: int) -> bool:
        """Count one dispatch error; True when the consecutive-error
        threshold was just crossed and the caller should eject."""
        with self._lock:
            self.dispatch_errors += 1
            self.consecutive_errors += 1
            self.last_error = f"{type(err).__name__}: {err}"
            return (self.state == HEALTHY
                    and self.consecutive_errors >= eject_after)

    def _on_eject(self, reason: str) -> int:
        """Unit-specific isolation work after the state flip; returns a
        count for the log line (a replica: drained requests)."""
        return 0

    def eject(self, reason: str) -> int:
        """HEALTHY/PROBING -> EJECTED: stop routing here and run the
        unit's isolation hook. Returns the hook's count."""
        with self._lock:
            if self.state == EJECTED:
                return 0
            self.state = EJECTED
            self.ejected_at = time.monotonic()
            self.ejections += 1
            self.last_error = reason
        drained = self._on_eject(reason)
        log_fleet.warning(
            "ejected %s %d (%s) — drained %d queued request(s) "
            "onto the survivors", self.KIND, self.rid, reason, drained)
        return drained

    def due_for_probe(self, cooldown_s: float) -> bool:
        with self._lock:
            if self.awaiting_admission:     # born-PROBING (grow/replace):
                return True                 # admission probe runs at the
            return (self.state == EJECTED   # next health tick, no cooldown
                    and time.monotonic() - self.ejected_at >= cooldown_s)

    def begin_probe(self) -> None:
        with self._lock:
            if self.state == EJECTED:
                self.state = PROBING
            self.awaiting_admission = False
            self.probes += 1

    def probe_failed(self, reason: str) -> None:
        with self._lock:
            if self.state == PROBING:
                self.state = EJECTED
                self.ejected_at = time.monotonic()  # restart cooldown
            self.last_error = f"probe failed: {reason}"

    def readmit(self) -> None:
        with self._lock:
            prev = self.state
            self.state = HEALTHY
            self.consecutive_errors = 0
            self.readmissions += 1
        log_fleet.info("re-admitted %s %d (was %s) after probe "
                       "success", self.KIND, self.rid, prev)

    def breaker_stats(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "consecutive_errors": self.consecutive_errors,
            "dispatch_errors": self.dispatch_errors,
            "ejections": self.ejections,
            "readmissions": self.readmissions,
            "probes": self.probes,
            "last_error": self.last_error,
        }


class Replica(CircuitBreaker):
    """One engine plus its circuit-breaker state.

    All transitions happen under the replica's own lock and are driven
    by the router (request callbacks + health thread); the engine knows
    nothing about fleet membership beyond its ``replica_id``.
    """

    KIND = "replica"

    def __init__(self, engine: InferenceEngine, rid: int,
                 cohort: str = "stable", state: str = HEALTHY):
        super().__init__(rid, state=state)
        self.engine = engine
        # deployment cohort: "stable" serves normal traffic, "canary"
        # serves the routed fraction on a candidate snapshot, "shadow"
        # serves only duplicated traffic and never answers a client
        self.cohort = cohort
        # pre-deploy state kept while this replica runs a canary/shadow
        # snapshot: rollback = install this back (the arrays are
        # immutable JAX trees, so holding references is free)
        self.rollback_state: Optional[Dict[str, Any]] = None
        self.rollback_version: int = 0

    # --- routing signals ----------------------------------------------
    @property
    def queue_depth(self) -> int:
        return self.engine.queue_depth

    def routable(self, cohort: str = "stable") -> bool:
        """Eligible for client traffic of the given cohort."""
        return self.state == HEALTHY and self.cohort == cohort

    def _on_eject(self, reason: str) -> int:
        """Drain the queue so every waiting future fails fast with
        ReplicaDown (the router retries each on a survivor)."""
        return self.engine.drain_pending(
            ReplicaDown(self.rid, f"ejected: {reason}"))

    # --- deployment helpers (used by the router's canary/shadow) -------
    def capture_rollback_state(self) -> None:
        """Snapshot the CURRENT inference state by reference before a
        candidate snapshot is installed. Reads through the engine's
        ``state_snapshot`` so a reload parked-but-not-yet-applied is
        captured (not the arrays it is about to supersede)."""
        state, version = self.engine.state_snapshot()
        self.rollback_state = state
        self.rollback_version = version

    def restore_rollback_state(self) -> None:
        if self.rollback_state is None:
            raise RuntimeError(
                f"replica {self.rid} has no captured rollback state")
        self.engine.install_snapshot(self.rollback_state,
                                     self.rollback_version,
                                     source="rollback")
        self.rollback_state = None

    def stats(self) -> Dict[str, Any]:
        out = self.breaker_stats()
        out.update({
            "cohort": self.cohort,
            "queue_depth": self.queue_depth,
            "heartbeat_age_s": round(self.engine.heartbeat_age(), 4),
            "engine": self.engine.stats(),
        })
        return out


class Fleet:
    """The replica set: lifecycle, elastic grow/shrink, and fleet-wide
    stats aggregation.

    Construct from engines (``replica_id`` is assigned positionally when
    the engine doesn't carry one) or via :meth:`build` from a model
    factory — each replica needs its OWN model instance (its own param
    arrays to hot-swap independently); data-parallelism comes from every
    model being compiled/restored identically. A fleet built with a
    factory can also :meth:`grow` (new replicas boot from the persistent
    compile cache when one is configured, enter PROBING, and are
    admitted only after the router's end-to-end probe succeeds) and
    :meth:`shrink` back when idle — the verbs the SLO autoscaler
    (``serve/autoscale.py``) drives.
    """

    # bounded warm-up pool: N-replica cold start used to AOT-warm every
    # bucket serially, making it N x single-replica warmup; replicas warm
    # concurrently up to this many at a time (compilation is host-CPU
    # work — unbounded parallelism would thrash the compiler)
    WARM_POOL = 4

    def __init__(self, engines: List[InferenceEngine],
                 model_factory=None, config=None,
                 checkpoint_dir: Optional[str] = None,
                 shard_set=None):
        if not engines:
            raise ValueError("a fleet needs at least one replica")
        # grow() provisioning recipe (None = fixed-size fleet)
        self._factory = model_factory
        self._config = config
        self._checkpoint_dir = checkpoint_dir
        # the shared row-sharded lookup tier (serve/shardtier.py) the
        # ranker replicas resolve sparse ids through; one set serves
        # every ranker, so it hangs off the FLEET, not a replica
        self.shard_set = shard_set
        # replicas list is COPY-ON-WRITE under this lock: readers (the
        # router's pick/health loops) grab the current list reference
        # without locking; grow/shrink build a new list and swap it
        self._fleet_lock = make_lock("Fleet._fleet_lock")
        self.grows = 0
        self.shrinks = 0
        replicas: List[Replica] = []
        for i, eng in enumerate(engines):
            if eng.replica_id is None:
                eng.replica_id = i
            replicas.append(Replica(eng, eng.replica_id))
        rids = [r.rid for r in replicas]
        if len(set(rids)) != len(rids):
            raise ValueError(f"duplicate replica ids {rids}")
        self.replicas = replicas

    @classmethod
    def build(cls, model_factory, n: int, config=None,
              checkpoint_dir: Optional[str] = None,
              shard_set=None) -> "Fleet":
        """N engines over N fresh models from ``model_factory(i)``; each
        gets its own SnapshotWatcher when a checkpoint dir is given, so
        the whole fleet follows the trainer's publications.

        The factory receives the replica index so it can pin each
        replica's model to ITS OWN device/mesh — replicas sharing one
        mesh would serialize (and on CPU can deadlock: two dispatches'
        collective participants interleave on the shared device set).
        A data-parallel fleet means N independent single-replica meshes,
        not N views of one mesh. The factory is retained so the
        autoscaler can :meth:`grow` the fleet later."""
        engines = [InferenceEngine(model_factory(i), config,
                                   checkpoint_dir=checkpoint_dir,
                                   replica_id=i, shard_set=shard_set)
                   for i in range(n)]
        return cls(engines, model_factory=model_factory, config=config,
                   checkpoint_dir=checkpoint_dir, shard_set=shard_set)

    @classmethod
    def connect(cls, addresses, deadline_s: float = 30.0) -> "Fleet":
        """A fleet over ranker PROCESSES: one
        :class:`~.transport.RemoteEngineClient` per ``host:port`` (each
        a replica running ``engine.serve_forever()`` in its own
        process). The router's dispatch, breaker, and health machinery
        drive these exactly like in-process engines; canary/shadow
        snapshot installs are refused by the proxy (deploys stay where
        the model lives). A fixed-size fleet: no grow()."""
        from .shardtier import _parse_address
        from .transport import RemoteEngineClient
        if not addresses:
            raise ValueError("connect() needs at least one replica "
                             "address")
        engines = [RemoteEngineClient(_parse_address(addr), rid=i,
                                      deadline_s=deadline_s)
                   for i, addr in enumerate(addresses)]
        return cls(engines)

    def __len__(self) -> int:
        return len(self.replicas)

    def __iter__(self):
        return iter(self.replicas)

    def get(self, rid: int) -> Replica:
        for r in self.replicas:
            if r.rid == rid:
                return r
        raise KeyError(f"no replica {rid} in fleet "
                       f"{[r.rid for r in self.replicas]}")

    def healthy(self, cohort: Optional[str] = None) -> List[Replica]:
        out = [r for r in self.replicas if r.state == HEALTHY
               and r.cohort != "shadow"]
        if cohort is not None:
            out = [r for r in out if r.cohort == cohort]
        return out

    # --- lifecycle -----------------------------------------------------
    def _start_engines(self, replicas: List[Replica]) -> None:
        """Start (and AOT-warm) a set of engines CONCURRENTLY through a
        bounded pool of ff-named daemon threads, every one joined before
        return. Bucket warmup is the dominant cold-start cost; with the
        persistent compile cache attached each warm is a deserialize,
        and either way N replicas no longer pay N serial warmups."""
        import threading
        if len(replicas) == 1:
            replicas[0].engine.start()
            return
        errs: List[BaseException] = []
        errs_lock = make_lock("Fleet._warm_errs_lock")
        it = iter(list(replicas))
        it_lock = make_lock("Fleet._warm_iter_lock")

        def _worker():
            while True:
                with it_lock:
                    rep = next(it, None)
                if rep is None:
                    return
                try:
                    rep.engine.start()
                except BaseException as e:   # noqa: BLE001 — surface
                    with errs_lock:          # after every join
                        errs.append(e)

        threads = [threading.Thread(target=_worker, daemon=True,
                                    name=f"ff-fleet-warm-{i}")
                   for i in range(min(self.WARM_POOL, len(replicas)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    def start(self) -> "Fleet":
        self._start_engines(self.replicas)
        return self

    # --- elastic size (driven by serve/autoscale.py) -------------------
    @property
    def can_grow(self) -> bool:
        return self._factory is not None

    def grow(self, n: int = 1) -> List[int]:
        """Provision `n` new replicas from the retained factory: build
        each model (booting from the persistent compile cache when the
        config enables one), start+warm the engines concurrently, and
        add them in PROBING state — the router's next health tick runs
        the end-to-end admission probe and only success makes them
        routable. Returns the new replica ids."""
        if self._factory is None:
            raise RuntimeError(
                "this fleet was not built with Fleet.build(model_factory"
                "=...); it has no recipe to provision new replicas from")
        if n < 1:
            raise ValueError(f"grow() needs n >= 1, got {n}")
        with self._fleet_lock:
            next_rid = max(r.rid for r in self.replicas) + 1
        fresh: List[Replica] = []
        for k in range(n):
            rid = next_rid + k
            eng = InferenceEngine(self._factory(rid), self._config,
                                  checkpoint_dir=self._checkpoint_dir,
                                  replica_id=rid,
                                  shard_set=self.shard_set)
            fresh.append(Replica(eng, rid, state=PROBING))
        self._start_engines(fresh)
        with self._fleet_lock:
            self.replicas = self.replicas + fresh
            self.grows += n
        ids = [r.rid for r in fresh]
        log_fleet.warning("fleet grew by %d replica(s) %s (now %d); "
                          "awaiting admission probes", n, ids,
                          len(self.replicas))
        return ids

    def shrink(self, n: int = 1, deadline_s: float = 10.0) -> List[int]:
        """Retire `n` healthy STABLE replicas (highest rid first —
        canary/shadow cohorts and already-ejected replicas are never
        chosen), always leaving at least one. Queued requests drain with
        a typed ReplicaDown so the router retries them on survivors;
        the engine then closes. Returns the retired replica ids."""
        if n < 1:
            raise ValueError(f"shrink() needs n >= 1, got {n}")
        with self._fleet_lock:
            victims = [r for r in self.replicas
                       if r.state == HEALTHY and r.cohort == "stable"]
            victims = sorted(victims, key=lambda r: r.rid)[-n:]
            keep_floor = 1
            while (len(self.replicas) - len(victims)) < keep_floor \
                    and victims:
                victims.pop()
            if not victims:
                return []
            gone = {r.rid for r in victims}
            self.replicas = [r for r in self.replicas
                             if r.rid not in gone]
            self.shrinks += len(victims)
        for r in victims:
            r.eject("retired by autoscaler shrink")
            try:
                r.engine.close(deadline_s)
            except Exception as e:   # noqa: BLE001 — a wedged retiree
                log_fleet.warning("shrink: replica %d close failed "
                                  "(%s)", r.rid, e)
        ids = [r.rid for r in victims]
        log_fleet.warning("fleet shrank by %d replica(s) %s (now %d)",
                          len(ids), ids, len(self.replicas))
        return ids

    def close(self, deadline_s: float = 10.0) -> None:
        errs = []
        for r in self.replicas:
            try:
                r.engine.close(deadline_s)
            except Exception as e:   # noqa: BLE001 — close every
                errs.append(e)       # replica before reporting
        if errs:
            raise errs[0]

    # --- observability -------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Fleet-wide aggregation: totals across replicas plus merged
        latency percentiles over every replica's window (percentiles do
        not average — merge the samples, then cut)."""
        per = {r.rid: r.stats() for r in self.replicas}
        lat: List[float] = []
        for r in self.replicas:
            # the engine windows are obs Reservoirs (self-locking;
            # samples() snapshots) — merge the samples, THEN cut the
            # percentile: percentiles do not average
            lat.extend(r.engine._lat_ms.samples())
        lat.sort()
        totals = {k: sum(p["engine"][k] for p in per.values())
                  for k in ("requests", "responses", "overloaded",
                            "timeouts", "batches", "queue_depth",
                            "reloads", "reload_rejects")}
        dispatched = sum(p["engine"]["requests"] for p in per.values())
        out = {
            "replicas": per,
            "size": len(self.replicas),
            "healthy": len(self.healthy()),
            "states": {r.rid: r.state for r in self.replicas},
            "p50_ms": percentile(lat, 50),
            "p99_ms": percentile(lat, 99),
            "totals": totals,
            "requests_dispatched": dispatched,
            "grows": self.grows,
            "shrinks": self.shrinks,
        }
        if self.shard_set is not None:
            out["shard_set"] = self.shard_set.stats()
        return out
