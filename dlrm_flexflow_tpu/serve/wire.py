"""The serving seams' binary wire protocol (frames + payload codecs).

Everything "distributed" in the serving stack used to be method calls
inside one process; this module is the byte-level contract that lets the
same seams cross real process boundaries (ROADMAP item 1). One frame is

    +--------+-----+--------+------+----------------+--------+---------+
    | magic  | ver | opcode | pad  |   request-id   | length |  CRC-32 |
    | 4 B    | 1 B | 1 B    | 2 B  |      8 B       |  4 B   |   4 B   |
    +--------+-----+--------+------+----------------+--------+---------+
    |                      payload (length bytes)                      |
    +------------------------------------------------------------------+

big-endian, 24-byte header. The CRC-32 covers the payload; a mismatch
(or a bad magic/version/oversized length) raises :class:`FrameError`,
which the transport treats as transient — close the connection, retry
within the budget. The request-id is the idempotency key: a client
retries (and fault injection duplicates) frames under the SAME id, and
the server's dedup window answers repeats from cache without re-running
the handler.

Payloads are deterministic in-memory npz containers (STORED zip of
``.npy`` members plus a ``__meta__.json`` entry) — the same framing the
delta files on disk use, so the quantized lookup payloads of PR 14
(codes + row scales + dtype) and the per-shard delta slices of PR 10
(rows/full/crc) ship over the wire byte-compatibly with how they are
persisted. Version vectors, ``degraded`` flags, and slice CRCs travel
in the JSON meta, in-band.

Codecs only — no sockets here. serve/transport.py carries these frames.
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

MAGIC = b"FFWP"
WIRE_VERSION = 1
# one frame's payload ceiling: a full shard install of a large tier is
# the biggest legitimate message; anything past this is a corrupt
# length field, not a real payload
MAX_FRAME_BYTES = 1 << 31

_HDR = struct.Struct(">4sBBxxQII")
HEADER_BYTES = _HDR.size  # 24

# --- opcodes ----------------------------------------------------------
# requests are low; a response echoes the request opcode with RESP_BIT
# set; OP_ERR is the one response opcode that can answer anything
OP_LOOKUP = 0x01      # shard seam: batched row lookup
OP_PUBLISH = 0x02     # shard seam: one delta publish's slice
OP_INSTALL = 0x03     # shard seam: full block replacement
OP_PROBE = 0x04       # shard seam: identity/version/freshness
OP_STATS = 0x05       # any server: stats() snapshot
OP_PREDICT = 0x10     # ranker seam: synchronous predict
OP_HEALTH = 0x11      # ranker seam: healthz snapshot
OP_MANIFEST = 0x20    # watcher seam: publish-directory manifest
OP_FETCH = 0x21       # watcher seam: one published file's bytes
RESP_BIT = 0x80
OP_ERR = 0xFF

OPCODE_NAMES = {
    OP_LOOKUP: "lookup", OP_PUBLISH: "publish", OP_INSTALL: "install",
    OP_PROBE: "probe", OP_STATS: "stats", OP_PREDICT: "predict",
    OP_HEALTH: "health", OP_MANIFEST: "manifest", OP_FETCH: "fetch",
    OP_ERR: "err",
}


def opcode_name(op: int) -> str:
    base = OPCODE_NAMES.get(op & ~RESP_BIT, f"op{op:#04x}")
    return base + ("+resp" if op & RESP_BIT and op != OP_ERR else "")


class FrameError(Exception):
    """A malformed or corrupted frame: bad magic, unknown protocol
    version, an impossible length, or a payload failing its CRC-32.
    Transient from the transport's point of view — the connection is
    poisoned (stream framing is lost), so the client closes it and
    retries on a fresh one within its budget."""


# --- frame codec ------------------------------------------------------
def encode_frame(opcode: int, request_id: int, payload: bytes) -> bytes:
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameError(
            f"payload of {len(payload)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte frame ceiling")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return _HDR.pack(MAGIC, WIRE_VERSION, opcode & 0xFF,
                     request_id & 0xFFFFFFFFFFFFFFFF,
                     len(payload), crc) + payload


def decode_header(header: bytes) -> Tuple[int, int, int, int]:
    """(opcode, request_id, length, crc) from a 24-byte header; raises
    FrameError on bad magic / version / length."""
    if len(header) != HEADER_BYTES:
        raise FrameError(f"short header: {len(header)} of "
                         f"{HEADER_BYTES} bytes")
    magic, ver, opcode, rid, length, crc = _HDR.unpack(header)
    if magic != MAGIC:
        raise FrameError(f"bad magic {magic!r} (expected {MAGIC!r} — "
                         f"not a wire-protocol peer?)")
    if ver != WIRE_VERSION:
        raise FrameError(f"wire version {ver} (this build speaks "
                         f"{WIRE_VERSION})")
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds the "
                         f"{MAX_FRAME_BYTES}-byte ceiling (corrupt "
                         f"length field)")
    return opcode, rid, length, crc


def decode_frame(buf: bytes) -> Tuple[int, int, bytes]:
    """(opcode, request_id, payload) from one complete frame's bytes,
    CRC-verified."""
    opcode, rid, length, crc = decode_header(buf[:HEADER_BYTES])
    payload = buf[HEADER_BYTES:HEADER_BYTES + length]
    if len(payload) != length:
        raise FrameError(f"truncated frame: payload {len(payload)} of "
                         f"{length} bytes")
    got = zlib.crc32(payload) & 0xFFFFFFFF
    if got != crc:
        raise FrameError(f"frame CRC mismatch: payload sums to "
                         f"{got:#010x}, header declares {crc:#010x} "
                         f"(corrupt in transit)")
    return opcode, rid, payload


def read_frame(sock) -> Tuple[int, int, bytes]:
    """Read exactly one frame off a socket; FrameError on corruption,
    ConnectionError on EOF mid-frame."""
    header = _recv_exact(sock, HEADER_BYTES)
    opcode, rid, length, crc = decode_header(header)
    payload = _recv_exact(sock, length)
    got = zlib.crc32(payload) & 0xFFFFFFFF
    if got != crc:
        raise FrameError(f"frame CRC mismatch: payload sums to "
                         f"{got:#010x}, header declares {crc:#010x} "
                         f"(corrupt in transit)")
    return opcode, rid, payload


def write_frame(sock, opcode: int, request_id: int,
                payload: bytes) -> None:
    sock.sendall(encode_frame(opcode, request_id, payload))


def _recv_exact(sock, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-frame ({got} of {n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


# --- payload codec ----------------------------------------------------
_META_NAME = "__meta__.json"


def encode_payload(meta: Dict[str, Any],
                   arrays: Optional[Dict[str, np.ndarray]] = None
                   ) -> bytes:
    """JSON meta + named ndarrays as a deterministic STORED zip of
    ``.npy`` members (the delta files' on-disk framing, in memory).
    Array names may contain '/' — they are zip entry names, not
    keywords."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_STORED,
                         allowZip64=True) as zf:
        info = zipfile.ZipInfo(_META_NAME, date_time=(1980, 1, 1,
                                                      0, 0, 0))
        zf.writestr(info, json.dumps(meta, sort_keys=True))
        for name in sorted(arrays or {}):
            arr = np.ascontiguousarray((arrays or {})[name])
            info = zipfile.ZipInfo(name + ".npy",
                                   date_time=(1980, 1, 1, 0, 0, 0))
            with zf.open(info, "w", force_zip64=True) as f:
                np.lib.format.write_array(f, arr, allow_pickle=False)
    return buf.getvalue()


def decode_payload(data: bytes
                   ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """(meta, arrays) back from :func:`encode_payload` bytes; a torn or
    foreign container is a FrameError (transient to the transport)."""
    try:
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            meta = json.loads(zf.read(_META_NAME).decode("utf-8"))
            arrays = {}
            for name in zf.namelist():
                if not name.endswith(".npy"):
                    continue
                with zf.open(name) as f:
                    arrays[name[:-4]] = np.lib.format.read_array(
                        f, allow_pickle=False)
    except (zipfile.BadZipFile, KeyError, ValueError, OSError,
            json.JSONDecodeError) as e:
        raise FrameError(f"payload decode failed: {e}") from None
    if not isinstance(meta, dict):
        raise FrameError(f"payload meta is {type(meta).__name__}, "
                         f"expected an object")
    return meta, arrays


# --- seam codecs: shard lookups ---------------------------------------
def encode_lookup_request(requests: Dict[str, np.ndarray]) -> bytes:
    return encode_payload(
        {"kind": "lookup"},
        {"ids/" + op: np.asarray(ids, np.int64)
         for op, ids in requests.items()})


def decode_lookup_request(data: bytes) -> Dict[str, np.ndarray]:
    _meta, arrays = decode_payload(data)
    return {name[len("ids/"):]: arr for name, arr in arrays.items()
            if name.startswith("ids/")}


def encode_lookup_response(out: Dict[str, Any], version: int) -> bytes:
    """A shard's lookup result: dense rows ship as fp32 matrices,
    quantized ops ship their PR 14 wire payload — codes + row scales +
    dtype tag (the ranker boundary dequantizes). The shard version
    rides in-band."""
    meta: Dict[str, Any] = {"kind": "lookup", "version": int(version),
                            "quant": {}}
    arrays: Dict[str, np.ndarray] = {}
    for op, val in out.items():
        if isinstance(val, tuple):
            codes, scales, dtype = val
            arrays["q/" + op] = codes
            arrays["s/" + op] = scales
            meta["quant"][op] = str(dtype)
        else:
            arrays["rows/" + op] = np.asarray(val, np.float32)
    return encode_payload(meta, arrays)


def decode_lookup_response(data: bytes
                           ) -> Tuple[Dict[str, Any], int]:
    meta, arrays = decode_payload(data)
    out: Dict[str, Any] = {}
    for name, arr in arrays.items():
        if name.startswith("rows/"):
            out[name[len("rows/"):]] = arr
    for op, dtype in (meta.get("quant") or {}).items():
        out[op] = (arrays["q/" + op], arrays["s/" + op], str(dtype))
    return out, int(meta.get("version", 0))


# --- seam codecs: delta publishes -------------------------------------
def encode_publish(sub: Optional[Dict[str, Any]], version: int,
                   expect_crc: Optional[int]) -> bytes:
    """One shard's slice of a delta publish (the output of
    ``split_host_rows_by_shard``): sparse row updates as index+value
    pairs, full-table slices whole, the split-time slice CRC in-band.
    ``sub`` None is a version bump + chain link only."""
    meta: Dict[str, Any] = {"kind": "publish", "version": int(version),
                            "has_sub": sub is not None,
                            "expect_crc": expect_crc}
    arrays: Dict[str, np.ndarray] = {}
    if sub is not None:
        meta["crc"] = int(sub.get("crc", 0))
        meta["row_keys"] = sorted(sub.get("rows", {}))
        meta["full_keys"] = sorted(sub.get("full", {}))
        for key, (idx, vals) in sub.get("rows", {}).items():
            arrays["ri/" + key] = np.asarray(idx, np.int64)
            arrays["rv/" + key] = np.asarray(vals, np.float32)
        for key, arr in sub.get("full", {}).items():
            arrays["full/" + key] = np.asarray(arr, np.float32)
    return encode_payload(meta, arrays)


def decode_publish(data: bytes
                   ) -> Tuple[Optional[Dict[str, Any]], int,
                              Optional[int]]:
    """(sub, version, expect_crc) back from :func:`encode_publish`."""
    meta, arrays = decode_payload(data)
    version = int(meta.get("version", 0))
    expect_crc = meta.get("expect_crc")
    if expect_crc is not None:
        expect_crc = int(expect_crc)
    if not meta.get("has_sub"):
        return None, version, expect_crc
    sub: Dict[str, Any] = {"rows": {}, "full": {},
                           "crc": int(meta.get("crc", 0))}
    for key in meta.get("row_keys", []):
        sub["rows"][key] = (arrays["ri/" + key], arrays["rv/" + key])
    for key in meta.get("full_keys", []):
        sub["full"][key] = arrays["full/" + key]
    return sub, version, expect_crc


# --- seam codecs: full block install (warm boot over the wire) --------
def encode_blocks(blocks: Dict[str, Any], version: int,
                  chain_crc: int) -> bytes:
    """A shard's full blocks (install / warm-cache boot): fp32 blocks
    whole, quantized blocks as codes + scales + dtype — the same
    representation ``utils.warmcache.ShardCache`` persists, so a boot
    over the wire is bit-identical to a boot from disk."""
    from ..quant.store import QuantTable
    meta: Dict[str, Any] = {"kind": "install", "version": int(version),
                            "chain_crc": int(chain_crc) & 0xFFFFFFFF,
                            "quant": {}}
    arrays: Dict[str, np.ndarray] = {}
    for op, blk in blocks.items():
        if isinstance(blk, QuantTable):
            arrays["q/" + op] = blk.encoded()
            arrays["s/" + op] = blk.scales
            meta["quant"][op] = blk.dtype
        else:
            arrays["b/" + op] = np.asarray(blk, np.float32)
    return encode_payload(meta, arrays)


def decode_blocks(data: bytes
                  ) -> Tuple[Dict[str, Any], int, int]:
    """(blocks, version, chain_crc); quantized entries come back as
    QuantTable (codes + scales bit-exact)."""
    from ..quant.store import QuantTable
    meta, arrays = decode_payload(data)
    blocks: Dict[str, Any] = {}
    for name, arr in arrays.items():
        if name.startswith("b/"):
            blocks[name[len("b/"):]] = arr
    for op, dtype in (meta.get("quant") or {}).items():
        blocks[op] = QuantTable.from_encoded(
            arrays["q/" + op], arrays["s/" + op], str(dtype))
    return (blocks, int(meta.get("version", 0)),
            int(meta.get("chain_crc", 0)) & 0xFFFFFFFF)


# --- seam codecs: ranker predict --------------------------------------
def encode_predict_request(features: Dict[str, np.ndarray]) -> bytes:
    return encode_payload(
        {"kind": "predict"},
        {"f/" + k: np.asarray(v) for k, v in features.items()})


def decode_predict_request(data: bytes) -> Dict[str, np.ndarray]:
    _meta, arrays = decode_payload(data)
    return {name[len("f/"):]: arr for name, arr in arrays.items()
            if name.startswith("f/")}


def encode_prediction(pred) -> bytes:
    """A :class:`~.engine.Prediction`, version vector and ``degraded``
    flag in-band (old-or-new-never-mixed must survive the process
    boundary, so the consistency evidence ships with the scores)."""
    versions = pred.versions
    return encode_payload(
        {"kind": "prediction", "version": int(pred.version),
         "latency_ms": float(pred.latency_ms),
         "degraded": bool(pred.degraded),
         "versions": (None if versions is None
                      else {str(k): int(v)
                            for k, v in versions.items()})},
        {"scores": np.asarray(pred.scores)})


def decode_prediction(data: bytes):
    from .engine import Prediction
    meta, arrays = decode_payload(data)
    versions = meta.get("versions")
    if versions is not None:
        versions = {int(k): int(v) for k, v in versions.items()}
    return Prediction(arrays["scores"], int(meta.get("version", 0)),
                      float(meta.get("latency_ms", 0.0)),
                      versions=versions,
                      degraded=bool(meta.get("degraded", False)))


# --- seam codecs: errors ----------------------------------------------
def encode_error(exc: BaseException) -> bytes:
    """A handler failure as data: exception type name + message, plus
    the structured fields the typed serving errors carry (shard id) so
    the client re-raises something the breaker logic already knows."""
    meta = {"kind": "error", "type": type(exc).__name__,
            "message": str(exc)}
    sid = getattr(exc, "shard_id", None)
    if sid is not None:
        meta["shard_id"] = int(sid)
    rid = getattr(exc, "replica_id", None)
    if rid is not None:
        meta["replica_id"] = int(rid)
    return encode_payload(meta)


def decode_error(data: bytes) -> Dict[str, Any]:
    meta, _arrays = decode_payload(data)
    return meta
