"""One lookup shard as an OS process.

::

    python -m dlrm_flexflow_tpu.serve.shard_server \
        --cache-dir /ckpt/cache --nshards 4 --slot 2 --port 0

Boots slot ``--slot`` of an ``--nshards``-way tier from a seeded warm
cache (``EmbeddingShardSet.seed_shard_cache`` wrote the row blocks and
the tier-geometry meta sidecar; no model, no checkpoint, no JAX compile
in this process's serving path) and serves the wire protocol until
killed. ``--port 0`` takes an OS-assigned port; the chosen port is
printed on the ``SHARD_SERVER_OK`` line so a spawner (tests, the
serving example, an init system) can read it from stdout — the same
sentinel contract as ``tests/_mp3_worker.py``.

This is the process boundary ROADMAP item 1 asks for: ``kill -9`` of
this process is a real shard outage — the set's circuit breaker ejects
the slot, responses degrade (flagged), and replace-dead boots a warm
in-process standby from the same cache this process booted from.
"""

from __future__ import annotations

import argparse
import sys


def build_shard(cache_dir: str, nshards: int, slot: int):
    """The boot path, importable for tests: warm-cache blocks + meta
    sidecar -> a live :class:`~.shardtier.EmbeddingShard`."""
    from ..utils.warmcache import ShardCache
    from .shardtier import EmbeddingShard

    cache = ShardCache(cache_dir)
    meta = cache.get_meta(nshards)
    if meta is None:
        raise SystemExit(
            f"shard_server: no tier meta for {nshards} shard(s) in "
            f"{cache_dir!r} ({cache.last_reject or 'missing'}) — seed "
            f"it with EmbeddingShardSet.seed_shard_cache")
    cache.fingerprint = str(meta.get("fingerprint", ""))
    got = cache.get(nshards, slot)
    if got is None:
        raise SystemExit(
            f"shard_server: no cached blocks for slot {slot} of "
            f"{nshards} in {cache_dir!r} "
            f"({cache.last_reject or 'missing'})")
    blocks, version, chain_crc = got
    ranges = {op: tuple(r[slot]) for op, r in meta["ranges"].items()}
    domains = meta.get("domains") or [""] * nshards
    return EmbeddingShard(
        slot, slot, blocks, ranges, version=version,
        chain_crc=chain_crc, domain=str(domains[slot]),
        quant={str(k): str(v)
               for k, v in (meta.get("quant") or {}).items()})


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="serve one embedding lookup shard over the wire "
                    "protocol (boots from a seeded shard warm cache)")
    ap.add_argument("--cache-dir", required=True,
                    help="seeded ShardCache directory "
                         "(EmbeddingShardSet.seed_shard_cache)")
    ap.add_argument("--nshards", type=int, required=True,
                    help="total shard count of the tier")
    ap.add_argument("--slot", type=int, required=True,
                    help="which row-range slot this process owns")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="listen port; 0 = OS-assigned (printed on "
                         "the SHARD_SERVER_OK line)")
    args = ap.parse_args(argv)
    if not 0 <= args.slot < args.nshards:
        ap.error(f"--slot {args.slot} outside [0, {args.nshards})")

    shard = build_shard(args.cache_dir, args.nshards, args.slot)
    server = shard.serve(host=args.host, port=args.port)
    print(f"SHARD_SERVER_OK slot={args.slot} "
          f"port={server.address[1]} version={shard.version}",
          flush=True)
    try:
        server.serve_forever()   # start() is idempotent; blocks here
    except KeyboardInterrupt:
        pass
    finally:
        server.close()


if __name__ == "__main__":
    main()
