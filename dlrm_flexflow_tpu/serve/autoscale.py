"""SLO-driven fleet autoscaler: grow under sustained load, shrink when
idle, replace dead replicas.

The serving stack so far could only SHED load: a saturated engine raises
``Overloaded``, the router retries elsewhere, and when every replica is
saturated the client eats ``FleetUnavailable``. This module closes the
loop the other way — a policy thread reads ``FleetRouter.stats()`` every
``interval_s`` and drives :meth:`~.fleet.Fleet.grow` /
:meth:`~.fleet.Fleet.shrink`:

- **Grow** when the client-observed p99 exceeds ``slo_ms`` (or the mean
  queue depth per healthy replica exceeds ``queue_hwm``) for ``sustain``
  consecutive evaluation periods. New replicas boot from the persistent
  compile cache when one is configured (``--compile-cache-dir``), warm
  their buckets concurrently, enter PROBING, and are admitted only after
  the router's end-to-end probe succeeds — a grow can never inject a
  broken replica into the routable set.
- **Replace** immediately (no sustain debounce) when the healthy count
  falls below ``min_replicas`` — the chaos case: a replica crashes, the
  circuit breaker ejects it, and the autoscaler provisions a substitute
  while the survivors absorb the retried traffic (zero failed requests,
  tests/test_autoscale.py pins it).
- **Shrink** when the fleet has been idle — p99 comfortably inside the
  SLO and queues near empty — for ``idle_sustain`` periods, never below
  ``min_replicas`` and never touching canary/shadow cohorts.

Every decision is debounced (``utils.watchdog.Sustained``), rate-limited
(``cooldown_s`` between actions), bounded (``min_replicas`` ..
``max_replicas``), and recorded in :meth:`stats` with its reason. The
policy thread is ff-named, daemon, stop-signalled and joined on
``close()`` — flexcheck FLX101-104 clean by construction.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..analysis.sanitizer import make_lock
from ..obs import metrics as obsm
from ..obs import trace as obstrace
from ..utils.logging import get_logger
from ..utils.watchdog import Sustained

log_scale = get_logger("serve.autoscale")


@dataclass
class AutoscaleConfig:
    """Policy knobs; ``from_config`` lifts the ``--serve-*`` flags."""

    slo_ms: float = 0.0          # p99 objective; 0 = queue-depth only
    min_replicas: int = 1
    max_replicas: int = 8
    interval_s: float = 0.25     # evaluation period
    sustain: int = 3             # breach periods before a grow
    idle_sustain: int = 12       # idle periods before a shrink
    queue_hwm: float = 4.0       # mean queued reqs / healthy replica
    queue_lwm: float = 0.5       # below this counts as idle
    idle_p99_frac: float = 0.5   # idle also needs p99 < frac * slo
    grow_step: int = 1           # replicas added per grow action
    cooldown_s: float = 1.0      # min seconds between scaling actions
    replace_dead: bool = True    # heal below min_replicas immediately

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}")

    @staticmethod
    def from_config(cfg) -> "AutoscaleConfig":
        return AutoscaleConfig(
            slo_ms=float(getattr(cfg, "serve_slo_ms", 0.0)),
            min_replicas=int(getattr(cfg, "serve_min_replicas", 1)),
            max_replicas=int(getattr(cfg, "serve_max_replicas", 8)))


class Autoscaler:
    """The policy thread over a started :class:`~.router.FleetRouter`.

    The router keeps owning health/probing/ejection; this class only
    decides SIZE. It therefore composes with everything the router
    already does: a grown replica is admitted through the same probe
    machinery an ejected one is re-admitted through, and a shrink drains
    through the same typed-``ReplicaDown`` retry path a crash does.
    """

    def __init__(self, router, config: Optional[AutoscaleConfig] = None,
                 shard_set=None):
        self.router = router
        self.config = config or AutoscaleConfig()
        # the row-sharded lookup tier, when the fleet serves through one
        # (serve/shardtier.py): the autoscaler drives its health ticks —
        # probe/re-admit ejected shards and REPLACE the ones whose
        # probes keep failing (booted from the warm cache, admitted only
        # on probe success). Same replace-dead philosophy as replicas,
        # one tier down.
        self.shard_set = shard_set if shard_set is not None \
            else getattr(router.fleet, "shard_set", None)
        self._shard_replacements = 0
        self._shard_readmissions = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_lock = make_lock("Autoscaler._m_lock")
        self._breach = Sustained(self.config.sustain)
        self._idle = Sustained(self.config.idle_sustain)
        self._last_action_t = 0.0
        self._grows = 0
        self._shrinks = 0
        self._replacements = 0
        self._breaches = 0
        self._last_reason = ""
        self._decisions: List[Dict[str, Any]] = []
        if not router.fleet.can_grow:
            log_scale.warning(
                "fleet was not built via Fleet.build(model_factory=...): "
                "the autoscaler can observe but never grow it")

    # --- lifecycle -----------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._policy_loop,
                                        daemon=True,
                                        name="ff-autoscaler")
        self._thread.start()
        return self

    def close(self, deadline_s: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(deadline_s)
        self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # --- policy --------------------------------------------------------
    def _policy_loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self._tick()
            except Exception:   # noqa: BLE001 — the policy thread must
                # outlive a bad stats read; scaling just skips a beat
                log_scale.exception("autoscaler tick failed")

    def _record(self, action: str, reason: str, detail=None) -> None:
        with self._m_lock:
            self._last_reason = f"{action}: {reason}"
            self._decisions.append({"t": time.time(), "action": action,
                                    "reason": reason, "detail": detail})
            del self._decisions[:-64]
        # labeled decision counter + trace instant: chaos benches (and
        # a scraper) assert on WHICH actions fired, not just how many
        obsm.counter(
            "ff_autoscaler_decisions_total",
            "scaling decisions by action (grow/shrink/replace/"
            "shard-replace/shard-readmit)",
            labelnames=("action",)).inc(action=action)
        obstrace.instant(f"autoscaler/{action}", cat="autoscale",
                         reason=reason[:200])
        log_scale.warning("autoscaler %s (%s)", action, reason)

    def _cooldown_ok(self) -> bool:
        return (time.monotonic() - self._last_action_t
                >= self.config.cooldown_s)

    def _acted(self) -> None:
        self._last_action_t = time.monotonic()
        self._breach.reset()
        self._idle.reset()

    def _tick(self) -> None:
        cfg = self.config
        fleet = self.router.fleet
        self._shard_tick()
        st = self.router.stats()
        healthy = int(st["fleet"]["healthy"])
        size = len(fleet)
        p99 = st.get("p99_ms")
        depth = sum(r.queue_depth for r in fleet.healthy())
        q_per = depth / healthy if healthy else float("inf")

        # 1. heal: a fleet below its floor is not a load question — the
        #    chaos bar (replica dies, autoscaler replaces it, zero
        #    failed requests) keys on this firing without debounce
        if (cfg.replace_dead and fleet.can_grow
                and healthy < cfg.min_replicas
                and size < cfg.max_replicas):
            want = min(cfg.min_replicas - healthy,
                       cfg.max_replicas - size)
            ids = fleet.grow(want)
            with self._m_lock:
                self._replacements += len(ids)
            self._record("replace",
                         f"healthy {healthy} < min {cfg.min_replicas}",
                         {"new": ids})
            self._acted()
            return

        # 2. grow: sustained SLO breach or queue pressure
        over_slo = bool(cfg.slo_ms > 0 and p99 is not None
                        and p99 > cfg.slo_ms)
        over_q = q_per > cfg.queue_hwm
        breach = over_slo or over_q
        if breach:
            with self._m_lock:
                self._breaches += 1
        if (self._breach.observe(breach) and fleet.can_grow
                and self._cooldown_ok() and size < cfg.max_replicas):
            n = min(cfg.grow_step, cfg.max_replicas - size)
            reason = (f"p99 {p99:.1f} ms > SLO {cfg.slo_ms:g} ms"
                      if over_slo else
                      f"queue depth {q_per:.1f}/replica > "
                      f"{cfg.queue_hwm:g}")
            ids = fleet.grow(n)
            with self._m_lock:
                self._grows += len(ids)
            self._record("grow", reason, {"new": ids})
            self._acted()
            return

        # 3. shrink: sustained idle, never below the floor
        idle = (q_per < cfg.queue_lwm and not over_slo
                and (cfg.slo_ms <= 0 or p99 is None
                     or p99 < cfg.idle_p99_frac * cfg.slo_ms))
        if (self._idle.observe(idle) and self._cooldown_ok()
                and healthy > cfg.min_replicas):
            ids = fleet.shrink(1)
            if ids:
                with self._m_lock:
                    self._shrinks += len(ids)
                self._record("shrink",
                             f"idle: queue {q_per:.2f}/replica, p99 "
                             f"{p99 if p99 is None else round(p99, 1)}"
                             f" ms", {"retired": ids})
                self._acted()

    def _shard_tick(self) -> None:
        """Shard-tier health pass: probe shards due for one, replace
        shards whose probes keep failing. No debounce — a dark shard is
        degraded answers RIGHT NOW, the replica floor philosophy applied
        to the lookup tier."""
        if self.shard_set is None or not self.config.replace_dead:
            return
        for action in self.shard_set.health_tick():
            kind = action.get("action")
            if kind == "shard-replace":
                with self._m_lock:
                    self._shard_replacements += 1
                self._record("shard-replace",
                             f"slot {action['slot']} probes kept "
                             f"failing", action)
            elif kind == "shard-probe" and action.get("ok"):
                with self._m_lock:
                    self._shard_readmissions += 1
                self._record("shard-readmit",
                             f"slot {action['slot']} probe succeeded",
                             action)

    # --- observability -------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._m_lock:
            return {
                "grows": self._grows,
                "shrinks": self._shrinks,
                "replacements": self._replacements,
                "shard_replacements": self._shard_replacements,
                "shard_readmissions": self._shard_readmissions,
                "breaches": self._breaches,
                "last_reason": self._last_reason,
                "decisions": list(self._decisions),
                "slo_ms": self.config.slo_ms,
                "min_replicas": self.config.min_replicas,
                "max_replicas": self.config.max_replicas,
                "size": len(self.router.fleet),
                "healthy": len(self.router.fleet.healthy()),
            }
