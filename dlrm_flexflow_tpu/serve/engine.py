"""Online inference engine: dynamic batching over the AOT eval cache.

The training side of this framework amortizes dispatch overhead by fusing
steps (supersteps); the read path amortizes it by COALESCING REQUESTS —
the Clipper/Orca discipline. ``InferenceEngine`` accepts per-request
feature dicts from any number of threads, queues them in a bounded queue,
and a single batcher thread forms dynamic batches. Admission is
**continuous** (iteration-level, à la Orca) by default: the moment a
dispatch completes, everything that queued up WHILE it ran forms the
next batch and goes out immediately — the dispatch itself is the
coalescing window, no artificial delay is ever inserted, and a request
never waits out a flush cycle it arrived in the middle of. The
pre-continuous **flush-cycle** mode (``continuous=False``) is kept for
comparison: there a batch flushes only when it reaches ``max_batch``
rows (size-flush) or when its oldest request has waited ``max_delay_ms``
(deadline-flush), so a partial batch always pays the delay even on an
idle engine. Either way every batch is zero-padded up to a small
set of power-of-two buckets so each dispatch hits one of a FIXED set of
pre-compiled AOT executables (all buckets are warmed at ``start()`` —
no live request ever pays a compile), and the padded rows are sliced off
before the response: per-request scores are bit-identical to a direct
``forward_batch`` of the same rows.

Operational contracts:

- **Backpressure**: a submit against a full queue raises a typed
  :class:`Overloaded` immediately — the caller sheds load; the engine
  never buffers unboundedly.
- **Deadlines**: a request still waiting past ``deadline_ms`` fails with
  :class:`DeadlineExceeded` (a :class:`~..utils.watchdog.WorkerStalled`
  carrying the structured :class:`~..utils.watchdog.StallReport`) instead
  of occupying a batch slot.
- **Zero-downtime reload**: :class:`~.watcher.SnapshotWatcher` polls a
  ``CheckpointManager`` directory and stages new params via
  ``install_snapshot``; the batcher thread applies the swap BETWEEN
  dispatches (the swap lock only guards the reference hand-off — no
  lock is ever held across the dispatch itself, which ``FF_SANITIZE=1``
  asserts). In-flight batches finish on the old weights, the next
  dispatch sees the new ones, and every response carries the version
  (checkpoint step) it was computed with: old-or-new, never a mix.
- **Observability**: ``stats()`` reports p50/p99 latency, batch-fill
  fraction, queue depth, embedding-cache hit rate, reload counts, and
  the eval-executable-cache occupancy/evictions.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Dict, List, NamedTuple, Optional

import numpy as np

from ..analysis.sanitizer import make_lock
from ..data.dataloader import coalesce_batches
from ..obs import metrics as obsm
from ..obs import trace as obstrace
# THE percentile of the codebase now lives with the other window math
# in obs.metrics (same semantics: linear interpolation, None on an
# empty window — never a flawless p99 for a server that answered
# nothing); re-exported here because the fleet/router/benches have
# always imported it from serve.engine
from ..obs.metrics import percentile  # noqa: F401 — re-export
from ..utils import faults
from ..utils.logging import get_logger
from ..utils.watchdog import Deadline, Heartbeat, WorkerStalled
from .cache import EmbeddingCache

log_serve = get_logger("serve")


class Overloaded(RuntimeError):
    """The bounded request queue is full — typed backpressure. Callers
    shed or retry with backoff; the engine never buffers unboundedly."""

    def __init__(self, depth: int, capacity: int):
        super().__init__(
            f"serving queue full ({depth}/{capacity} requests) — "
            f"rejecting (backpressure); retry with backoff or raise "
            f"--serve-queue")
        self.depth = depth
        self.capacity = capacity


class DeadlineExceeded(WorkerStalled, TimeoutError):
    """A request missed its per-request deadline while queued. Reuses
    the watchdog's structured StallReport so serving timeouts and
    training-worker stalls read the same way in logs/alerts."""


class ReplicaDown(RuntimeError):
    """This replica's serving process is gone — a crash (injected by
    ``FF_FAULT_REPLICA_DOWN``), a dead batcher thread, or the router's
    circuit breaker draining an ejected replica's queue. Retryable: the
    fleet router re-routes the failed request to a surviving replica."""

    def __init__(self, replica_id: Optional[int] = None, detail: str = ""):
        rid = "?" if replica_id is None else replica_id
        super().__init__(f"serving replica {rid} is down"
                         + (f": {detail}" if detail else ""))
        self.replica_id = replica_id


class Prediction(NamedTuple):
    """Per-request result: model scores for the request's rows, the
    weight version (checkpoint step) that computed them, and the
    end-to-end latency.

    Under the sharded serving tier (serve/shardtier.py) two more fields
    are populated: ``versions`` is the VERSION VECTOR — the per-shard
    versions this request's embedding lookups actually read (keyed by
    shard slot; old-or-new-never-mixed holds per shard, so each slot
    appears with exactly one version) — and ``degraded`` is True when
    any of the request's rows were answered from cache hits + per-table
    default rows because a shard was out (the response is SERVED, just
    flagged; see EmbeddingShardSet)."""

    scores: np.ndarray
    version: int
    latency_ms: float
    versions: Optional[Dict[int, int]] = None
    degraded: bool = False


@dataclass
class ServeConfig:
    """Engine knobs; ``from_config`` lifts the ``--serve-*`` flags."""

    max_batch: int = 64          # largest bucket / flush-on-size bound
    max_delay_ms: float = 5.0    # flush-mode deadline for a partial batch
    queue_capacity: int = 256    # bounded queue -> Overloaded past this
    deadline_ms: float = 0.0     # per-request budget; 0 = none
    cache_rows: int = 0          # embedding-row cache capacity; 0 = off
    cache_warm: str = ""         # id-histogram npz (or checkpoint dir)
    #                              to pre-warm the row cache from
    poll_s: float = 0.5          # snapshot-watcher poll interval
    warmup: bool = True          # AOT-compile all buckets at start()
    continuous: bool = True      # iteration-level admission (Orca);
    #                              False = pure size/deadline flush
    reshard: bool = False        # allow cross-mesh snapshot reloads (a
    #                              per-device fleet replica following a
    #                              multi-device trainer's snapshots)

    @staticmethod
    def from_config(cfg) -> "ServeConfig":
        return ServeConfig(
            max_batch=int(getattr(cfg, "serve_max_batch", 64)),
            max_delay_ms=float(getattr(cfg, "serve_max_delay_ms", 5.0)),
            queue_capacity=int(getattr(cfg, "serve_queue", 256)),
            deadline_ms=float(getattr(cfg, "serve_deadline_ms", 0.0)),
            cache_rows=int(getattr(cfg, "serve_cache_rows", 0)),
            cache_warm=str(getattr(cfg, "serve_cache_warm", "")),
            poll_s=float(getattr(cfg, "serve_poll_s", 0.5)),
            continuous=(getattr(cfg, "serve_batching", "continuous")
                        != "flush"),
            reshard=bool(getattr(cfg, "serve_replicas", 1) > 1))


class _Request:
    __slots__ = ("features", "rows", "future", "t0", "deadline")

    def __init__(self, features, rows, deadline_s: float):
        self.features = features
        self.rows = rows
        self.future: Future = Future()
        self.t0 = time.monotonic()
        self.deadline = Deadline(deadline_s) if deadline_s > 0 else None


class InferenceEngine:
    """Thread-safe dynamic-batching server over a compiled FFModel.

    The model must be compiled + initialized (or restored). The engine
    owns the model's serving lifecycle from ``start()`` to ``close()``;
    training the same model concurrently is not supported (the trainer
    runs in its own process and publishes snapshots via
    ``CheckpointManager`` — see :class:`~.watcher.SnapshotWatcher`).
    """

    def __init__(self, model, config: Optional[ServeConfig] = None,
                 checkpoint_dir: Optional[str] = None,
                 replica_id: Optional[int] = None,
                 shard_set=None):
        if model.params is None:
            raise ValueError("InferenceEngine needs an initialized model "
                             "(init_layers() or restore_checkpoint())")
        self._model = model
        # the row-sharded lookup tier (serve/shardtier.py): when set,
        # this engine is a STATELESS RANKER — sparse ids resolve through
        # the shard set (fronted by the per-ranker EmbeddingCache), host
        # rows of publishes route to the owning shards, and responses
        # carry the per-shard version vector + degraded flag
        self._shard_set = shard_set
        self._lookup_meta = None   # batcher-thread scratch (per batch)
        # fleet identity: names the batcher thread, keys the per-replica
        # fault hooks (FF_FAULT_REPLICA_DOWN / per-replica serve delay)
        self.replica_id = replica_id
        self.config = config or ServeConfig.from_config(model.config)
        if self.config.max_batch < 1:
            raise ValueError("serve max_batch must be >= 1")
        self._buckets = tuple(model.bucket_sizes(self.config.max_batch))
        if self._buckets[-1] != self.config.max_batch:
            log_serve.warning(
                "serve max_batch %d is not an admissible bucket; "
                "clamping to %d (buckets %s)", self.config.max_batch,
                self._buckets[-1], self._buckets)
        self.max_batch = self._buckets[-1]
        self._input_names = {t.name for t in model.input_tensors}
        # per-sample shapes for submit-time validation: a wrong-shaped
        # feature must fail THERE as a non-retryable ValueError — at
        # dispatch it would fail the whole batch, burn the router's
        # retry budget, and trip the circuit breaker (one malformed
        # client ejecting every replica is how a fleet goes down)
        self._input_sample_shapes = {t.name: tuple(t.shape[1:])
                                     for t in model.input_tensors}
        # embedding-row cache only applies to host-resident tables
        self._cache: Optional[EmbeddingCache] = None
        if (self.config.cache_rows > 0
                and getattr(model, "_host_resident_list", None)):
            # under a quantized storage policy the cache stores
            # codes + row scales (~4x more hot rows per MB) and
            # dequantizes at the ranker boundary on every hit
            quant = {name: pol.dtype for name, pol in
                     (getattr(model, "quant_policies", dict)()
                      or {}).items()
                     if getattr(pol, "is_quantized", False)}
            self._cache = EmbeddingCache(self.config.cache_rows,
                                         quant=quant)
        self._checkpoint_dir = checkpoint_dir
        # persistent compile cache (utils/warmcache.py): when the model
        # config enables one, bucket warmup deserializes stored AOT
        # executables instead of recompiling — a replica cold start (or
        # autoscaler grow) costs milliseconds on a cache hit
        if hasattr(model, "_attach_configured_caches"):
            model._attach_configured_caches(checkpoint_dir)
        self._watcher = None
        # queue + batcher state
        self._q: "deque[_Request]" = deque()
        self._q_rows = 0
        self._cond = threading.Condition()
        self._closing = False
        self._started = False
        self._thread: Optional[threading.Thread] = None
        # swap staging: a hot reload PARKS the new state here under the
        # lock; the batcher applies it between dispatches. The lock only
        # ever guards reference hand-off — never file IO, device_puts,
        # or the dispatch itself (the FF_SANITIZE no-dispatch assertion
        # and flexcheck's FLX203 both pin that), so a slow reload can
        # never stall the serving hot path behind the lock.
        self._swap_lock = make_lock(
            f"InferenceEngine._swap_lock[{replica_id}]",
            no_dispatch=True)
        # ordered queue of parked installs: ("full", state, ...) entries
        # replace everything queued before them; ("delta", payload, ...)
        # entries are INCREMENTAL and append — the batcher drains the
        # queue in order between dispatches
        self._pending: List[tuple] = []
        self._version = int(getattr(model, "_step", 0))
        # version of the params the batcher has actually applied; the
        # response tag (== _version once the pending swap lands)
        self._applied_version = self._version
        # whether ANY snapshot install has been applied: until then the
        # engine serves the model's own (constructor-time) state, whose
        # version number can numerically coincide with a published
        # step without being that state — the watcher must not treat
        # it as a delta-chain node (it would patch rows onto the wrong
        # base params)
        self._applied_any = False
        # stats (their own lock: stats() readers race the batcher's
        # appends — iterating a deque mid-append raises)
        self._stats_lock = make_lock(
            f"InferenceEngine._stats_lock[{replica_id}]")
        # bounded latency window (obs Reservoir): same deque-shaped API
        # the fleet merges over, but the window doubles as a scrapeable
        # registry histogram when --obs on
        self._lat_ms = obsm.latency_reservoir(
            "ff_serve_request_latency_ms",
            "end-to-end request latency at the engine", maxlen=4096,
            replica="" if replica_id is None else str(replica_id))
        self._n_requests = 0
        self._n_responses = 0
        self._n_overloaded = 0
        self._n_timeouts = 0
        self._n_batches = 0
        self._rows_served = 0
        self._rows_padded = 0
        self._reloads = 0
        self._delta_reloads = 0
        self._reload_rejects = 0
        self._last_reject = ""
        self._n_degraded = 0
        self._last_versions: Dict[int, int] = {}
        self._warmup_s = 0.0
        # how each dispatched batch was formed (continuous admission vs
        # flush-mode size/deadline) — lets the fleet bench verify the
        # continuous path is actually taken
        self._flushes = {"continuous": 0, "size": 0, "deadline": 0}
        # liveness: the batcher beats once around its loop; the fleet
        # router's health thread ejects a replica whose heartbeat goes
        # stale (wedged dispatch) before any request even errors
        self._heartbeat = Heartbeat(self._thread_name())

    def _thread_name(self) -> str:
        return ("ff-serve-batcher" if self.replica_id is None
                else f"ff-serve-batcher-{self.replica_id}")

    # --- lifecycle -----------------------------------------------------
    def start(self) -> "InferenceEngine":
        """Warm every bucket's executable, start the batcher (and the
        snapshot watcher when a checkpoint dir was given)."""
        if self._started:
            return self
        self._started = True
        if self.config.warmup:
            self._warmup_s = self._model.warmup_buckets(
                self._buckets, host_gather=self._host_gather())
            log_serve.info("warmed %d bucket executables %s in %.0f ms",
                           len(self._buckets), list(self._buckets),
                           1e3 * self._warmup_s)
        self._prewarm_cache()
        self._thread = threading.Thread(target=self._batcher, daemon=True,
                                        name=self._thread_name())
        self._thread.start()
        # registry collector: the stats() counters become scrapeable
        # time series without double-counting (no-op when --obs off)
        obsm.register_collector(self._obs_collect)
        if self._checkpoint_dir:
            from .watcher import SnapshotWatcher
            self._watcher = SnapshotWatcher(
                self, self._checkpoint_dir, poll_s=self.config.poll_s,
                elastic=self.config.reshard)
            self._watcher.start()
        return self

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Expose this engine's dispatch surface (predict / health /
        stats / probe) on a wire socket; returns the started
        :class:`~.transport.EngineServer` (``address`` carries the
        OS-assigned port when ``port=0``). The engine itself must
        already be :meth:`start`-ed."""
        from .transport import EngineServer
        return EngineServer(self, host=host, port=port).start()

    def serve_forever(self, host: str = "127.0.0.1",
                      port: int = 0) -> None:
        """Run this engine as a blocking socket server — the body of a
        ranker-replica OS process; the router reaches it through
        :class:`~.transport.RemoteEngineClient`."""
        from .transport import EngineServer
        EngineServer(self, host=host, port=port).serve_forever()

    def close(self, deadline_s: float = 10.0) -> None:
        """Drain the queue (pending requests still get answers), stop
        the batcher + watcher. A wedged batcher surfaces as a typed
        WorkerStalled instead of hanging the caller."""
        with self._cond:
            if not self._started or self._closing:
                self._closing = True
                return
            self._closing = True
            self._cond.notify_all()
        obsm.unregister_collector(self._obs_collect)
        if self._watcher is not None:
            self._watcher.stop()
        t = self._thread
        if t is not None and t.is_alive():
            dl = Deadline(deadline_s)
            t.join(deadline_s if deadline_s > 0 else None)
            if t.is_alive():
                raise WorkerStalled(dl.report(
                    worker=t.name, waiting_for="serving queue drain",
                    detail=f"{len(self._q)} requests still queued"))

    def __enter__(self) -> "InferenceEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # --- request path --------------------------------------------------
    def submit(self, features: Dict[str, np.ndarray]) -> Future:
        """Enqueue one request (1+ rows); returns a Future resolving to
        a :class:`Prediction`. Raises :class:`Overloaded` when the
        bounded queue is full, ValueError on malformed features."""
        feats = {}
        for k, v in features.items():
            if k not in self._input_names:
                raise ValueError(
                    f"unknown input {k!r}; model inputs are "
                    f"{sorted(self._input_names)}")
            arr = np.asarray(v)
            want = self._input_sample_shapes[k]
            if arr.ndim >= 1 and tuple(arr.shape[1:]) != want:
                import math
                if (arr.ndim and want
                        and math.prod(arr.shape[1:]) == math.prod(want)):
                    # same per-sample element count, different layout
                    # (e.g. sparse (n, T) for a (n, T, 1) bag input):
                    # the reshape is unambiguous, accept it
                    arr = arr.reshape((arr.shape[0],) + want)
                else:
                    raise ValueError(
                        f"input {k!r} rows have per-sample shape "
                        f"{tuple(arr.shape[1:])}; the model expects "
                        f"{want}")
            feats[k] = arr
        missing = self._input_names - set(feats)
        if missing:
            raise ValueError(f"request is missing inputs {sorted(missing)}")
        rows = {int(v.shape[0]) if v.ndim else -1 for v in feats.values()}
        if len(rows) != 1 or -1 in rows:
            raise ValueError(
                f"request inputs disagree on the sample dim: {rows}")
        n = rows.pop()
        if n < 1:
            raise ValueError("request must carry at least one row")
        if n > self.max_batch:
            raise ValueError(
                f"request rows {n} exceed serve max_batch "
                f"{self.max_batch}; split the request")
        req = _Request(feats, n, self.config.deadline_ms / 1e3)
        with obstrace.span("serve/enqueue", rows=n), self._cond:
            if self._closing:
                raise RuntimeError("engine is closed")
            if not self._started:
                raise RuntimeError("engine not started (call start())")
            if len(self._q) >= self.config.queue_capacity:
                self._n_overloaded += 1
                raise Overloaded(len(self._q), self.config.queue_capacity)
            self._q.append(req)
            self._q_rows += n
            self._n_requests += 1
            self._cond.notify_all()
        return req.future

    def predict(self, features: Dict[str, np.ndarray],
                timeout: Optional[float] = None) -> Prediction:
        """Synchronous submit+wait."""
        return self.submit(features).result(timeout)

    # --- batcher -------------------------------------------------------
    def _batcher(self) -> None:
        while True:
            # parked hot reloads apply HERE, on the dispatch thread,
            # outside the condition lock — an idle engine picks a new
            # snapshot up within one wakeup, a busy one between batches
            self._apply_pending_swap()
            take: List[_Request] = []
            flush = "continuous"
            t_form = time.perf_counter()
            with self._cond:
                self._heartbeat.beat()
                while (not self._q and not self._closing
                        and not self._pending):
                    self._cond.wait(0.1)
                    self._heartbeat.beat()
                if not self._q and self._closing:
                    return
                if not self._q:   # woken only to apply a parked swap
                    continue
                if not self.config.continuous:
                    # flush-cycle mode: a batch is open from the moment
                    # its OLDEST request arrived; flush on size
                    # (max_batch rows coalesced) or on that request's
                    # age (max_delay)
                    t_flush = (self._q[0].t0
                               + self.config.max_delay_ms / 1e3)
                    while (self._q_rows < self.max_batch
                           and not self._closing):
                        left = t_flush - time.monotonic()
                        if left <= 0:
                            break
                        self._cond.wait(left)
                        self._heartbeat.beat()
                        if not self._q:  # all timed out? (can't happen:
                            break        # only this thread pops)
                    flush = ("size" if self._q_rows >= self.max_batch
                             else "deadline")
                # continuous mode pops straight away: whatever queued up
                # while the previous dispatch executed (or the request
                # that just woke an idle batcher) IS the next batch —
                # the dispatch latency is the coalescing window, and a
                # request never waits out a flush cycle
                rows = 0
                while self._q and rows + self._q[0].rows <= self.max_batch:
                    r = self._q.popleft()
                    self._q_rows -= r.rows
                    rows += r.rows
                    take.append(r)
            if take:
                # the window from waking to a formed batch IS the
                # coalescing window in continuous mode — a batch-
                # formation stall shows as a long span here
                obstrace.complete("serve/batch-form", t_form,
                                  requests=len(take), flush=flush)
                with self._stats_lock:
                    self._flushes[flush] += 1
                try:
                    self._dispatch(take)
                except BaseException as e:   # noqa: BLE001 — a model
                    # error must fail THESE requests, not kill serving
                    for r in take:
                        if not r.future.done():
                            r.future.set_exception(e)

    def prewarm_cache_from(self, sketches) -> None:
        """Pre-warm the embedding-row cache from LIVE sketches ({op ->
        IdFrequencySketch}) instead of a published file — the online
        re-placement controller calls this right after a placement swap
        so the cache restarts hot against the NEW distribution."""
        self._prewarm_cache(hists=sketches)

    def _prewarm_cache(self, hists=None) -> None:
        """Pre-warm the embedding-row cache from a published
        id-frequency histogram (``--serve-cache-warm PATH``: the
        ``id_histogram.npz`` a DeltaPublisher writes next to its
        snapshots, or the checkpoint directory holding one), or from the
        in-memory ``hists`` mapping when one is passed. Sample
        index tuples are drawn from the per-table observed marginals —
        zipfian traffic concentrates on few tuples, so a fresh replica
        starts with the hot working set cached instead of paying cold
        host gathers for it. Non-fatal: a missing/foreign histogram
        just starts cold."""
        if self._cache is None:
            return
        if hists is None and not self.config.cache_warm:
            return
        if getattr(self._model, "_host_tables_released", False):
            log_serve.info("cache pre-warm skipped: ranker tables "
                           "released to the shard tier (warm hits come "
                           "from live traffic instead)")
            return
        if hists is None:
            import os

            from ..utils.histogram import HISTOGRAM_FILE, load_histograms
            path = self.config.cache_warm
            if os.path.isdir(path):
                path = os.path.join(path, HISTOGRAM_FILE)
            try:
                hists = load_histograms(path)
            except (IOError, OSError, ValueError, KeyError) as e:
                log_serve.warning(
                    "cache pre-warm skipped: cannot read id histogram "
                    "%s (%s)", path, e)
                return
        else:
            path = "<live sketches>"
        model = self._model
        rng = np.random.RandomState(0)
        n = max(min(self.config.cache_rows, 2048), 1)
        warmed = 0
        for op in model._host_resident_list:
            sk = hists.get(op.name)
            if sk is None:
                continue
            sample_shape = tuple(op.inputs[0].shape[1:])  # (T, bag)|(bag,)
            if hasattr(op, "table_sizes"):        # concat: offset ranges
                bag = sample_shape[-1]
                cols = [sk.sample_range(rng, off, off + sz, (n, bag))
                        for off, sz in zip(op._offsets, op.table_sizes)]
                idx = np.stack(cols, axis=1)
            elif len(sample_shape) == 2:          # stacked (T, bag)
                rows = op.num_entries
                cols = [sk.sample_range(rng, t * rows, (t + 1) * rows,
                                        (n, sample_shape[1]))
                        for t in range(sample_shape[0])]
                idx = np.stack(cols, axis=1)
            else:                                 # per-table (bag,)
                idx = sk.sample_range(rng, 0, op.num_entries,
                                      (n,) + sample_shape)
            idx = np.ascontiguousarray(idx, np.int32)
            with model._host_lock:
                warmed += self._cache.prewarm(
                    op, model.host_params[op.name], idx)
        if warmed:
            log_serve.info("pre-warmed %d embedding-cache entr%s from "
                           "%s", warmed, "y" if warmed == 1 else "ies",
                           path)

    def _host_gather(self):
        """The cached host-table gather (None = model default); with a
        shard set attached, the shard-tier gather instead."""
        if self._shard_set is not None:
            return self._shard_gather()
        if self._cache is None:
            return None
        model = self._model
        cache = self._cache

        def gather(host_idx):
            import jax
            # rows come OUT under the table lock (lookup returns fresh
            # arrays); the H2D device_put runs after release — same
            # FLX203 discipline as FFModel._host_emb_forward
            rows = {}
            with model._host_lock:
                for op in model._host_resident_list:
                    rows[op] = cache.lookup(op,
                                            model.host_params[op.name],
                                            host_idx[op.name])
            return {op.name: jax.device_put(
                        rows[op], model._out_sharding[op.outputs[0].guid])
                    for op in rows}

        return gather

    def attach_shard_set(self, shard_set) -> "InferenceEngine":
        """Wire this ranker to a (shared) EmbeddingShardSet. Must
        happen before ``start()`` — the warmed bucket executables bake
        the gather hook's call sites."""
        if self._started:
            raise RuntimeError("attach_shard_set before start()")
        self._shard_set = shard_set
        return self

    @property
    def shard_set(self):
        return self._shard_set

    def _shard_gather(self):
        """The sharded-tier gather: probe the per-ranker cache per
        sample and op, batch EVERY op's misses into ONE
        ``EmbeddingShardSet.fetch`` (one locked read per shard — the
        version-vector consistency unit), assemble the miss samples
        through the op's own ``host_lookup_rows`` (bit-identical to the
        local host path), and insert only NON-degraded samples back into
        the cache. The batch's version vector + per-row degraded marks
        are stashed for ``_dispatch`` to tag each request's
        Prediction."""
        model = self._model
        cache = self._cache
        shard_set = self._shard_set

        def gather(host_idx):
            import jax
            plan = {}
            per_op = {}
            n_rows = None
            for op in model._host_resident_list:
                idx = np.asarray(host_idx[op.name])
                n_rows = int(idx.shape[0])
                if cache is not None:
                    vals, miss = cache.probe(op, idx)
                else:
                    vals, miss = [None] * n_rows, list(range(n_rows))
                entry = {"idx": idx, "vals": vals, "miss": miss}
                if miss:
                    g3 = op.host_flat_indices(idx[np.asarray(miss)])
                    u, inv = np.unique(g3, return_inverse=True)
                    entry.update(g3=g3, u=u, inv=inv)
                    plan[op.name] = u
                per_op[op] = entry
            fetch = shard_set.fetch(plan) if plan else None
            row_degraded = np.zeros(n_rows or 0, bool)
            out_rows = {}
            for op, entry in per_op.items():
                vals, miss = entry["vals"], entry["miss"]
                if miss:
                    g3, u, inv = entry["g3"], entry["u"], entry["inv"]
                    rows = fetch.rows[op.name]
                    local = inv.reshape(g3.shape).astype(np.int64)
                    sub = np.asarray(op.host_lookup_rows(rows, local))
                    # which miss samples were assembled from default
                    # rows: flagged degraded, never cached
                    dm = fetch.default_mask[op.name][inv].reshape(
                        g3.shape)
                    sample_deg = dm.reshape(dm.shape[0], -1).any(axis=1)
                    if cache is not None:
                        # insert returns the CANONICAL values (the
                        # quantize-dequantize image under a quantized
                        # policy) so a later hit equals this miss
                        sub = cache.insert(op, entry["idx"], miss, sub,
                                           ok=~sample_deg)
                    for j, i in enumerate(miss):
                        vals[i] = np.ascontiguousarray(sub[j])
                    row_degraded[np.asarray(miss)[sample_deg]] = True
                out_rows[op.name] = np.stack(vals, axis=0)
            self._lookup_meta = {
                "versions": dict(fetch.versions) if fetch else
                            shard_set.version_vector(),
                "row_degraded": row_degraded,
            }
            from ..analysis import sanitizer as _san
            _san.note_jax_dispatch("shard-tier row device_put")
            return {op.name: jax.device_put(
                        out_rows[op.name],
                        model._out_sharding[op.outputs[0].guid])
                    for op in model._host_resident_list}

        return gather

    def _dispatch(self, reqs: List[_Request]) -> None:
        # expired requests fail with the structured report instead of
        # wasting a batch slot
        live: List[_Request] = []
        for r in reqs:
            if r.deadline is not None and r.deadline.expired():
                self._n_timeouts += 1
                r.future.set_exception(DeadlineExceeded(r.deadline.report(
                    worker="ff-serve-batcher",
                    waiting_for="a dynamic-batch dispatch slot",
                    detail=f"{r.rows} row(s), queue depth "
                           f"{len(self._q)}")))
            else:
                live.append(r)
        if not live:
            return
        # a crashed replica (FF_FAULT_REPLICA_DOWN) answers nothing: the
        # typed ReplicaDown fails the whole batch and the fleet router
        # re-routes every request to a surviving replica
        if faults.take_replica_down(self.replica_id):
            raise ReplicaDown(self.replica_id, "fault injection")
        faults.maybe_serve_delay(self.replica_id)
        batch = coalesce_batches([r.features for r in live])
        n = sum(r.rows for r in live)
        bucket = next(b for b in self._buckets if b >= n)
        # apply any parked hot reload FIRST, then dispatch with NO lock
        # held: the batcher thread is the only toucher of the model, so
        # swap-then-dispatch on this thread gives the same atomicity the
        # old dispatch-under-lock gave — a reload is entirely before or
        # entirely after this batch, never a mix — without ever holding
        # a lock across device work (the FF_SANITIZE=1 run asserts it)
        self._apply_pending_swap()
        version = self._applied_version
        self._lookup_meta = None
        with obstrace.span("serve/dispatch", rows=n, bucket=bucket):
            out = self._model.forward_bucket(
                batch, bucket=bucket, host_gather=self._host_gather())
            scores = np.asarray(out)      # device→host sync
        # shard-tier metadata the gather hook stashed for THIS batch:
        # the per-shard version vector and which rows degraded to
        # default embeddings (padding rows beyond n are ignored — a
        # dead shard owning row 0 must not flag real requests that
        # never looked anything up)
        meta = self._lookup_meta
        self._lookup_meta = None
        versions = meta["versions"] if meta else None
        rowdeg = meta["row_degraded"] if meta else None
        t_done = time.monotonic()
        off = 0
        n_degraded = 0
        for r in live:
            deg = bool(rowdeg is not None
                       and rowdeg[off:off + r.rows].any())
            n_degraded += int(deg)
            r.future.set_result(Prediction(
                scores[off:off + r.rows], version,
                1e3 * (t_done - r.t0), versions=versions,
                degraded=deg))
            off += r.rows
        with self._stats_lock:
            for r in live:
                self._lat_ms.append(1e3 * (t_done - r.t0))
            self._n_responses += len(live)
            self._n_degraded += n_degraded
            self._n_batches += 1
            self._rows_served += n
            self._rows_padded += bucket - n
            if versions is not None:
                self._last_versions = versions

    # --- hot reload (called by SnapshotWatcher) ------------------------
    def install_snapshot(self, state: Dict[str, Any], version: int,
                         source: str = "") -> None:
        """Swap in pre-loaded inference state (the output of
        ``checkpoint.load_params_for_swap``) between dispatches.

        The caller's slow work (file read, CRC, device_put) already
        happened outside any lock; this PARKS the new state under the
        swap lock and the batcher thread applies it between dispatches —
        the model is only ever touched by its dispatch thread, so
        in-flight batches finish on the old weights and the next batch
        sees the new ones (old-or-new, never a mix) WITHOUT any lock
        being held across device work (the FF_SANITIZE no-dispatch
        assertion pins that). The call returns once the swap has been
        applied — callers (canary/rollback/watcher) observe the model
        synchronously, exactly as when the swap ran under the dispatch
        lock. On a batcher-less engine (not started / draining / called
        from the batcher itself) the swap applies inline."""
        params = state.get("params")
        if params is not None:
            import jax
            old = jax.tree.structure(self._model.params)
            new = jax.tree.structure(params)
            if old != new:
                raise ValueError(
                    f"install_snapshot: params tree {new} does not match "
                    f"the compiled model's {old} — a snapshot from a "
                    f"differently-built model cannot hot-swap")
        applied = threading.Event()
        with self._swap_lock:
            # a FULL install replaces the whole state: everything queued
            # before it (older fulls, incremental deltas) is superseded —
            # release their waiters, the engine moves straight past them.
            # Parked quiesced CALLS are not state and survive in order (a
            # re-placement recompile must not be silently dropped by a
            # concurrent publish).
            superseded = self._pending
            self._pending = ([e for e in superseded if e[0] == "call"]
                             + [("full", dict(state), int(version),
                                 source, applied)])
            self._version = int(version)
            self._reloads += 1
            for entry in superseded:
                if entry[0] != "call":
                    entry[4].set()
        self._await_applied(applied)

    def install_delta(self, payload: Dict[str, Any], version: int,
                      source: str = "") -> None:
        """Park an INCREMENTAL delta (a ``load_delta_file`` payload whose
        device rows were already staged via ``stage_delta_rows`` — the
        H2D happened on the watcher thread, outside any lock). The
        batcher applies it between dispatches via ``FFModel.apply_delta``
        exactly like a full swap: in-flight batches finish on the old
        rows, the next dispatch sees the new ones, old-or-new never a
        mix. Deltas APPEND to the install queue (they are increments,
        not replacements — dropping one would corrupt the chain) and the
        call returns once applied."""
        applied = threading.Event()
        with self._swap_lock:
            self._pending.append(("delta", dict(payload), int(version),
                                  source, applied))
            self._version = int(version)
            self._reloads += 1
            self._delta_reloads += 1
        self._await_applied(applied)

    def run_quiesced(self, fn, label: str = ""):
        """Run ``fn()`` on the batcher thread between dispatches and
        return its result — the generic form of the parked-install
        contract: the in-flight batch finishes BEFORE ``fn`` runs, the
        next dispatch runs entirely AFTER it, and no lock is held across
        the call. The online re-placement path recompiles the model
        inside one of these, extending old-or-new-never-a-mix from
        weight swaps to placement swaps; a failed ``fn`` re-raises here
        (and shows up as a reload reject), leaving the batcher alive.
        Incoming requests queue for the duration — on a routed fleet the
        caller ejects the replica first so traffic drains to siblings
        instead of aging in this queue."""
        box: Dict[str, Any] = {}

        def call():
            try:
                box["result"] = fn()
            except BaseException as e:   # noqa: BLE001 — re-raised to
                box["error"] = e         # the run_quiesced caller below
                raise

        applied = threading.Event()
        with self._swap_lock:
            self._pending.append(
                ("call", call, self._version,
                 label or getattr(fn, "__name__", "call"), applied))
        self._await_applied(applied)
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _await_applied(self, applied: threading.Event) -> None:
        t = self._thread
        if (t is None or not t.is_alive()
                or t is threading.current_thread()):
            self._apply_pending_swap()
            return
        with self._cond:
            self._cond.notify_all()   # wake an idle batcher to apply now
        while not applied.wait(0.05):
            t = self._thread
            if t is None or not t.is_alive():   # batcher died mid-wait:
                self._apply_pending_swap()      # no dispatch racer left
                return

    def _apply_pending_swap(self) -> None:
        """Drain the parked install queue in order and swap/apply each
        into the model. Runs on the batcher thread between dispatches
        (or inline on a batcher-less engine); the model mutation happens
        OUTSIDE the swap lock — the lock only guards the queue
        hand-off."""
        with self._swap_lock:
            pending, self._pending = self._pending, []
        for kind, state, version, source, applied in pending:
            t_swap = time.perf_counter()
            try:
                if kind == "call":
                    # quiesced callable (run_quiesced): executes with the
                    # same atomicity as a weight swap — entirely between
                    # dispatches on this thread — and installs no
                    # version, so the bookkeeping below is skipped
                    state()
                    obstrace.complete("serve/quiesced", t_swap,
                                      label=source)
                    continue
                if kind == "full":
                    host_params = state.get("host_params")
                    if self._shard_set is not None:
                        # split tier: host tables belong to the shard
                        # set (idempotent per version — every ranker's
                        # watcher routes the same snapshot here); the
                        # stateless ranker swaps dense params only
                        if host_params is not None:
                            self._shard_set.install_full(host_params,
                                                         int(version))
                        host_params = None
                    self._model.swap_params(
                        params=state["params"],
                        host_params=host_params,
                        op_state=state.get("op_state"))
                    if self._cache is not None:
                        self._cache.invalidate()
                        # a full reload leaves the cache exactly as
                        # cold as a fresh start — re-warm from the
                        # histogram against the NEW tables (entries
                        # are post-swap lookups, so never-mixed holds;
                        # no-op unless --serve-cache-warm is set)
                        self._prewarm_cache()
                elif self._shard_set is not None:
                    # delta: host-table rows route to their owning
                    # shards (per-slice CRC chains, atomic per shard);
                    # the ranker applies the dense remainder
                    self._shard_set.apply_delta(state, int(version))
                    dense = dict(state)
                    dense["rows"] = {k: v for k, v in
                                     state.get("rows", {}).items()
                                     if not k.startswith("hostparams/")}
                    dense["full"] = {k: v for k, v in
                                     state.get("full", {}).items()
                                     if not k.startswith("hostparams/")}
                    self._model.apply_delta(dense)
                    self._invalidate_cache_rows(state)
                else:
                    self._model.apply_delta(state)
                    self._invalidate_cache_rows(state)
                self._applied_version = version
                self._applied_any = True
                obstrace.complete("serve/swap", t_swap, kind=kind,
                                  version=version)
                log_serve.info("hot-%s weights to version %d%s",
                               "reloaded" if kind == "full"
                               else "delta-patched", version,
                               f" from {source}" if source else "")
            except BaseException as e:   # noqa: BLE001 — a failed apply
                # must release the installer AND show up in stats, not
                # kill the batcher. Roll _version back to what is
                # actually applied so the watcher retries (with backoff)
                # or falls back instead of believing the reload landed.
                with self._swap_lock:
                    if not self._pending:
                        self._version = self._applied_version
                self.record_reload_reject(
                    f"staged {kind} (version {version}) failed to "
                    f"apply: {e}")
            finally:
                applied.set()

    def _invalidate_cache_rows(self, payload: Dict[str, Any]) -> None:
        """Delta reload: invalidate only the cached samples a dirtied
        host-table row feeds (a full-array host replacement still drops
        everything for safety)."""
        if self._cache is None:
            return
        if any(k.startswith("hostparams/")
               for k in (payload.get("full") or {})):
            self._cache.invalidate()
            return
        for key, (idx, _vals) in (payload.get("rows") or {}).items():
            if key.startswith("hostparams/"):
                self._cache.invalidate_rows(key.split("/")[1],
                                            np.asarray(idx))

    def state_snapshot(self) -> tuple:
        """(state dict, version) of what this engine is serving — the
        newest parked FULL install when one exists (it WILL be the next
        batch's weights), else the model's current arrays. The fleet's
        rollback capture and canary promotion read through this so they
        can never grab a half-superseded view. A parked DELTA cannot be
        represented without applying it; installs are synchronous, so
        the window where one is pending is the installer's own call —
        the model's current arrays are the honest answer then."""
        with self._swap_lock:
            pending = self._pending
            if pending and pending[-1][0] == "full":
                _, state, version, _, _ = pending[-1]
                m = self._model
                return ({"params": state.get("params", m.params),
                         "host_params": (state.get("host_params")
                                         if state.get("host_params")
                                         is not None else m.host_params),
                         "op_state": (state.get("op_state")
                                      if state.get("op_state") is not None
                                      else m.op_state)}, version)
        m = self._model
        return ({"params": m.params, "host_params": m.host_params,
                 "op_state": m.op_state}, self._applied_version)

    def record_reload_reject(self, reason: str) -> None:
        self._reload_rejects += 1
        self._last_reject = reason
        log_serve.warning("snapshot reload rejected: %s — continuing to "
                          "serve version %d", reason, self._version)

    @property
    def version(self) -> int:
        return self._version

    @property
    def has_applied_snapshot(self) -> bool:
        """True once any install (full or delta) has been applied —
        before that, ``version`` describes the model's constructor-time
        state, not a published snapshot."""
        return self._applied_any

    @property
    def version_floor(self) -> int:
        """The oldest version anywhere in this engine's serving path:
        its own applied version AND (split tier) the oldest live shard.
        The snapshot watcher keys its catch-up on this — a replacement
        shard that booted slightly stale keeps the delta chain
        replaying (idempotent per shard) until the whole tier is at the
        tip, even though the ranker itself already is."""
        if self._shard_set is None:
            return self._version
        floor = self._shard_set.min_version()
        return self._version if floor is None \
            else min(self._version, floor)

    @property
    def model(self):
        return self._model

    # --- fleet hooks (called by serve.fleet / serve.router) ------------
    @property
    def queue_depth(self) -> int:
        """Current queued request count — the router's load-balancing
        signal, cheap enough to read per pick (stats() sorts the whole
        latency window)."""
        return len(self._q)

    def alive(self) -> bool:
        """True while the batcher thread is running and the engine is
        neither unstarted nor draining."""
        t = self._thread
        return bool(self._started and not self._closing
                    and t is not None and t.is_alive())

    def heartbeat_age(self) -> float:
        """Seconds since the batcher last went around its loop. Grows
        past the dispatch latency only when the batcher is wedged —
        the router's heartbeat health check keys off this."""
        return self._heartbeat.age()

    @property
    def heartbeat(self) -> Heartbeat:
        return self._heartbeat

    def drain_pending(self, exc: Optional[BaseException] = None) -> int:
        """Fail every still-queued (not yet dispatched) request with
        ``exc`` (default: this replica's ReplicaDown) and empty the
        queue. The router calls this when its circuit breaker ejects the
        replica: the rescued futures' retry callbacks re-route their
        requests to surviving replicas instead of leaving them to rot
        behind a dead batcher. Returns how many requests were failed."""
        if exc is None:
            exc = ReplicaDown(self.replica_id, "queue drained on ejection")
        with self._cond:
            taken = list(self._q)
            self._q.clear()
            self._q_rows = 0
        n = 0
        for r in taken:
            if not r.future.done():
                r.future.set_exception(exc)
                n += 1
        return n

    def healthz(self) -> Dict[str, Any]:
        """Readiness snapshot for a /healthz endpoint. ``ok`` is False
        when sending this replica traffic is pointless: the engine is
        draining (close() begun / never started), its batcher thread
        died, or the bounded queue is saturated (submits are being
        rejected with Overloaded right now).

        ``degraded`` is True while the shard tier has a shard out of the
        routable set: answers are still served (cache hits + default
        rows, flagged per response) — DEGRADED IS NOT DOWN. A load
        balancer must keep routing here (HTTP 200 with
        ``"degraded": true``), reserving 503 for ``ok: false``."""
        depth = len(self._q)
        saturated = depth >= self.config.queue_capacity
        draining = self._closing or not self._started
        t = self._thread
        batcher_alive = bool(t is not None and t.is_alive())
        dead = self._started and not self._closing and not batcher_alive
        out = {
            "ok": not (saturated or draining or dead),
            "version": self._version,
            "draining": draining,
            "saturated": saturated,
            "batcher_alive": batcher_alive,
            "queue_depth": depth,
            "queue_capacity": self.config.queue_capacity,
        }
        if self._shard_set is not None:
            out["degraded"] = self._shard_set.degraded_now()
            out["shard_states"] = {r.slot: r.state
                                   for r in self._shard_set.shards}
        return out

    # --- observability -------------------------------------------------
    def _obs_collect(self):
        """Registry collector (pull-time): the hot stats() counters as
        scrapeable samples. The stats dict stays the source of truth —
        the scrape reads through it, so the two can never disagree."""
        lab = {"replica": ("" if self.replica_id is None
                           else str(self.replica_id))}
        yield "ff_serve_requests_total", lab, self._n_requests
        yield "ff_serve_responses_total", lab, self._n_responses
        yield "ff_serve_overloaded_total", lab, self._n_overloaded
        yield "ff_serve_timeouts_total", lab, self._n_timeouts
        yield "ff_serve_batches_total", lab, self._n_batches
        yield "ff_serve_queue_depth", lab, len(self._q)
        yield "ff_serve_reloads_total", lab, self._reloads
        yield "ff_serve_delta_reloads_total", lab, self._delta_reloads
        yield "ff_serve_reload_rejects_total", lab, self._reload_rejects
        yield "ff_serve_version", lab, self._version
        if self._shard_set is not None:
            yield "ff_serve_degraded_responses_total", lab, \
                self._n_degraded
        if self._cache is not None:
            cs = self._cache.stats()
            yield "ff_serve_cache_hits_total", lab, cs.get("hits", 0)
            yield "ff_serve_cache_misses_total", lab, cs.get("misses", 0)

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            lat = sorted(self._lat_ms)
            flushes = dict(self._flushes)

        def pct(p):
            return percentile(lat, p)

        dispatched = self._rows_served + self._rows_padded
        out = {
            "requests": self._n_requests,
            "responses": self._n_responses,
            "overloaded": self._n_overloaded,
            "timeouts": self._n_timeouts,
            "queue_depth": len(self._q),
            "batches": self._n_batches,
            "batch_fill": (self._rows_served / dispatched
                           if dispatched else 0.0),
            "p50_ms": pct(50),
            "p99_ms": pct(99),
            "version": self._version,
            "reloads": self._reloads,
            "delta_reloads": self._delta_reloads,
            "reload_rejects": self._reload_rejects,
            "last_reload_reject": self._last_reject,
            "buckets": list(self._buckets),
            "warmup_s": round(self._warmup_s, 4),
            "flushes": flushes,
            "continuous": self.config.continuous,
            "eval_exec_cache": self._model.eval_exec_cache_stats(),
        }
        if self.replica_id is not None:
            out["replica_id"] = self.replica_id
        if self._shard_set is not None:
            out["degraded_responses"] = self._n_degraded
            out["shard_versions"] = dict(self._last_versions)
            out["shard_set"] = self._shard_set.stats()
        cc = getattr(self._model, "_compile_cache", None)
        if cc is not None:
            out["compile_cache"] = cc.stats()
        if self._cache is not None:
            out["embedding_cache"] = self._cache.stats()
        if self._watcher is not None:
            out["watcher"] = self._watcher.stats()
        return out
