"""Fleet router: SLO-aware request routing over N engine replicas.

The Clipper insight is that a routing layer in front of model replicas
buys fault isolation the replicas cannot provide themselves; this module
is that layer for :class:`~.fleet.Fleet`:

- **Load balancing** — every request goes to the healthy replica with
  the shallowest queue (``InferenceEngine.queue_depth``), round-robin on
  ties, so one slow replica backs up its own queue and organically
  stops attracting traffic.
- **Bounded retry with backoff** — ``Overloaded`` (backpressure),
  ``DeadlineExceeded``, ``ReplicaDown`` and dispatch errors re-route to
  a different replica after an exponential backoff, up to ``retries``
  times; only malformed requests (``ValueError``) fail without retry.
  A request fails ONLY when every attempt is exhausted — the chaos bar
  is zero non-retried-to-success failures while a replica dies mid-load.
- **Circuit breaker** — ``eject_after`` consecutive dispatch errors (or
  a dead batcher thread, or a heartbeat older than
  ``heartbeat_deadline_s``) ejects the replica: no more traffic, queued
  futures drained onto survivors. After ``cooldown_s`` a real probe
  request runs end-to-end under ``probe_deadline_s``; success re-admits.
- **Tail-latency hedging** — optionally (``hedge_ms``) a request still
  unresolved after the hedge delay is duplicated to a second replica;
  first result wins. Classic p99 insurance against one slow dispatch.
- **Canary rollout** — ``start_canary(snapshot)`` installs a candidate
  snapshot on part of the fleet and routes ``canary_fraction`` of
  traffic there (deterministic credit pacing, not sampling). The health
  thread compares the canary cohort against the stable cohort and
  AUTO-ROLLS-BACK — reinstalling the captured pre-deploy params, which
  in-flight requests never observe mid-swap — when canary p99 exceeds
  ``canary_p99_ratio`` × stable p99 or the cohorts' mean scores diverge
  past ``canary_score_tol``. A bad deploy costs a log line, never an
  error.
- **Shadow traffic** — ``start_shadow(snapshot)`` installs a candidate
  on a replica that receives only DUPLICATED requests: clients are
  answered by the stable cohort, the shadow's scores are compared
  offline (``shadow_report()``), and shadow failures are swallowed.

Everything observable lands in ``stats()``: per-replica circuit-breaker
state, fleet-aggregated engine stats, client-observed p50/p99 (which
include retry/hedge time — the number a user actually feels), and the
canary/shadow controllers' verdicts.
"""

from __future__ import annotations

import heapq
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from ..analysis.sanitizer import make_lock
from ..obs import metrics as obsm
from ..obs import trace as obstrace
from ..utils import faults
from ..utils.checkpoint import load_params_for_swap
from ..utils.logging import get_logger
from .engine import Overloaded, Prediction, percentile
from .fleet import HEALTHY, Fleet, Replica

log_router = get_logger("serve.router")


class FleetUnavailable(RuntimeError):
    """No healthy replica could serve the request within the retry
    budget — the fleet-level analogue of ``Overloaded``. Callers shed
    load or retry later; seeing this with zero healthy replicas means
    the whole fleet is down or ejected."""


@dataclass
class RouterConfig:
    """Routing/health/deployment knobs; ``from_config`` lifts the
    ``--serve-*`` flags."""

    retries: int = 2                   # re-dispatches after the first try
    backoff_ms: float = 5.0            # exponential retry backoff base
    hedge_ms: float = 0.0              # duplicate-after delay; 0 = off
    eject_after: int = 3               # consecutive errors -> ejection
    cooldown_s: float = 1.0            # ejection -> first probe
    probe_deadline_s: float = 5.0      # end-to-end probe budget
    heartbeat_deadline_s: float = 0.0  # stale-batcher ejection; 0 = off
    health_interval_s: float = 0.25    # health/canary evaluation period
    canary_fraction: float = 0.1       # share of traffic to the canary
    canary_p99_ratio: float = 2.0      # rollback past ratio x stable p99
    canary_score_tol: float = 0.5      # rollback past |mean score| gap
    canary_min_samples: int = 32       # per-cohort floor before judging
    shadow_sample: float = 1.0         # share of traffic duplicated
    window: int = 2048                 # cohort/client latency windows

    @staticmethod
    def from_config(cfg) -> "RouterConfig":
        return RouterConfig(
            retries=int(getattr(cfg, "serve_retries", 2)),
            hedge_ms=float(getattr(cfg, "serve_hedge_ms", 0.0)),
            canary_fraction=float(getattr(cfg, "serve_canary_fraction",
                                          0.1)))


class _Timer(threading.Thread):
    """Monotonic-deadline action queue for retries/hedges: callbacks
    from engine batcher threads must never sleep (that would stall the
    batcher), so delayed work is handed here instead."""

    def __init__(self, name: str):
        super().__init__(daemon=True, name=name)
        self._heap: list = []
        self._cond = threading.Condition()
        self._seq = 0
        self._stopped = False

    def call_later(self, delay_s: float, fn) -> None:
        with self._cond:
            if self._stopped:           # late scheduling after close():
                return                  # the action runs in close()'s
            heapq.heappush(self._heap,  # drain or not at all
                           (time.monotonic() + max(delay_s, 0.0),
                            self._seq, fn))
            self._seq += 1
            self._cond.notify()

    def run(self) -> None:
        while True:
            with self._cond:
                while not self._stopped:
                    if not self._heap:
                        self._cond.wait()
                        continue
                    left = self._heap[0][0] - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(left)
                if self._stopped:
                    return
                _, _, fn = heapq.heappop(self._heap)
            try:
                fn()
            except Exception:   # noqa: BLE001 — a failed retry action
                log_router.exception("router timer action failed")

    def close(self) -> None:
        """Stop the loop, then run whatever was still pending NOW: a
        scheduled retry holds a client future that would otherwise hang
        forever — running it against a closing fleet fails it fast."""
        with self._cond:
            self._stopped = True
            pending = [fn for _, _, fn in self._heap]
            self._heap.clear()
            self._cond.notify_all()
        self.join(5.0)
        for fn in pending:
            try:
                fn()
            except Exception:   # noqa: BLE001
                log_router.exception("router timer drain action failed")


class _Cohort:
    """Latency window + running score mean for one deployment cohort —
    plus, under the sharded serving tier, the newest per-shard VERSION
    VECTOR the cohort's responses read. Canary judgement compares
    vectors, not scalar versions: with tables split over shards there
    is no single "the version" anymore, and two cohorts mid-publish can
    legitimately read different shard versions for a tick — comparing
    their score means then would blame the deploy for a skew the
    publish caused."""

    def __init__(self, maxlen: int, name: str = ""):
        self._lock = make_lock("_Cohort._lock")
        self.maxlen = maxlen
        self.name = name
        # bounded obs reservoir (scrapeable as
        # ff_router_cohort_latency_ms{cohort=...} when --obs on)
        self.lat_ms = obsm.latency_reservoir(
            "ff_router_cohort_latency_ms",
            "client-observed latency per deployment cohort",
            maxlen=maxlen, cohort=name)
        self.score_sum = 0.0
        self.score_n = 0
        self.versions: Optional[Dict[int, int]] = None
        self.degraded = 0

    def reset(self) -> None:
        with self._lock:
            self.lat_ms.clear()
            self.score_sum = 0.0
            self.score_n = 0
            self.versions = None
            self.degraded = 0

    def add(self, ms: float, scores: np.ndarray,
            versions: Optional[Dict[int, int]] = None,
            degraded: bool = False) -> None:
        with self._lock:
            self.lat_ms.append(ms)
            self.score_sum += float(np.sum(scores))
            self.score_n += int(scores.size)
            if versions is not None:
                self.versions = versions
            self.degraded += int(degraded)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            lat = sorted(self.lat_ms)
            s, n = self.score_sum, self.score_n
            vv = dict(self.versions) if self.versions is not None \
                else None
            degraded = self.degraded
        return {
            "n": len(lat),
            "p50_ms": percentile(lat, 50),
            "p99_ms": percentile(lat, 99),
            "score_mean": (s / n) if n else None,
            "score_n": n,
            "versions": vv,
            "degraded": degraded,
        }


class _RouterReq:
    """One client request's routing state across attempts."""

    __slots__ = ("features", "future", "t0", "lock", "cohort", "tried",
                 "retry_no", "hedged", "primary_scores", "shadow_scores")

    def __init__(self, features):
        self.features = features
        self.future: Future = Future()
        self.t0 = time.monotonic()
        self.lock = make_lock("_RouterReq.lock")
        self.cohort: Optional[str] = None
        self.tried: set = set()
        self.retry_no = 0
        self.hedged = False
        self.primary_scores: Optional[np.ndarray] = None
        self.shadow_scores: Optional[np.ndarray] = None


class FleetRouter:
    """Spread requests over a :class:`Fleet`, keep serving through
    replica failures, and run canary/shadow deployments. See the module
    docstring for the full contract."""

    def __init__(self, fleet, config: Optional[RouterConfig] = None,
                 probe_features: Optional[Dict[str, np.ndarray]] = None):
        if isinstance(fleet, Fleet):
            self.fleet = fleet
        else:
            self.fleet = Fleet(list(fleet))
        self.config = config or RouterConfig()
        if self.config.retries < 0:
            raise ValueError("router retries must be >= 0")
        self._probe_features = probe_features
        self._started = False
        self._closed = False
        self._timer = _Timer("ff-router-timer")
        self._health_stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self._rr_counter = 0
        # metrics (one lock: counters + windows; callbacks are cheap)
        self._m_lock = make_lock("FleetRouter._m_lock")
        # client-observed latency (includes retries/hedges — the number
        # an SLO is written against); the obs reservoir doubles as the
        # ff_router_client_latency_ms scrape when --obs on
        self._lat_ms = obsm.latency_reservoir(
            "ff_router_client_latency_ms",
            "client-observed latency incl. retries and hedges",
            maxlen=self.config.window)
        self._n_requests = 0
        self._n_responses = 0
        self._n_failed = 0
        self._n_retries = 0
        self._n_hedges = 0
        self._n_hedge_wins = 0
        self._cohorts = {"stable": _Cohort(self.config.window, "stable"),
                         "canary": _Cohort(self.config.window, "canary")}
        # deployment state (its own lock: install/rollback swap params
        # replica-by-replica and must not interleave). no_dispatch: the
        # deploy verbs stage snapshot loads + device_puts OUTSIDE it and
        # only flip cohorts/install references under it
        self._deploy_lock = make_lock("FleetRouter._deploy_lock",
                                      no_dispatch=True)
        self._canary_active = False
        self._canary_fraction = self.config.canary_fraction
        self._canary_credit = 0.0
        self._rollbacks = 0
        self._promotions = 0
        self._last_rollback_reason = ""
        self._vv_skew_skips = 0
        self._shadow_rid: Optional[int] = None
        self._shadow_credit = 0.0
        self._shadow_n = 0
        self._shadow_sum_abs = 0.0
        self._shadow_max_abs = 0.0
        self._shadow_errors = 0

    # --- lifecycle -----------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._started:
            return self
        self._started = True
        self.fleet.start()
        obsm.register_collector(self._obs_collect)
        self._timer.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="ff-router-health")
        self._health_thread.start()
        return self

    def close(self, deadline_s: float = 10.0) -> None:
        if self._closed:
            return
        self._closed = True
        obsm.unregister_collector(self._obs_collect)
        self._health_stop.set()
        t = self._health_thread
        if t is not None:
            t.join(5.0)
        self.fleet.close(deadline_s)
        self._timer.close()

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # --- request path --------------------------------------------------
    def submit(self, features: Dict[str, np.ndarray]) -> Future:
        """Route one request; returns a Future resolving to a
        :class:`~.engine.Prediction`. The future only fails once the
        retry budget is spent (or the request is malformed)."""
        if self._closed:
            raise RuntimeError("router is closed")
        if not self._started:
            raise RuntimeError("router not started (call start())")
        rr = _RouterReq(features)
        with self._m_lock:
            self._n_requests += 1
        if self._probe_features is None:
            self._probe_features = features
        self._dispatch(rr)
        return rr.future

    def predict(self, features: Dict[str, np.ndarray],
                timeout: Optional[float] = None) -> Prediction:
        """Synchronous submit+wait."""
        return self.submit(features).result(timeout)

    def _choose_cohort(self) -> str:
        """Deterministic credit pacing: exactly `fraction` of fresh
        requests go canary (no RNG — tests and traffic splits are
        reproducible)."""
        if not self._canary_active:
            return "stable"
        with self._m_lock:
            self._canary_credit += self._canary_fraction
            if self._canary_credit >= 1.0:
                self._canary_credit -= 1.0
                return "canary"
        return "stable"

    def _pick(self, cohort: str, exclude: set) -> Optional[Replica]:
        """Shallowest-queue healthy replica of the cohort; falls back
        to the other cohort (availability beats cohort purity), then to
        already-tried replicas (retrying somewhere beats failing)."""
        for relax_exclude in (False, True):
            for want in (cohort, "canary" if cohort == "stable"
                         else "stable"):
                cands = [r for r in self.fleet.replicas
                         if r.state == HEALTHY and r.cohort == want
                         and (relax_exclude or r.rid not in exclude)]
                if cands:
                    self._rr_counter += 1
                    rr = self._rr_counter
                    return min(cands, key=lambda r: (
                        r.queue_depth, (r.rid + rr) % (len(cands) + 1)))
        return None

    def _dispatch(self, rr: _RouterReq, hedge: bool = False) -> None:
        if rr.future.done():
            return
        if self._closed:
            self._fail(rr, RuntimeError("router is closed"))
            return
        if rr.cohort is None:
            rr.cohort = self._choose_cohort()
        rep = self._pick(rr.cohort, rr.tried)
        if rep is None:
            self._attempt_failed(rr, None, FleetUnavailable(
                f"no healthy replica (states "
                f"{ {r.rid: r.state for r in self.fleet.replicas} })"))
            return
        try:
            fut = rep.engine.submit(rr.features)
        except ValueError as e:          # malformed request — no retry
            self._fail(rr, e)            # can fix a bad feature dict
            return
        except Exception as e:           # noqa: BLE001 — Overloaded,
            # closed engine, crashed submit: all retryable elsewhere
            self._attempt_failed(rr, rep, e)
            return
        rr.tried.add(rep.rid)
        if (not hedge and self.config.hedge_ms > 0
                and len(self.fleet) > 1):
            self._timer.call_later(self.config.hedge_ms / 1e3,
                                   lambda: self._hedge(rr))
        if not hedge:
            self._maybe_shadow(rr)
        fut.add_done_callback(
            lambda f: self._on_done(rr, rep, f, hedge))

    def _on_done(self, rr: _RouterReq, rep: Replica, fut: Future,
                 hedge: bool) -> None:
        exc = fut.exception()
        if exc is None:
            rep.record_success()
            self._complete(rr, fut.result(), rep, hedge)
        else:
            self._attempt_failed(rr, rep, exc)

    def _attempt_failed(self, rr: _RouterReq, rep: Optional[Replica],
                        exc: BaseException) -> None:
        # circuit breaker first — the replica's health is tracked even
        # when this particular request already succeeded via a hedge.
        # Overloaded is backpressure, not breakage: it steers the retry
        # elsewhere but never trips the breaker.
        if rep is not None and not isinstance(exc, Overloaded):
            if rep.record_error(exc, self.config.eject_after):
                rep.eject(f"{self.config.eject_after} consecutive "
                          f"errors, last: {exc}")
        if rr.future.done():
            return
        if isinstance(exc, ValueError):
            self._fail(rr, exc)          # malformed: retry can't help
            return
        if rr.retry_no < self.config.retries:
            delay = (self.config.backoff_ms / 1e3) * (2 ** rr.retry_no)
            rr.retry_no += 1
            with self._m_lock:
                self._n_retries += 1
            self._timer.call_later(delay, lambda: self._dispatch(rr))
        else:
            self._fail(rr, exc)

    def _fail(self, rr: _RouterReq, exc: BaseException) -> None:
        with rr.lock:
            if rr.future.done():
                return
            rr.future.set_exception(exc)
        with self._m_lock:
            self._n_failed += 1

    def _complete(self, rr: _RouterReq, pred: Prediction, rep: Replica,
                  hedge: bool) -> None:
        with rr.lock:
            if rr.future.done():
                return                   # the other attempt won
            rr.future.set_result(pred)
            rr.primary_scores = pred.scores
            shadow_scores = rr.shadow_scores
        ms = 1e3 * (time.monotonic() - rr.t0)
        with self._m_lock:
            self._n_responses += 1
            self._lat_ms.append(ms)
            if hedge:
                self._n_hedge_wins += 1
        # cohort metrics feed the canary judgement: client-observed
        # latency (what an SLO means) + the response score mass + the
        # shard version vector the response read (vector-mismatch gates
        # the score comparison under the sharded tier)
        cohort = rep.cohort if rep.cohort in self._cohorts else "stable"
        self._cohorts[cohort].add(
            ms, np.asarray(pred.scores),
            versions=getattr(pred, "versions", None),
            degraded=bool(getattr(pred, "degraded", False)))
        if shadow_scores is not None:
            self._shadow_compare(pred.scores, shadow_scores)

    def _hedge(self, rr: _RouterReq) -> None:
        with rr.lock:
            if rr.future.done() or rr.hedged:
                return
            rr.hedged = True
        with self._m_lock:
            self._n_hedges += 1
        self._dispatch(rr, hedge=True)

    # --- shadow traffic ------------------------------------------------
    def _maybe_shadow(self, rr: _RouterReq) -> None:
        rid = self._shadow_rid
        if rid is None:
            return
        with self._m_lock:
            self._shadow_credit += self.config.shadow_sample
            if self._shadow_credit < 1.0:
                return
            self._shadow_credit -= 1.0
        try:
            rep = self.fleet.get(rid)
            if rep.state != HEALTHY or rep.cohort != "shadow":
                return
            fut = rep.engine.submit(rr.features)
        except Exception:   # noqa: BLE001 — shadow failures are
            # interesting offline, invisible to the client
            with self._m_lock:
                self._shadow_errors += 1
            return
        fut.add_done_callback(lambda f: self._on_shadow_done(rr, f))

    def _on_shadow_done(self, rr: _RouterReq, fut: Future) -> None:
        exc = fut.exception()
        if exc is not None:
            with self._m_lock:
                self._shadow_errors += 1
            return
        scores = np.asarray(fut.result().scores)
        with rr.lock:
            rr.shadow_scores = scores
            primary = rr.primary_scores
        if primary is not None:   # else _complete compares when it runs
            self._shadow_compare(primary, scores)

    def _shadow_compare(self, primary, shadow) -> None:
        diff = np.abs(np.asarray(primary, np.float64)
                      - np.asarray(shadow, np.float64))
        with self._m_lock:
            self._shadow_n += int(diff.size)
            self._shadow_sum_abs += float(diff.sum())
            self._shadow_max_abs = max(self._shadow_max_abs,
                                       float(diff.max()))

    def shadow_report(self) -> Dict[str, Any]:
        with self._m_lock:
            n = self._shadow_n
            return {
                "replica": self._shadow_rid,
                "n": n,
                "mean_abs_diff": (self._shadow_sum_abs / n) if n else None,
                "max_abs_diff": self._shadow_max_abs if n else None,
                "errors": self._shadow_errors,
            }

    # --- deployments ---------------------------------------------------
    def _load_state(self, rep: Replica, snapshot,
                    version: Optional[int]):
        """Resolve a snapshot argument (path or preloaded state dict)
        into (state, version) for one replica's model. Path loads run
        the poison hook — a canary deploy IS a reload."""
        if getattr(rep.engine, "remote", False):
            raise RuntimeError(
                f"replica {rep.rid} runs in another process; "
                f"canary/shadow deploys mutate replica state in-place "
                f"and are inproc-only — publish the candidate through "
                f"that process's own SnapshotWatcher instead")
        if isinstance(snapshot, str):
            state = load_params_for_swap(
                rep.engine.model, snapshot,
                elastic=rep.engine.config.reshard)
            state = faults.maybe_poison_reload(state)
            return state, int(state["step"] if version is None
                              else version)
        if version is None:
            version = int(snapshot.get("step", rep.engine.version + 1))
        return snapshot, version

    def start_canary(self, snapshot, replica_ids: Optional[List[int]]
                     = None, fraction: Optional[float] = None,
                     version: Optional[int] = None) -> List[int]:
        """Install a candidate snapshot (path or
        ``load_params_for_swap`` state) on part of the fleet and start
        routing ``fraction`` of traffic there. Default cohort: the
        highest-rid healthy replica — one replica's blast radius.
        Returns the canary replica ids."""
        with self._deploy_lock:
            if self._canary_active:
                raise RuntimeError("a canary is already active — "
                                   "promote or roll back first")
            if replica_ids is None:
                healthy = self.fleet.healthy("stable")
                if len(healthy) < 2:
                    raise RuntimeError(
                        "canary needs >= 2 healthy replicas (one must "
                        "keep serving stable traffic)")
                reps = [healthy[-1]]
            else:
                reps = [self.fleet.get(r) for r in replica_ids]
        # slow part (snapshot read + CRC + device_put) OUTSIDE the
        # deploy lock: a multi-GB canary load must not block a
        # concurrent rollback/judgement (flexcheck FLX203)
        staged = [(rep, self._load_state(rep, snapshot, version))
                  for rep in reps]
        with self._deploy_lock:
            if self._canary_active:
                raise RuntimeError("a canary is already active — "
                                   "promote or roll back first")
            for rep, (state, ver) in staged:
                rep.capture_rollback_state()
                rep.engine.install_snapshot(state, ver, source="canary")
                rep.cohort = "canary"
            self._canary_fraction = (self.config.canary_fraction
                                     if fraction is None else
                                     float(fraction))
            self._cohorts["stable"].reset()
            self._cohorts["canary"].reset()
            self._canary_active = True
            ids = [r.rid for r in reps]
            log_router.info(
                "canary started on replica(s) %s at %.0f%% of traffic",
                ids, 100 * self._canary_fraction)
            return ids

    def rollback_canary(self, reason: str = "manual") -> None:
        """Reinstall the captured pre-canary state on every canary
        replica and return it to the stable cohort. The swap is atomic
        per replica (between dispatches): in-flight requests finish on
        the canary weights with their version tag, later ones see
        stable — zero client-visible errors."""
        with self._deploy_lock:
            if not self._canary_active:
                return
            for rep in self.fleet.replicas:
                if rep.cohort == "canary":
                    rep.restore_rollback_state()
                    rep.cohort = "stable"
            self._canary_active = False
            self._rollbacks += 1
            self._last_rollback_reason = reason
            obstrace.instant("router/canary-rollback", cat="deploy",
                             reason=reason[:200])
            log_router.warning("canary rolled back: %s", reason)

    def promote_canary(self) -> None:
        """The candidate won: install its state on the REST of the
        fleet so every replica serves the new version, and retire the
        rollback capture."""
        import jax

        with self._deploy_lock:
            if not self._canary_active:
                raise RuntimeError("no active canary to promote")
            canaries = [r for r in self.fleet.replicas
                        if r.cohort == "canary"]
            targets = [r for r in self.fleet.replicas
                       if r.cohort != "canary"]
            # pending-swap-aware read of the winner's state
            src_state, src_version = canaries[0].engine.state_snapshot()
        # the heavy lifting — gather ONCE to host, then device_put per
        # target's compiled shardings (each replica owns its own mesh,
        # so the canary's device arrays cannot be aliased) — runs
        # OUTSIDE the deploy lock: promoting a large model must not
        # freeze rollback/judgement for the transfer (flexcheck FLX203)
        host = {
            "params": jax.tree.map(np.asarray, src_state["params"]),
            "host_params": src_state["host_params"],
            "op_state": jax.tree.map(np.asarray, src_state["op_state"]),
        }
        states = {}
        for rep in targets:
            m = rep.engine.model
            states[rep.rid] = {
                "params": {
                    op: {n: jax.device_put(
                        v, m._param_sharding.get(op, {}).get(n))
                        for n, v in pd.items()}
                    for op, pd in host["params"].items()},
                "host_params": host["host_params"],
                "op_state": jax.tree.map(jax.device_put,
                                         host["op_state"]),
            }
        with self._deploy_lock:
            if not self._canary_active:
                raise RuntimeError(
                    "canary rolled back while its promotion staged — "
                    "the fleet keeps the stable version")
            for rep in canaries:
                rep.rollback_state = None
                rep.cohort = "stable"
            for rep in targets:
                rep.engine.install_snapshot(states[rep.rid], src_version,
                                            source="promote")
            self._canary_active = False
            self._promotions += 1
            log_router.info("canary promoted: fleet now serves "
                            "version %d", src_version)

    def start_shadow(self, snapshot, replica_id: Optional[int] = None,
                     version: Optional[int] = None) -> int:
        """Install a candidate on one replica as SHADOW: it leaves the
        routable set, receives only duplicated traffic, and its scores
        are compared against the primary responses offline."""
        with self._deploy_lock:
            if self._shadow_rid is not None:
                raise RuntimeError("a shadow is already active")
            if replica_id is None:
                healthy = self.fleet.healthy("stable")
                if len(healthy) < 2:
                    raise RuntimeError(
                        "shadow needs >= 2 healthy replicas (one must "
                        "keep serving client traffic)")
                rep = healthy[-1]
            else:
                rep = self.fleet.get(replica_id)
        # snapshot load outside the lock (same discipline as canary)
        state, ver = self._load_state(rep, snapshot, version)
        with self._deploy_lock:
            if self._shadow_rid is not None:
                raise RuntimeError("a shadow is already active")
            rep.capture_rollback_state()
            rep.engine.install_snapshot(state, ver, source="shadow")
            rep.cohort = "shadow"
            with self._m_lock:
                self._shadow_n = 0
                self._shadow_sum_abs = 0.0
                self._shadow_max_abs = 0.0
                self._shadow_errors = 0
            self._shadow_rid = rep.rid
            log_router.info("shadow started on replica %d", rep.rid)
            return rep.rid

    def stop_shadow(self, restore: bool = True) -> Dict[str, Any]:
        """Return the shadow replica to the stable cohort (reinstalling
        its pre-shadow state unless ``restore=False``) and hand back the
        final comparison report."""
        with self._deploy_lock:
            rid = self._shadow_rid
            if rid is None:
                return self.shadow_report()
            rep = self.fleet.get(rid)
            report = self.shadow_report()
            if restore:
                rep.restore_rollback_state()
            else:
                rep.rollback_state = None
            rep.cohort = "stable"
            self._shadow_rid = None
            log_router.info("shadow stopped on replica %d: %s", rid,
                            report)
            return report

    # --- health + canary judgement ------------------------------------
    def _health_loop(self) -> None:
        while not self._health_stop.wait(self.config.health_interval_s):
            try:
                self._health_check()
            except Exception:   # noqa: BLE001 — the health thread must
                log_router.exception("fleet health check failed")

    def _health_check(self) -> None:
        cfg = self.config
        for rep in self.fleet.replicas:
            if rep.state == HEALTHY:
                if not rep.engine.alive():
                    rep.eject("batcher thread dead")
                elif (cfg.heartbeat_deadline_s > 0
                      and rep.engine.heartbeat_age()
                      > cfg.heartbeat_deadline_s):
                    rep.eject("stale heartbeat: " + str(
                        rep.engine.heartbeat.report(
                            cfg.heartbeat_deadline_s,
                            "a batcher loop iteration",
                            detail=f"queue depth {rep.queue_depth}")))
            elif rep.due_for_probe(cfg.cooldown_s):
                self._probe(rep)
        self._judge_canary()

    def _probe(self, rep: Replica) -> None:
        """End-to-end liveness probe: a real request through the real
        dispatch path under the probe deadline. Success re-admits."""
        probe = self._probe_features
        if probe is None:
            return   # nothing ever submitted — no template to probe with
        rep.begin_probe()
        try:
            pred = rep.engine.submit(probe).result(
                self.config.probe_deadline_s)
            assert pred.scores is not None
        except Exception as e:   # noqa: BLE001 — stay ejected
            rep.probe_failed(f"{type(e).__name__}: {e}")
            return
        rep.readmit()

    def _judge_canary(self) -> None:
        if not self._canary_active:
            return
        cfg = self.config
        c = self._cohorts["canary"].snapshot()
        s = self._cohorts["stable"].snapshot()
        if (c["n"] < cfg.canary_min_samples
                or s["n"] < cfg.canary_min_samples):
            return
        if (c["p99_ms"] is not None and s["p99_ms"] is not None
                and s["p99_ms"] > 0
                and c["p99_ms"] > cfg.canary_p99_ratio * s["p99_ms"]):
            self.rollback_canary(
                f"p99 regression: canary {c['p99_ms']:.1f} ms > "
                f"{cfg.canary_p99_ratio:g}x stable {s['p99_ms']:.1f} ms")
            return
        if c["score_mean"] is not None and s["score_mean"] is not None:
            # version-vector gate (sharded tier): when the two cohorts'
            # responses read DIFFERENT shard versions — a publish
            # landing shard by shard, or one cohort degraded onto
            # default rows — their score means are not comparable this
            # tick. Skip the judgement (counted) rather than roll back
            # a healthy deploy for skew the embedding tier caused.
            c_vv, s_vv = c.get("versions"), s.get("versions")
            if (c_vv is not None and s_vv is not None and c_vv != s_vv):
                with self._m_lock:
                    self._vv_skew_skips += 1
                return
            gap = abs(c["score_mean"] - s["score_mean"])
            # NOT `gap > tol`: a truly garbage canary (params scaled to
            # overflow) scores inf/NaN, and `nan > tol` is False — the
            # worst deploy would be the one that never rolls back
            if not (gap <= cfg.canary_score_tol):
                self.rollback_canary(
                    f"score divergence: |canary mean "
                    f"{c['score_mean']:.4g} - stable mean "
                    f"{s['score_mean']:.4g}| = {gap:.4g} > "
                    f"{cfg.canary_score_tol:g}")

    # --- observability -------------------------------------------------
    def _obs_collect(self):
        """Registry collector: router totals + fleet shape as scrapeable
        samples (reads the same counters stats() reports)."""
        yield "ff_router_requests_total", {}, self._n_requests
        yield "ff_router_responses_total", {}, self._n_responses
        yield "ff_router_failed_total", {}, self._n_failed
        yield "ff_router_retries_total", {}, self._n_retries
        yield "ff_router_hedges_total", {}, self._n_hedges
        yield "ff_router_hedge_wins_total", {}, self._n_hedge_wins
        yield "ff_router_canary_rollbacks_total", {}, self._rollbacks
        yield "ff_router_canary_promotions_total", {}, self._promotions
        yield "ff_fleet_size", {}, len(self.fleet)
        yield "ff_fleet_healthy", {}, len(self.fleet.healthy())
        for rep in self.fleet.replicas:
            yield ("ff_fleet_replica_healthy",
                   {"replica": str(rep.rid)},
                   1.0 if rep.state == HEALTHY else 0.0)

    def healthz(self) -> Dict[str, Any]:
        """Fleet readiness: ok while at least one healthy replica can
        accept a request and the router is not draining. ``degraded``
        (sharded tier) means answers are being served from cache +
        default rows while a lookup shard is out — still ok: a load
        balancer must keep routing to a degraded-but-answering fleet
        (HTTP 200 with ``"degraded": true``), not starve it."""
        healthy = self.fleet.healthy()
        accepting = [r for r in healthy
                     if r.engine.healthz()["ok"]]
        out = {
            "ok": bool(accepting) and not self._closed,
            "draining": self._closed,
            "size": len(self.fleet),
            "healthy": len(healthy),
            "accepting": len(accepting),
            "states": {r.rid: r.state for r in self.fleet.replicas},
        }
        shard_set = getattr(self.fleet, "shard_set", None)
        if shard_set is not None:
            out["degraded"] = shard_set.degraded_now()
            out["shard_states"] = {r.slot: r.state
                                   for r in shard_set.shards}
        return out

    def stats(self) -> Dict[str, Any]:
        with self._m_lock:
            lat = sorted(self._lat_ms)
            out = {
                "requests": self._n_requests,
                "responses": self._n_responses,
                "failed": self._n_failed,
                "retries": self._n_retries,
                "hedges": self._n_hedges,
                "hedge_wins": self._n_hedge_wins,
            }
        out.update({
            # client-observed latency: includes queueing, retries and
            # hedges — the number an SLO is written against
            "p50_ms": percentile(lat, 50),
            "p99_ms": percentile(lat, 99),
            "canary": {
                "active": self._canary_active,
                "fraction": self._canary_fraction,
                "replicas": [r.rid for r in self.fleet.replicas
                             if r.cohort == "canary"],
                "rollbacks": self._rollbacks,
                "promotions": self._promotions,
                "last_rollback_reason": self._last_rollback_reason,
                "version_vector_skew_skips": self._vv_skew_skips,
            },
            "cohorts": {k: v.snapshot()
                        for k, v in self._cohorts.items()},
            "shadow": self.shadow_report(),
            "fleet": self.fleet.stats(),
        })
        return out
