"""Zero-downtime snapshot hot reload for the serving engine.

A trainer publishes rolling snapshots through ``CheckpointManager`` —
and, in the continual-learning loop, **delta snapshots** chained off
them through :class:`~..utils.delta.DeltaPublisher`. The
:class:`SnapshotWatcher` polls that directory READ-ONLY from the serving
process — it deliberately does not construct a ``CheckpointManager``
(whose init sweeps ``*.tmp-*`` orphans, which would race a live
trainer's in-flight atomic write).

Reload strategy, freshest-first:

1. **Delta chain**: when the manifest lists a chain whose tip is newer
   than the served version, the WHOLE chain is validated up front
   (:func:`~..utils.delta.resolve_chain`: prev links contiguous, every
   file present + CRC-32 clean, fingerprints match this model's build,
   base identity unchanged). If the engine is already AT a chain node,
   only the deltas past it are loaded — touched-rows-sized, not
   checkpoint-sized; a cold engine loads the base (full) plus the chain.
   Row payloads are ``device_put`` on this thread, OUTSIDE any dispatch
   lock, then applied between dispatches via ``FFModel.apply_delta`` —
   the same old-or-new-never-mixed discipline as ``swap_params``.
2. **Graceful degradation**: ANY chain problem — a gap from a lost
   manifest entry, a torn/missing delta, a replaced base, a foreign
   fingerprint, a load or apply failure — is a reject-with-reason, and
   the watcher falls back to the newest valid FULL snapshot (possibly
   the chain's own base: older but consistent). Never a failed request.

Failure handling keeps the two existing tiers — transient IO retried by
the shared ``read_with_retries`` backoff; real failures recorded in
``stats()`` (cumulative ``reload_failures`` + ``last_reload_error``) and
reject-with-reason'd to the engine once per cause — plus **exponential
backoff with jitter** on consecutive failures: a permanently-bad
manifest is re-polled at up to ``backoff_max_s`` instead of hammered at
the poll interval, and ``stats()["next_poll_s"]`` shows the current
pace. Any successful poll resets the backoff — including a poll that
recorded failures before recovering within the same tick (an install
landed after a CRC reject, or a chain fallback reached a good full
snapshot): a recovered watcher returns to the base poll interval.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Dict, Optional

from ..data.dataloader import read_with_retries
from ..obs import metrics as obsm
from ..obs import trace as obstrace
from ..utils import faults
from ..utils.checkpoint import (_file_crc32, config_fingerprint,
                                load_params_for_swap)
from ..utils.delta import (ChainError, load_delta_file, resolve_chain,
                           stage_delta_rows)


class SnapshotWatcher:
    """Background poller installing newer valid snapshots (full or
    delta-chained) into an :class:`~.engine.InferenceEngine`."""

    MANIFEST = "manifest.json"

    def __init__(self, engine, directory: str, poll_s: float = 0.5,
                 elastic: bool = False, allow_deltas: bool = True,
                 backoff_max_s: float = 30.0, wire=None):
        self._engine = engine
        self.directory = os.path.abspath(directory)
        # wire mode: manifest polls and snapshot/delta loads go through
        # a transport.SnapshotWireSource (the publish directory lives in
        # ANOTHER process); fetched files spool locally so the loaders'
        # zip validation + chain CRCs run unchanged on local paths. The
        # source gives wire IO the same retry/backoff treatment
        # read_with_retries gives file IO, with cumulative
        # wire_retries/last_wire_error surfaced in stats().
        self._wire = wire
        self._fs_dir = (self.directory if wire is None
                        else os.path.abspath(wire.spool_dir))
        self.poll_s = max(float(poll_s), 0.01)
        # cross-mesh reshard on load: a per-device fleet replica follows
        # a multi-device trainer's snapshots (ServeConfig.reshard)
        self.elastic = bool(elastic)
        self.allow_deltas = bool(allow_deltas)
        self.backoff_max_s = max(float(backoff_max_s), self.poll_s)
        self._fingerprint = config_fingerprint(engine.model)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._polls = 0
        # a permanently-bad snapshot (foreign fingerprint, torn file
        # left on disk) would otherwise re-record the same reject every
        # poll interval, forever
        self._rejected: set = set()
        # cumulative failure record (every failed attempt, unlike the
        # reject-once engine notification): a watcher that never manages
        # to reload must be visible in stats(), not silent
        self._reload_failures = 0
        self._last_reload_error = ""
        # exponential backoff on consecutive failing polls
        self._consecutive_failures = 0
        self._next_poll_s = self.poll_s
        self._jitter = random.Random(os.getpid() ^ id(self))
        # chain accounting for stats()
        self._delta_installs = 0
        self._chain_fallbacks = 0

    def _record_failure(self, reason: str) -> None:
        self._reload_failures += 1
        self._last_reload_error = reason

    def _reject_once(self, key: tuple, reason: str) -> None:
        self._record_failure(reason)
        if key in self._rejected:
            return
        self._rejected.add(key)
        self._engine.record_reload_reject(reason)

    # --- lifecycle -----------------------------------------------------
    def start(self) -> "SnapshotWatcher":
        if self._thread is not None:
            return self
        obsm.register_collector(self._obs_collect)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ff-serve-watcher")
        self._thread.start()
        return self

    def _obs_collect(self):
        """Registry collector: the freshness loop's health as
        scrapeable samples — a watcher that silently stopped reloading
        shows up as a flat ff_watcher_polls_total."""
        rid = getattr(self._engine, "replica_id", None)
        lab = {"replica": "" if rid is None else str(rid)}
        yield "ff_watcher_polls_total", lab, self._polls
        yield "ff_watcher_reload_failures_total", lab, \
            self._reload_failures
        yield "ff_watcher_delta_installs_total", lab, \
            self._delta_installs
        yield "ff_watcher_chain_fallbacks_total", lab, \
            self._chain_fallbacks
        yield "ff_watcher_consecutive_failures", lab, \
            self._consecutive_failures
        if self._wire is not None:
            yield "ff_watcher_wire_retries_total", lab, \
                self._wire.wire_retries

    def stop(self) -> None:
        obsm.unregister_collector(self._obs_collect)
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            self._poll_tick()
            self._stop.wait(self._next_poll_s)

    def _poll_tick(self) -> bool:
        """One watcher iteration: poll, then re-pace. A poll that
        INSTALLED something is a recovery even when the same poll also
        recorded failures on the way (a CRC-rejected newest entry before
        an older one installed, a torn delta chain that fell back to a
        good full reload) — the watcher returns to the base interval
        instead of compounding backoff forever after a mid-episode
        recovery."""
        before = self._reload_failures
        reloaded = False
        try:
            reloaded = self.poll_once()
        except Exception as e:   # noqa: BLE001 — the watcher must
            # never die; a failed poll is a reject, not an outage
            self._record_failure(f"watcher poll error: {e}")
            self._engine.record_reload_reject(
                f"watcher poll error: {e}")
        if reloaded or self._reload_failures == before:
            self._consecutive_failures = 0
        else:
            self._consecutive_failures += 1
        self._next_poll_s = self._backoff_interval()
        return reloaded

    def _backoff_interval(self) -> float:
        """Next poll delay: the base interval normally; exponential in
        the consecutive-failure count, jittered (x0.5–1.0 so a fleet of
        watchers hitting the same bad manifest desynchronizes), capped
        at ``backoff_max_s``."""
        if self._consecutive_failures == 0:
            return self.poll_s
        k = min(self._consecutive_failures, 10)
        base = min(self.poll_s * (2.0 ** k), self.backoff_max_s)
        return max(base * (0.5 + 0.5 * self._jitter.random()),
                   self.poll_s)

    # --- manifest read -------------------------------------------------
    def _read_manifest(self) -> Optional[Dict[str, Any]]:
        if self._wire is not None:
            try:
                m = self._wire.read_manifest()
            except Exception as e:   # noqa: BLE001 — wire budget spent
                self._record_failure(
                    f"manifest unreadable over the wire: {e}")
                return None
            return m if isinstance(m, dict) else None
        path = os.path.join(self.directory, self.MANIFEST)
        if not os.path.isfile(path):
            return None   # normal pre-publish state, not a failure

        def _load():
            with open(path) as f:
                return json.load(f)

        try:
            # transient IO (NFS hiccup, a read racing the trainer's
            # atomic manifest replace) gets the shared retry/backoff
            m = read_with_retries(_load, site="snapshot_manifest")
        except FileNotFoundError:
            return None   # swept between the isfile check and the open
        except (json.JSONDecodeError, OSError) as e:
            self._record_failure(f"manifest unreadable: {e}")
            return None
        return m if isinstance(m, dict) else None

    def _read_entries(self) -> list:
        m = self._read_manifest() or {}
        entries = m.get("entries")
        return entries if isinstance(entries, list) else []

    def _fetch_local(self, name: str) -> Optional[str]:
        """A published file's LOCAL path: the publish directory itself
        normally; in wire mode the spooled copy (fetched with wire
        retry/backoff — a failed fetch reads as a missing file, which
        the caller already degrades on)."""
        if not name:
            return None
        if self._wire is None:
            return os.path.join(self.directory, name)
        try:
            return self._wire.fetch_file(name)
        except Exception as e:   # noqa: BLE001 — wire budget spent
            self._record_failure(
                f"fetch of {name} over the wire failed: {e}")
            return None

    def _latest_valid(self, entries: Optional[list] = None
                      ) -> Optional[Dict[str, Any]]:
        """Newest manifest entry that exists on disk, matches this
        model's fingerprint, and checksums clean — the same discipline
        as ``CheckpointManager.latest_valid``, read-only."""
        if entries is None:
            entries = self._read_entries()
        for entry in sorted(entries,
                            key=lambda e: e.get("step", -1), reverse=True):
            path = self._fetch_local(entry.get("file", ""))
            if path is None or not os.path.isfile(path):
                continue
            fp = entry.get("fingerprint")
            if fp not in (None, self._fingerprint):
                self._reject_once(
                    (entry.get("file"), "fingerprint"),
                    f"snapshot {entry.get('file')} fingerprint {fp} != "
                    f"this model's {self._fingerprint} (differently-"
                    f"built model)")
                return None
            crc = entry.get("crc32")
            if crc is not None and _file_crc32(path) != crc:
                self._reject_once(
                    (entry.get("file"), "crc"),
                    f"snapshot {entry.get('file')} fails its CRC-32 "
                    f"(torn write / corruption)")
                continue   # an older snapshot may still be good
            return entry
        return None

    # --- one poll ------------------------------------------------------
    def poll_once(self) -> bool:
        """Check for newer servable state; install it if found. The
        delta chain is tried first (freshest, cheapest); any chain
        problem degrades to the newest valid full snapshot. Returns True
        when a reload happened."""
        self._polls += 1
        manifest = self._read_manifest()
        if manifest is None:
            return False
        if self.allow_deltas and self._try_delta_chain(manifest):
            return True
        return self._try_full(manifest)

    # --- delta chain path ---------------------------------------------
    def _try_delta_chain(self, manifest: Dict[str, Any]) -> bool:
        deltas = manifest.get("deltas")
        if not isinstance(deltas, list) or not deltas:
            return False
        tip_step = max(int(e.get("step", -1)) for e in deltas)
        # the trigger is the version FLOOR, not the engine's own
        # version: under the sharded serving tier a replacement lookup
        # shard can boot slightly stale while the ranker is already at
        # the tip — the chain then keeps replaying (installs are
        # idempotent per shard AND for the ranker's absolute row
        # values) until every shard has caught up
        floor = getattr(self._engine, "version_floor",
                        self._engine.version)
        if tip_step <= floor:
            return False
        key = ("chain", tip_step)
        if key in self._rejected:
            return False   # already degraded for this tip
        if self._wire is not None:
            # spool every file the chain could touch (deltas + the
            # candidate bases) so resolve_chain's presence + CRC
            # validation runs on local copies; a failed fetch degrades
            # to the full-snapshot path like any other chain problem
            try:
                for e in deltas:
                    if e.get("file"):
                        self._wire.fetch_file(e["file"])
                for e in (manifest.get("entries") or []):
                    if isinstance(e, dict) and e.get("file"):
                        self._wire.fetch_file(e["file"])
            except Exception as e:   # noqa: BLE001 — wire budget spent
                self._chain_fallbacks += 1
                self._reject_once(
                    key, f"delta chain fetch over the wire failed: {e} "
                         f"— falling back to full reload")
                return False
        try:
            base_entry, chain = resolve_chain(manifest,
                                              self._fingerprint,
                                              self._fs_dir)
        except ChainError as e:
            self._chain_fallbacks += 1
            self._reject_once(
                key, f"delta chain rejected: {e} — falling back to "
                     f"full reload")
            return False
        base_step = int(base_entry.get("step", -1))
        applied = self._engine.version
        on_chain = {base_step} | {int(e.get("step", -1)) for e in chain}
        # the engine's version only names a chain node once something
        # was actually INSTALLED from this directory — a fresh engine's
        # constructor-time version can coincide with a published step
        # without being that state, and patching delta rows onto it
        # would silently mix lineages
        if (self._engine.has_applied_snapshot and applied in on_chain
                and floor >= base_step and floor in on_chain):
            need_base = False
            pending = [e for e in chain
                       if int(e.get("step", -1)) > floor]
        elif (not self._engine.has_applied_snapshot
                or applied < base_step or floor < base_step):
            need_base = True      # cold engine (or a shard staler than
            pending = chain       # the base): base full + whole chain
        else:
            # the served version is between base and tip but NOT a
            # chain node (e.g. a snapshot from a retired chain):
            # applying these deltas could mix lineages — degrade
            self._chain_fallbacks += 1
            self._reject_once(
                key, f"delta chain rejected: served version {applied} "
                     f"is not on the chain (base {base_step}, tip "
                     f"{tip_step}) — falling back to full reload")
            return False
        if not pending:
            return False
        t_apply = time.perf_counter()
        try:
            # slow half on THIS thread, outside any dispatch lock: file
            # reads, validation, and the row payloads' device_put
            payloads = []
            for e in pending:
                path = os.path.join(self._fs_dir, e["file"])
                payload = read_with_retries(
                    lambda p=path: load_delta_file(p),
                    site="delta_reload")
                payloads.append(stage_delta_rows(self._engine.model,
                                                 payload))
            if need_base:
                base_path = os.path.join(self._fs_dir,
                                         base_entry["file"])
                faults.maybe_corrupt_reload(base_path)
                state = read_with_retries(
                    lambda: load_params_for_swap(self._engine.model,
                                                 base_path,
                                                 elastic=self.elastic),
                    site="snapshot_reload")
                state = faults.maybe_poison_reload(state)
                self._engine.install_snapshot(state, base_step,
                                              source=base_entry["file"])
            for e, payload in zip(pending, payloads):
                self._engine.install_delta(payload,
                                           int(e.get("step", -1)),
                                           source=e["file"])
            self._delta_installs += len(pending)
            obstrace.complete("publish/watcher-apply", t_apply,
                              kind="delta", installs=len(pending),
                              tip=tip_step)
        except Exception as e:   # noqa: BLE001
            self._chain_fallbacks += 1
            obstrace.instant("publish/chain-fallback",
                             reason=str(e)[:200])
            self._reject_once(
                key, f"delta chain failed to load/apply: {e} — falling "
                     f"back to full reload")
            return False
        if self._engine.version != tip_step:
            # an apply failed between dispatches (engine rolled its
            # version back and recorded the reject) — degrade
            self._chain_fallbacks += 1
            self._record_failure(
                f"delta chain applied partially (at version "
                f"{self._engine.version}, tip {tip_step})")
            self._rejected.add(key)
            return False
        return True

    # --- full-snapshot path ---------------------------------------------
    def _try_full(self, manifest: Dict[str, Any]) -> bool:
        entries = manifest.get("entries")
        entries = entries if isinstance(entries, list) else []
        if self._wire is not None:
            # don't spool snapshots that could never install — each
            # wire fetch re-downloads the file
            entries = [e for e in entries if isinstance(e, dict)
                       and int(e.get("step", -1)) > self._engine.version]
        entry = self._latest_valid(entries)
        if entry is None:
            return False
        step = int(entry.get("step", -1))
        if step <= self._engine.version:
            return False
        path = os.path.join(self._fs_dir, entry["file"])
        # fault window: the file can be corrupted AFTER the CRC check
        # above and BEFORE the load below (a torn replace, bit rot) —
        # the injection truncates it right here and the load must reject
        faults.maybe_corrupt_reload(path)
        t_apply = time.perf_counter()
        try:
            # slow part (read + validate + device_put) outside the
            # engine's dispatch lock: serving continues on old weights.
            # Transient IOErrors retry with the shared backoff before
            # counting as a failure; anything else (torn zip, shape
            # mismatch) rejects immediately
            state = read_with_retries(
                lambda: load_params_for_swap(self._engine.model, path,
                                             elastic=self.elastic),
                site="snapshot_reload")
        except Exception as e:   # noqa: BLE001
            self._reject_once(
                (entry["file"], "load"),
                f"snapshot {entry['file']} failed to load: {e}")
            return False
        # bad-deploy injection: the snapshot loaded CLEAN but the
        # weights are garbage — exactly what the canary controller's
        # score-divergence rollback exists to catch
        state = faults.maybe_poison_reload(state)
        self._engine.install_snapshot(state, step, source=entry["file"])
        obstrace.complete("publish/watcher-apply", t_apply, kind="full",
                          step=step)
        return True

    def stats(self) -> Dict[str, Any]:
        out = {"directory": self.directory, "polls": self._polls,
               "version_floor": getattr(self._engine, "version_floor",
                                        self._engine.version),
               "poll_s": self.poll_s,
               "next_poll_s": self._next_poll_s,
               "consecutive_failures": self._consecutive_failures,
               "delta_installs": self._delta_installs,
               "chain_fallbacks": self._chain_fallbacks,
               "reload_failures": self._reload_failures,
               "last_reload_error": self._last_reload_error,
               "wire_retries": 0, "last_wire_error": ""}
        if self._wire is not None:
            out["wire_retries"] = self._wire.wire_retries
            out["last_wire_error"] = self._wire.last_wire_error
        return out
