"""Zero-downtime snapshot hot reload for the serving engine.

A trainer publishes rolling snapshots through ``CheckpointManager``
(atomic npz + manifest with step/fingerprint/CRC-32). The
:class:`SnapshotWatcher` polls that directory READ-ONLY from the serving
process — it deliberately does not construct a ``CheckpointManager``
(whose init sweeps ``*.tmp-*`` orphans, which would race a live trainer's
in-flight atomic write) — validates the newest manifest entry exactly
like ``CheckpointManager._entry_valid`` (file present, fingerprint
matches THIS model's build, CRC-32 clean), loads the params with the
``params_only`` fast path into FRESH arrays outside any lock, and then
swaps them into the engine between dispatches.

Failure is always non-fatal, and is handled in two tiers:

- **Transient IO** (an NFS hiccup mid-``np.load``, a manifest read
  racing a writer) is absorbed by the shared
  :func:`~..data.dataloader.read_with_retries` backoff — the same
  retry discipline the training dataloaders use — before it ever counts
  as a failure.
- **Real failures** (retries exhausted, a torn manifest, a fingerprint
  from a differently-built model, a CRC mismatch, or a snapshot
  corrupted between validation and load — the
  ``FF_FAULT_CORRUPT_RELOAD`` injection) are recorded: the engine gets
  a reject-with-reason, and the watcher's own ``stats()`` carries the
  cumulative ``reload_failures`` count plus ``last_reload_error`` so a
  silently-never-reloading server is visible from /stats instead of
  just skipping to the next poll. Either way the engine keeps serving
  the current version — zero failed requests.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

from ..data.dataloader import read_with_retries
from ..utils import faults
from ..utils.checkpoint import (_file_crc32, config_fingerprint,
                                load_params_for_swap)


class SnapshotWatcher:
    """Background poller installing newer valid snapshots into an
    :class:`~.engine.InferenceEngine`."""

    MANIFEST = "manifest.json"

    def __init__(self, engine, directory: str, poll_s: float = 0.5,
                 elastic: bool = False):
        self._engine = engine
        self.directory = os.path.abspath(directory)
        self.poll_s = max(float(poll_s), 0.01)
        # cross-mesh reshard on load: a per-device fleet replica follows
        # a multi-device trainer's snapshots (ServeConfig.reshard)
        self.elastic = bool(elastic)
        self._fingerprint = config_fingerprint(engine.model)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._polls = 0
        # a permanently-bad snapshot (foreign fingerprint, torn file
        # left on disk) would otherwise re-record the same reject every
        # poll interval, forever
        self._rejected: set = set()
        # cumulative failure record (every failed attempt, unlike the
        # reject-once engine notification): a watcher that never manages
        # to reload must be visible in stats(), not silent
        self._reload_failures = 0
        self._last_reload_error = ""

    def _record_failure(self, reason: str) -> None:
        self._reload_failures += 1
        self._last_reload_error = reason

    def _reject_once(self, key: tuple, reason: str) -> None:
        self._record_failure(reason)
        if key in self._rejected:
            return
        self._rejected.add(key)
        self._engine.record_reload_reject(reason)

    # --- lifecycle -----------------------------------------------------
    def start(self) -> "SnapshotWatcher":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ff-serve-watcher")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:   # noqa: BLE001 — the watcher must
                # never die; a failed poll is a reject, not an outage
                self._record_failure(f"watcher poll error: {e}")
                self._engine.record_reload_reject(
                    f"watcher poll error: {e}")
            self._stop.wait(self.poll_s)

    # --- one poll ------------------------------------------------------
    def _read_entries(self) -> list:
        path = os.path.join(self.directory, self.MANIFEST)
        if not os.path.isfile(path):
            return []   # normal pre-publish state, not a failure

        def _load():
            with open(path) as f:
                return json.load(f)

        try:
            # transient IO (NFS hiccup, a read racing the trainer's
            # atomic manifest replace) gets the shared retry/backoff
            m = read_with_retries(_load, site="snapshot_manifest")
        except FileNotFoundError:
            return []   # swept between the isfile check and the open
        except (json.JSONDecodeError, OSError) as e:
            self._record_failure(f"manifest unreadable: {e}")
            return []
        entries = m.get("entries") if isinstance(m, dict) else None
        return entries if isinstance(entries, list) else []

    def _latest_valid(self) -> Optional[Dict[str, Any]]:
        """Newest manifest entry that exists on disk, matches this
        model's fingerprint, and checksums clean — the same discipline
        as ``CheckpointManager.latest_valid``, read-only."""
        for entry in sorted(self._read_entries(),
                            key=lambda e: e.get("step", -1), reverse=True):
            path = os.path.join(self.directory, entry.get("file", ""))
            if not os.path.isfile(path):
                continue
            fp = entry.get("fingerprint")
            if fp not in (None, self._fingerprint):
                self._reject_once(
                    (entry.get("file"), "fingerprint"),
                    f"snapshot {entry.get('file')} fingerprint {fp} != "
                    f"this model's {self._fingerprint} (differently-"
                    f"built model)")
                return None
            crc = entry.get("crc32")
            if crc is not None and _file_crc32(path) != crc:
                self._reject_once(
                    (entry.get("file"), "crc"),
                    f"snapshot {entry.get('file')} fails its CRC-32 "
                    f"(torn write / corruption)")
                continue   # an older snapshot may still be good
            return entry
        return None

    def poll_once(self) -> bool:
        """Check for a newer valid snapshot; install it if found.
        Returns True when a reload happened."""
        self._polls += 1
        entry = self._latest_valid()
        if entry is None:
            return False
        step = int(entry.get("step", -1))
        if step <= self._engine.version:
            return False
        path = os.path.join(self.directory, entry["file"])
        # fault window: the file can be corrupted AFTER the CRC check
        # above and BEFORE the load below (a torn replace, bit rot) —
        # the injection truncates it right here and the load must reject
        faults.maybe_corrupt_reload(path)
        try:
            # slow part (read + validate + device_put) outside the
            # engine's dispatch lock: serving continues on old weights.
            # Transient IOErrors retry with the shared backoff before
            # counting as a failure; anything else (torn zip, shape
            # mismatch) rejects immediately
            state = read_with_retries(
                lambda: load_params_for_swap(self._engine.model, path,
                                             elastic=self.elastic),
                site="snapshot_reload")
        except Exception as e:   # noqa: BLE001
            self._reject_once(
                (entry["file"], "load"),
                f"snapshot {entry['file']} failed to load: {e}")
            return False
        # bad-deploy injection: the snapshot loaded CLEAN but the
        # weights are garbage — exactly what the canary controller's
        # score-divergence rollback exists to catch
        state = faults.maybe_poison_reload(state)
        self._engine.install_snapshot(state, step, source=entry["file"])
        return True

    def stats(self) -> Dict[str, Any]:
        return {"directory": self.directory, "polls": self._polls,
                "poll_s": self.poll_s,
                "reload_failures": self._reload_failures,
                "last_reload_error": self._last_reload_error}
