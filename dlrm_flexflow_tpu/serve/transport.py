"""Pluggable transport for the serving seams' wire protocol.

``serve/wire.py`` defines the frames; this module carries them. Two
transports:

- **inproc** — the default is no transport at all: the shard tier,
  fleet, and watcher keep calling methods (today's zero-serialization
  fast path, bit-identical to pre-wire behavior). For tests and
  single-process deployments that want the full codec + fault seams
  without sockets, :class:`InprocTransport` loops frames through a
  :class:`WireServer`'s dispatch in-process.
- **tcp** — :class:`WireClient` over real sockets: a small connection
  pool with per-connection locks (``make_lock``, so the lock sanitizer
  sees them), per-request deadlines through the existing
  :class:`~..utils.watchdog.Deadline`, and bounded retry with
  exponential backoff on transient frame errors (CRC mismatch, torn
  stream, refused/reset connections). Retries reuse the SAME
  request-id, so a retry racing a slow-but-delivered original is
  answered from the server's dedup window instead of being applied
  twice.

Network-level fault injection (``FF_FAULT_NET_*``) is applied HERE,
against real frames: drop (client raises a transient error pre-send and
its retry budget absorbs it), duplicate (client sends the frame twice;
the server's request-id dedup proves the second delivery a no-op),
reorder (server defers a frame until a later arrival has been handled),
slow-link (client sleeps per frame). Per-seam RTT Reservoirs and
``ff_wire_*`` counters make every seam's behavior scrapeable.

The seam proxies live here too: :class:`RemoteShard` (an
:class:`~.shardtier.EmbeddingShard` client the tier's breaker/
degradation machinery drives unchanged), :class:`ShardServer`,
:class:`RemoteEngineClient`/:class:`EngineServer` (the
FleetRouter→replica dispatch seam), and :class:`SnapshotServer` (the
watcher's manifest + file-fetch seam).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.sanitizer import make_lock
from ..obs import metrics as obsm
from ..utils import faults
from ..utils.logging import get_logger
from ..utils.watchdog import Deadline, Heartbeat
from . import wire
from .wire import FrameError

log_wire = get_logger("serve.transport")

# seam names (the FF_FAULT_NET_* and ff_wire_* vocabulary)
SEAM_LOOKUP = "lookup"      # ranker -> embedding shard
SEAM_DISPATCH = "dispatch"  # router -> ranker replica
SEAM_PUBLISH = "publish"    # watcher/publisher -> embedding shard
SEAM_MANIFEST = "manifest"  # watcher -> publish directory
SEAMS = (SEAM_LOOKUP, SEAM_DISPATCH, SEAM_PUBLISH, SEAM_MANIFEST)

TRANSPORTS = ("inproc", "tcp")


class WireError(ConnectionError):
    """Transport failure after the retry budget: unreachable peer,
    deadline expired mid-exchange, or persistent frame corruption. The
    caller's circuit breaker treats it like any other seam outage."""


class WireRemoteError(RuntimeError):
    """The server's handler raised something the wire has no typed
    mapping for; carries ``{type}: {message}`` verbatim."""


# ---------------------------------------------------------------------
# per-seam telemetry (RTT Reservoirs + ff_wire_* counters)
# ---------------------------------------------------------------------
class _WireTelemetry:
    """Process-wide wire counters and per-seam RTT windows. Plain ints
    under one lock (obs may be off; stats() needs them either way);
    registered as an obs collector so ``--obs on`` scrapes the same
    numbers as ``ff_wire_*`` series."""

    COUNTERS = ("frames_sent", "frames_recv", "bytes_sent",
                "bytes_recv", "retries", "crc_errors", "drops", "dups",
                "reorders", "dedup_hits", "remote_errors")

    def __init__(self):
        self._lock = make_lock("_WireTelemetry._lock")
        self._counts: Dict[Tuple[str, str], int] = {}
        self._rtt: Dict[str, Any] = {}
        self._registered = False

    def _ensure_registered(self) -> None:
        # obs collectors resolve at configure time; register lazily so a
        # transport built after ``--obs on`` shows up in /metrics
        if not self._registered:
            self._registered = True
            obsm.register_collector(self._obs_collect)

    def count(self, seam: str, counter: str, n: int = 1) -> None:
        with self._lock:
            key = (seam, counter)
            self._counts[key] = self._counts.get(key, 0) + n

    def rtt_reservoir(self, seam: str):
        with self._lock:
            res = self._rtt.get(seam)
            if res is None:
                res = obsm.latency_reservoir(
                    "ff_wire_rtt_ms",
                    "one wire request round trip, per serving seam",
                    maxlen=2048, seam=seam)
                self._rtt[seam] = res
            return res

    def observe_rtt(self, seam: str, ms: float) -> None:
        self.rtt_reservoir(seam).observe(ms)

    def measured_rtt_floor(self, seam: str) -> Optional[float]:
        """The seam's observed p50 RTT, or None before any traffic —
        shardcheck's FLX509 default budget."""
        with self._lock:
            res = self._rtt.get(seam)
        if res is None:
            return None
        p50 = res.percentile(50)
        return None if not p50 else float(p50)

    def _obs_collect(self):
        with self._lock:
            items = sorted(self._counts.items())
        for (seam, counter), n in items:
            yield f"ff_wire_{counter}_total", {"seam": seam}, n

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        with self._lock:
            seams = sorted({s for s, _ in self._counts} |
                           set(self._rtt))
            for seam in seams:
                d = {c: self._counts.get((seam, c), 0)
                     for c in self.COUNTERS
                     if self._counts.get((seam, c), 0)}
                res = self._rtt.get(seam)
                if res is not None and res.count:
                    d["rtt_p50_ms"] = res.percentile(50)
                    d["rtt_p99_ms"] = res.percentile(99)
                out[seam] = d
        return out

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._rtt.clear()


_TELEMETRY = _WireTelemetry()


def wire_stats() -> Dict[str, Any]:
    """Per-seam wire counters + RTT percentiles (everything the
    ``ff_wire_*`` series export, as one dict)."""
    return _TELEMETRY.stats()


def measured_rtt_floor(seam: str = SEAM_LOOKUP) -> Optional[float]:
    return _TELEMETRY.measured_rtt_floor(seam)


def reset_wire_stats() -> None:
    """Test isolation: drop every counter and RTT window."""
    _TELEMETRY.reset()


# ---------------------------------------------------------------------
# request ids
# ---------------------------------------------------------------------
_RID_LOCK = threading.Lock()
_RID_NEXT = [((os.getpid() & 0xFFFF) << 32) | 1]


def next_request_id() -> int:
    """Process-unique, monotonic. The pid salt keeps two processes'
    streams to one server from colliding in its dedup window."""
    with _RID_LOCK:
        rid = _RID_NEXT[0]
        _RID_NEXT[0] = rid + 1
    return rid


def _raise_remote(meta: Dict[str, Any], seam: str) -> None:
    """Re-raise a server-side failure as the typed error the client's
    breaker logic already understands. Applied errors are NOT retried by
    the transport — the handler ran; only the byte carriage failed
    cases retry."""
    kind = str(meta.get("type", ""))
    msg = str(meta.get("message", ""))
    _TELEMETRY.count(seam, "remote_errors")
    if kind == "ShardDown":
        from .shardtier import ShardDown
        raise ShardDown(meta.get("shard_id"), msg)
    if kind == "ShardLookupTimeout":
        from .shardtier import ShardLookupTimeout
        raise ShardLookupTimeout(msg)
    if kind == "ChainError":
        from ..utils.delta import ChainError
        raise ChainError(msg)
    if kind == "ReplicaDown":
        from .engine import ReplicaDown
        raise ReplicaDown(meta.get("replica_id"), msg)
    if kind == "Overloaded":
        from .engine import Overloaded
        raise Overloaded(-1, -1)
    if kind == "ValueError":
        raise ValueError(msg)
    raise WireRemoteError(f"{kind}: {msg}")


# ---------------------------------------------------------------------
# the tcp client
# ---------------------------------------------------------------------
class _Conn:
    """One pooled socket + its make_lock (held while a request is in
    flight on it — the sanitizer sees every connection's critical
    section)."""

    def __init__(self, sock: socket.socket, name: str):
        self.sock = sock
        self.lock = make_lock(name)
        self.dead = False


class WireClient:
    """Pooled, deadline-bounded, retrying client to ONE wire server.

    Transient failures (connect refused/reset, torn stream, CRC
    mismatch, injected drop) burn the connection and retry with
    exponential backoff up to ``retries`` times within the per-request
    :class:`Deadline`; the request-id is minted once per request, so a
    retry that crosses a slow-but-delivered original is served from the
    server's dedup window. Typed server-side errors (ShardDown,
    ChainError, ...) are re-raised without retry — the handler ran."""

    def __init__(self, address: Tuple[str, int], *,
                 seam: str = SEAM_LOOKUP, retries: int = 2,
                 backoff_ms: float = 5.0, pool_size: int = 2,
                 connect_timeout_s: float = 5.0,
                 default_deadline_s: float = 10.0, name: str = ""):
        self.address = (str(address[0]), int(address[1]))
        self.seam = seam
        self.retries = max(int(retries), 0)
        self.backoff_ms = float(backoff_ms)
        self.pool_size = max(int(pool_size), 1)
        self.connect_timeout_s = float(connect_timeout_s)
        self.default_deadline_s = float(default_deadline_s)
        self.name = name or f"{self.address[0]}:{self.address[1]}"
        self._pool_lock = make_lock(f"WireClient._pool_lock[{self.name}]")
        self._idle: List[_Conn] = []
        self._made = 0
        self._closed = False
        self.wire_retries = 0
        self.last_wire_error = ""
        _TELEMETRY._ensure_registered()

    # --- pool ---------------------------------------------------------
    def _borrow(self, dl: Deadline) -> _Conn:
        with self._pool_lock:
            if self._closed:
                raise WireError(f"client {self.name} is closed")
            if self._idle:
                return self._idle.pop()
            n = self._made
            self._made += 1
        timeout = min(self.connect_timeout_s,
                      max(dl.remaining(), 0.001))
        try:
            sock = socket.create_connection(self.address,
                                            timeout=timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError as e:
            raise WireError(
                f"shard/replica process unreachable at "
                f"{self.address[0]}:{self.address[1]}: {e}") from e
        return _Conn(sock, f"WireClient.conn[{self.name}#{n}]")

    def _give_back(self, conn: _Conn) -> None:
        if conn.dead:
            self._close_conn(conn)
            return
        with self._pool_lock:
            if not self._closed and len(self._idle) < self.pool_size:
                self._idle.append(conn)
                return
        self._close_conn(conn)

    @staticmethod
    def _close_conn(conn: _Conn) -> None:
        try:
            conn.sock.close()
        except OSError:
            pass

    # --- one request --------------------------------------------------
    def request(self, opcode: int, payload: bytes,
                deadline_s: Optional[float] = None
                ) -> Tuple[int, bytes]:
        """Send one frame, return ``(opcode, payload)`` of its response.
        Raises :class:`WireError` when the budget is spent, or the
        re-raised typed error when the server's handler failed."""
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        dl = Deadline(deadline_s)
        rid = next_request_id()
        frame = wire.encode_frame(opcode, rid, payload)
        seam = self.seam
        attempt = 0
        while True:
            err: Optional[BaseException] = None
            t0 = time.perf_counter()
            try:
                faults.maybe_net_slow(seam)
                if faults.take_net_drop(seam):
                    _TELEMETRY.count(seam, "drops")
                    raise FrameError(
                        f"injected frame drop on seam {seam!r}")
                resp = self._exchange(frame, rid, dl, seam)
            except (FrameError, OSError) as e:
                # OSError covers socket.timeout / reset / refused; a
                # FrameError means stream framing is lost — either way
                # the connection is burned and the attempt retries
                if isinstance(e, FrameError):
                    _TELEMETRY.count(seam, "crc_errors")
                err = e
            else:
                r_op, r_payload = resp
                _TELEMETRY.observe_rtt(
                    seam, 1e3 * (time.perf_counter() - t0))
                if r_op == wire.OP_ERR:
                    _raise_remote(wire.decode_error(r_payload), seam)
                return r_op, r_payload
            attempt += 1
            self.last_wire_error = f"{type(err).__name__}: {err}"
            if attempt > self.retries or dl.expired() or self._closed:
                raise WireError(
                    f"{wire.opcode_name(opcode)} to {self.name} failed "
                    f"after {attempt} attempt(s) "
                    f"({dl.elapsed() * 1e3:.0f} ms of "
                    f"{dl.seconds * 1e3:.0f} ms budget): "
                    f"{self.last_wire_error}") from err
            self.wire_retries += 1
            _TELEMETRY.count(seam, "retries")
            time.sleep(min((self.backoff_ms / 1e3) * (2 ** (attempt - 1)),
                           max(dl.remaining(), 0.0)))

    def _exchange(self, frame: bytes, rid: int, dl: Deadline,
                  seam: str) -> Tuple[int, bytes]:
        conn = self._borrow(dl)
        try:
            with conn.lock:
                conn.dead = True   # healthy again only on a clean round
                conn.sock.settimeout(max(dl.remaining(), 0.001))
                dup = faults.take_net_dup(seam)
                conn.sock.sendall(frame)
                _TELEMETRY.count(seam, "frames_sent")
                _TELEMETRY.count(seam, "bytes_sent", len(frame))
                if dup:
                    # same request-id on the wire twice: the server's
                    # dedup must answer both without re-running the
                    # handler
                    _TELEMETRY.count(seam, "dups")
                    conn.sock.sendall(frame)
                    _TELEMETRY.count(seam, "frames_sent")
                    _TELEMETRY.count(seam, "bytes_sent", len(frame))
                r_op, r_rid, r_payload = wire.read_frame(conn.sock)
                _TELEMETRY.count(seam, "frames_recv")
                _TELEMETRY.count(seam, "bytes_recv",
                                 wire.HEADER_BYTES + len(r_payload))
                if dup:
                    # drain the duplicate's response so it cannot
                    # poison the next request on this connection
                    d_op, d_rid, _d = wire.read_frame(conn.sock)
                    _TELEMETRY.count(seam, "frames_recv")
                    if d_rid != rid or d_op != r_op:
                        raise FrameError(
                            f"duplicate response mismatch: "
                            f"{wire.opcode_name(d_op)}/{d_rid:#x} vs "
                            f"{wire.opcode_name(r_op)}/{rid:#x}")
                if r_rid != rid:
                    raise FrameError(
                        f"response request-id {r_rid:#x} != sent "
                        f"{rid:#x} (stream desynchronized)")
                conn.dead = False
                return r_op, r_payload
        finally:
            self._give_back(conn)

    def stats(self) -> Dict[str, Any]:
        return {"address": f"{self.address[0]}:{self.address[1]}",
                "seam": self.seam,
                "wire_retries": self.wire_retries,
                "last_wire_error": self.last_wire_error}

    def close(self) -> None:
        with self._pool_lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for conn in idle:
            self._close_conn(conn)


# ---------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------
class WireServer:
    """Threaded frame server: one accept loop, one thread per
    connection, all ff-named daemons, all joined on close.

    ``handlers`` maps request opcodes to ``fn(payload) -> payload``;
    the response echoes the opcode with ``RESP_BIT``; a handler
    exception becomes an ``OP_ERR`` frame carrying the typed error.
    A bounded request-id dedup window answers repeated ids from cache
    without re-invoking the handler — what makes client retries and
    injected duplicates provably idempotent. The ``FF_FAULT_NET_REORDER``
    seam applies here: a marked frame's processing is deferred until a
    LATER frame (any connection) has been handled, bounded by a timeout
    so a lone frame cannot deadlock."""

    DEDUP_WINDOW = 512
    REORDER_HOLD_S = 0.25

    def __init__(self, handlers: Dict[int, Callable[[bytes], bytes]],
                 host: str = "127.0.0.1", port: int = 0,
                 seam: str = SEAM_LOOKUP, name: str = "wire"):
        self.handlers = dict(handlers)
        self.seam = seam
        self.name = name
        self._host = host
        self._port = int(port)
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conn_lock = make_lock(f"WireServer._conn_lock[{name}]")
        self._stop = threading.Event()
        self._dedup: "OrderedDict[int, Tuple[int, bytes]]" = \
            OrderedDict()
        self._dedup_lock = make_lock(f"WireServer._dedup_lock[{name}]")
        # reorder bookkeeping: a plain Condition (internal ordering
        # primitive, never held across handler work)
        self._order = threading.Condition()
        self._handled = 0
        self.requests = 0
        self.dedup_hits = 0
        _TELEMETRY._ensure_registered()

    # --- lifecycle ----------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        return (self._host, self._port)

    def start(self) -> "WireServer":
        if self._listener is not None:
            return self
        self._listener = socket.create_server(
            (self._host, self._port), backlog=64)
        self._port = self._listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"ff-wire-accept-{self.name}")
        self._accept_thread.start()
        log_wire.info("wire server %s listening on %s:%d (seam %s)",
                      self.name, self._host, self._port, self.seam)
        return self

    def serve_forever(self) -> None:
        """Start and block until :meth:`close` (a shard process's main
        thread parks here)."""
        self.start()
        self._stop.wait()

    def close(self) -> None:
        self._stop.set()
        listener = self._listener
        self._listener = None
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass
        with self._conn_lock:
            conns, self._conns = self._conns, []
            threads = list(self._conn_threads)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        t = self._accept_thread
        self._accept_thread = None
        if t is not None:
            t.join(5.0)
        for t in threads:
            t.join(5.0)
        with self._order:
            self._order.notify_all()

    def __enter__(self) -> "WireServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- loops --------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            listener = self._listener
            if listener is None:
                return
            try:
                sock, _addr = listener.accept()
            except OSError:
                return   # listener closed
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_lock:
                if self._stop.is_set():
                    sock.close()
                    return
                self._conns.append(sock)
                t = threading.Thread(
                    target=self._serve_conn, args=(sock,), daemon=True,
                    name=f"ff-wire-conn-{self.name}"
                         f"-{len(self._conn_threads)}")
                self._conn_threads.append(t)
            t.start()

    def _serve_conn(self, sock: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    opcode, rid, payload = wire.read_frame(sock)
                except (ConnectionError, OSError):
                    return   # peer went away / server closing
                except FrameError as e:
                    # framing is lost on this stream: drop the
                    # connection, the client retries on a fresh one
                    log_wire.warning(
                        "wire server %s dropping connection: %s",
                        self.name, e)
                    return
                if faults.take_net_reorder(self.seam):
                    _TELEMETRY.count(self.seam, "reorders")
                    self._hold_for_reorder()
                resp_op, resp_payload = self.dispatch(opcode, rid,
                                                      payload)
                try:
                    wire.write_frame(sock, resp_op, rid, resp_payload)
                except (ConnectionError, OSError):
                    return
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _hold_for_reorder(self) -> None:
        """Defer this frame until another frame has been handled (or
        the hold window expires — a lone frame must not deadlock)."""
        with self._order:
            target = self._handled + 1
            self._order.wait_for(
                lambda: self._handled >= target or self._stop.is_set(),
                timeout=self.REORDER_HOLD_S)

    # --- dispatch (shared with InprocTransport) -----------------------
    def dispatch(self, opcode: int, rid: int,
                 payload: bytes) -> Tuple[int, bytes]:
        """Dedup-checked handler invocation; returns the response
        (opcode, payload) and caches it under the request-id."""
        with self._dedup_lock:
            hit = self._dedup.get(rid)
            if hit is not None:
                self.dedup_hits += 1
                _TELEMETRY.count(self.seam, "dedup_hits")
                return hit
        handler = self.handlers.get(opcode)
        try:
            if handler is None:
                raise WireRemoteError(
                    f"server {self.name} has no handler for "
                    f"{wire.opcode_name(opcode)}")
            resp = (opcode | wire.RESP_BIT, handler(payload))
        except Exception as e:   # noqa: BLE001 — becomes an OP_ERR frame
            resp = (wire.OP_ERR, wire.encode_error(e))
        with self._dedup_lock:
            self.requests += 1
            self._dedup[rid] = resp
            while len(self._dedup) > self.DEDUP_WINDOW:
                self._dedup.popitem(last=False)
        with self._order:
            self._handled += 1
            self._order.notify_all()
        return resp

    def stats(self) -> Dict[str, Any]:
        return {"address": f"{self._host}:{self._port}",
                "seam": self.seam, "requests": self.requests,
                "dedup_hits": self.dedup_hits}


class InprocTransport:
    """Loopback transport: the full frame codec + fault seams + dedup
    against a :class:`WireServer`'s dispatch, no sockets. Same
    ``request()`` surface as :class:`WireClient`."""

    def __init__(self, server: WireServer, *,
                 seam: Optional[str] = None, retries: int = 2,
                 backoff_ms: float = 1.0,
                 default_deadline_s: float = 10.0):
        self._server = server
        self.seam = seam or server.seam
        self.retries = max(int(retries), 0)
        self.backoff_ms = float(backoff_ms)
        self.default_deadline_s = float(default_deadline_s)
        self.wire_retries = 0
        self.last_wire_error = ""

    def request(self, opcode: int, payload: bytes,
                deadline_s: Optional[float] = None
                ) -> Tuple[int, bytes]:
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        dl = Deadline(deadline_s)
        rid = next_request_id()
        frame = wire.encode_frame(opcode, rid, payload)
        seam = self.seam
        attempt = 0
        while True:
            t0 = time.perf_counter()
            err: Optional[BaseException] = None
            try:
                faults.maybe_net_slow(seam)
                if faults.take_net_drop(seam):
                    _TELEMETRY.count(seam, "drops")
                    raise FrameError(
                        f"injected frame drop on seam {seam!r}")
                sends = 2 if faults.take_net_dup(seam) else 1
                if sends == 2:
                    _TELEMETRY.count(seam, "dups")
                resp = None
                for _ in range(sends):
                    f_op, f_rid, f_payload = wire.decode_frame(frame)
                    _TELEMETRY.count(seam, "frames_sent")
                    _TELEMETRY.count(seam, "bytes_sent", len(frame))
                    resp = self._server.dispatch(f_op, f_rid, f_payload)
                    _TELEMETRY.count(seam, "frames_recv")
            except FrameError as e:
                _TELEMETRY.count(seam, "crc_errors")
                err = e
            else:
                r_op, r_payload = resp
                _TELEMETRY.observe_rtt(
                    seam, 1e3 * (time.perf_counter() - t0))
                if r_op == wire.OP_ERR:
                    _raise_remote(wire.decode_error(r_payload), seam)
                return r_op, r_payload
            attempt += 1
            self.last_wire_error = f"{type(err).__name__}: {err}"
            if attempt > self.retries or dl.expired():
                raise WireError(
                    f"{wire.opcode_name(opcode)} (inproc) failed after "
                    f"{attempt} attempt(s): "
                    f"{self.last_wire_error}") from err
            self.wire_retries += 1
            _TELEMETRY.count(seam, "retries")
            time.sleep(min((self.backoff_ms / 1e3) * (2 ** (attempt - 1)),
                           max(dl.remaining(), 0.0)))

    def stats(self) -> Dict[str, Any]:
        return {"address": "inproc", "seam": self.seam,
                "wire_retries": self.wire_retries,
                "last_wire_error": self.last_wire_error}

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------
# shard seam: server + client proxy
# ---------------------------------------------------------------------
class ShardServer:
    """One :class:`~.shardtier.EmbeddingShard` behind a wire server —
    what :meth:`EmbeddingShard.serve_forever` runs, and what a shard
    OS process is."""

    def __init__(self, shard, host: str = "127.0.0.1", port: int = 0):
        self.shard = shard
        self._server = WireServer(
            {
                wire.OP_LOOKUP: self._on_lookup,
                wire.OP_PUBLISH: self._on_publish,
                wire.OP_INSTALL: self._on_install,
                wire.OP_PROBE: self._on_probe,
                wire.OP_STATS: self._on_stats,
            },
            host=host, port=port, seam=SEAM_LOOKUP,
            name=f"shard{shard.slot}")

    # --- handlers -----------------------------------------------------
    def _on_lookup(self, payload: bytes) -> bytes:
        requests = wire.decode_lookup_request(payload)
        out, version = self.shard.lookup(requests)
        return wire.encode_lookup_response(out, version)

    def _on_publish(self, payload: bytes) -> bytes:
        sub, version, expect_crc = wire.decode_publish(payload)
        applied = self.shard.apply_publish(sub, version, expect_crc)
        return wire.encode_payload(
            {"applied": bool(applied), "version": self.shard.version,
             "chain_crc": self.shard.chain_crc})

    def _on_install(self, payload: bytes) -> bytes:
        blocks, version, chain_crc = wire.decode_blocks(payload)
        applied = self.shard.install_blocks(blocks, version,
                                            chain_crc=chain_crc)
        return wire.encode_payload(
            {"applied": bool(applied), "version": self.shard.version,
             "chain_crc": self.shard.chain_crc})

    def _on_probe(self, payload: bytes) -> bytes:
        s = self.shard
        return wire.encode_payload(
            {"sid": s.sid, "slot": s.slot, "domain": s.domain,
             "version": s.version, "chain_crc": s.chain_crc,
             "hbm_bytes": s.hbm_bytes(),
             "quant": dict(getattr(s, "quant", {}) or {})})

    def _on_stats(self, payload: bytes) -> bytes:
        return wire.encode_payload(self.shard.stats())

    # --- lifecycle ----------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        return self._server.address

    def start(self) -> "ShardServer":
        self._server.start()
        return self

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def close(self) -> None:
        self._server.close()

    def stats(self) -> Dict[str, Any]:
        return self._server.stats()


class RemoteShard:
    """Client-side proxy speaking :class:`~.shardtier.EmbeddingShard`'s
    serving surface over a transport. The tier's
    :class:`~.shardtier.ShardReplica` wraps it unchanged — retries,
    ejection, probing, degradation, and publish fan-out all drive this
    object exactly as they drive a local shard; only the byte carriage
    differs. Versions/CRCs are cached from every response's in-band
    copy, so ``min_version()``/``version_vector()`` stay O(1) reads."""

    # the set's warm-cache persistence reads blocks_copy(); a remote
    # shard's blocks live in another process — its own boot source (the
    # seeded ShardCache) already covers replacement
    supports_persist = False
    remote = True

    def __init__(self, sid: int, slot: int, transport, *,
                 domain: str = "", quant: Optional[Dict[str, str]] = None,
                 lookup_deadline_s: float = 10.0,
                 publish_deadline_s: float = 30.0):
        self.sid = int(sid)
        self.slot = int(slot)
        self.domain = domain
        self.quant = dict(quant or {})
        self.transport = transport
        self.lookup_deadline_s = float(lookup_deadline_s)
        self.publish_deadline_s = float(publish_deadline_s)
        self._version = 0
        self._chain_crc = 0
        self._hbm_bytes = 0

    # --- EmbeddingShard surface ---------------------------------------
    @property
    def version(self) -> int:
        return self._version

    @property
    def chain_crc(self) -> int:
        return self._chain_crc

    def hbm_bytes(self) -> int:
        return self._hbm_bytes

    def _adopt_meta(self, meta: Dict[str, Any]) -> None:
        """Adopt a response's in-band version/CRC MONOTONICALLY.

        Responses are written back by whichever client thread receives
        them, so a reordered/duplicated frame's stale version can land
        here AFTER a newer one: adopting it unconditionally would
        regress ``version_vector()`` — the exact thing the tier's
        monotonic-apply contract forbids. The CRC travels with its
        version, so both move (or neither)."""
        ver = int(meta.get("version", self._version))
        if ver >= self._version:
            self._version = ver
            self._chain_crc = int(meta.get("chain_crc",
                                           self._chain_crc))

    def lookup(self, requests: Dict[str, np.ndarray]
               ) -> Tuple[Dict[str, Any], int]:
        _op, data = self.transport.request(
            wire.OP_LOOKUP, wire.encode_lookup_request(requests),
            deadline_s=self.lookup_deadline_s)
        out, ver = wire.decode_lookup_response(data)
        self._adopt_meta({"version": ver})
        return out, ver

    def apply_publish(self, sub: Optional[Dict[str, Any]], version: int,
                      expect_crc: Optional[int] = None) -> bool:
        _op, data = self.transport.request(
            wire.OP_PUBLISH, wire.encode_publish(sub, version,
                                                 expect_crc),
            deadline_s=self.publish_deadline_s)
        meta, _ = wire.decode_payload(data)
        self._adopt_meta(meta)
        return bool(meta.get("applied"))

    def install_blocks(self, blocks: Dict[str, Any], version: int,
                       chain_crc: int = 0) -> bool:
        _op, data = self.transport.request(
            wire.OP_INSTALL, wire.encode_blocks(blocks, version,
                                                chain_crc),
            deadline_s=self.publish_deadline_s)
        meta, _ = wire.decode_payload(data)
        self._adopt_meta(meta)
        return bool(meta.get("applied"))

    def refresh(self) -> Dict[str, Any]:
        """PROBE round trip: refresh the cached version/CRC/footprint
        from the authoritative process (connect-time admission and
        health probes call this)."""
        _op, data = self.transport.request(
            wire.OP_PROBE, wire.encode_payload({}),
            deadline_s=self.lookup_deadline_s)
        meta, _ = wire.decode_payload(data)
        self._adopt_meta(meta)
        self._hbm_bytes = int(meta.get("hbm_bytes", self._hbm_bytes))
        if meta.get("quant") and not self.quant:
            self.quant = {str(k): str(v)
                          for k, v in meta["quant"].items()}
        return meta

    def stats(self) -> Dict[str, Any]:
        """Local view only — stats() runs on scrape paths that must not
        block on a dead peer; the cached version/CRC are refreshed by
        every successful round trip."""
        out = {"sid": self.sid, "slot": self.slot, "domain": self.domain,
               "version": self._version, "chain_crc": self._chain_crc,
               "hbm_bytes": self._hbm_bytes, "remote": True}
        out.update(self.transport.stats())
        return out

    def close(self) -> None:
        self.transport.close()


# ---------------------------------------------------------------------
# ranker dispatch seam: server + client proxy
# ---------------------------------------------------------------------
class EngineServer:
    """One :class:`~.engine.InferenceEngine` behind a wire server —
    the process-per-replica entry (``engine.serve_forever()``)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0):
        self.engine = engine
        rid = getattr(engine, "replica_id", 0)
        self._server = WireServer(
            {
                wire.OP_PREDICT: self._on_predict,
                wire.OP_HEALTH: self._on_health,
                wire.OP_STATS: self._on_stats,
                wire.OP_PROBE: self._on_probe,
            },
            host=host, port=port, seam=SEAM_DISPATCH,
            name=f"engine{rid}")

    def _on_predict(self, payload: bytes) -> bytes:
        features = wire.decode_predict_request(payload)
        pred = self.engine.predict(features)
        return wire.encode_prediction(pred)

    def _on_health(self, payload: bytes) -> bytes:
        return wire.encode_payload(self.engine.healthz())

    def _on_stats(self, payload: bytes) -> bytes:
        return wire.encode_payload(self.engine.stats())

    def _on_probe(self, payload: bytes) -> bytes:
        e = self.engine
        return wire.encode_payload(
            {"replica_id": getattr(e, "replica_id", 0),
             "version": e.version, "alive": bool(e.alive()),
             "queue_depth": int(e.queue_depth),
             "heartbeat_age_s": float(e.heartbeat_age())})

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.address

    def start(self) -> "EngineServer":
        self._server.start()
        return self

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def close(self) -> None:
        self._server.close()


class RemoteEngineClient:
    """The dispatch-relevant :class:`~.engine.InferenceEngine` surface
    over the wire, so a :class:`~.fleet.Replica` can wrap a ranker in
    another process. Routing signals (queue depth, heartbeat age,
    liveness) come from probe/response traffic; a transport failure
    surfaces as :class:`~.engine.ReplicaDown`, which the router's
    breaker already absorbs. Deploy mutations (canary/shadow snapshot
    installs) are refused — those stay an inproc feature."""

    remote = True

    def __init__(self, address: Tuple[str, int], rid: int = 0, *,
                 deadline_s: float = 30.0, retries: int = 1,
                 backoff_ms: float = 5.0, pool_size: int = 4):
        self.replica_id = int(rid)
        self.client = WireClient(
            address, seam=SEAM_DISPATCH, retries=retries,
            backoff_ms=backoff_ms, pool_size=pool_size,
            default_deadline_s=deadline_s, name=f"engine{rid}")
        self._heartbeat = Heartbeat(f"remote-engine-{rid}")
        self._lat_ms = obsm.latency_reservoir(
            "ff_wire_dispatch_latency_ms",
            "remote replica dispatch round trip",
            maxlen=2048, replica=str(rid))
        self._pool = ThreadPoolExecutor(
            max_workers=max(int(pool_size), 1),
            thread_name_prefix=f"ff-wire-dispatch-{rid}")
        self._pending_lock = make_lock(
            f"RemoteEngineClient._pending_lock[{rid}]")
        self._pending: List[Future] = []
        self._version = 0
        self._closed = False

    # --- the dispatch path --------------------------------------------
    def predict(self, features: Dict[str, np.ndarray],
                timeout: Optional[float] = None):
        t0 = time.perf_counter()
        try:
            _op, data = self.client.request(
                wire.OP_PREDICT, wire.encode_predict_request(features),
                deadline_s=timeout)
        except WireError as e:
            from .engine import ReplicaDown
            raise ReplicaDown(self.replica_id, str(e)) from e
        pred = wire.decode_prediction(data)
        self._version = pred.version
        self._heartbeat.beat()
        self._lat_ms.observe(1e3 * (time.perf_counter() - t0))
        return pred

    def submit(self, features: Dict[str, np.ndarray]) -> Future:
        if self._closed:
            raise RuntimeError("remote engine client is closed")
        fut = self._pool.submit(self.predict, features)
        with self._pending_lock:
            self._pending = [f for f in self._pending
                             if not f.done()] + [fut]
        return fut

    # --- fleet hooks --------------------------------------------------
    @property
    def queue_depth(self) -> int:
        with self._pending_lock:
            self._pending = [f for f in self._pending if not f.done()]
            return len(self._pending)

    def alive(self) -> bool:
        return not self._closed

    def heartbeat_age(self) -> float:
        return self._heartbeat.age()

    @property
    def heartbeat(self) -> Heartbeat:
        return self._heartbeat

    def drain_pending(self, exc: Optional[BaseException] = None) -> int:
        with self._pending_lock:
            taken, self._pending = self._pending, []
        n = 0
        for f in taken:
            if f.cancel():
                n += 1
        return n

    @property
    def version(self) -> int:
        return self._version

    def healthz(self) -> Dict[str, Any]:
        try:
            _op, data = self.client.request(
                wire.OP_HEALTH, wire.encode_payload({}), deadline_s=5.0)
            meta, _ = wire.decode_payload(data)
            return meta
        except (WireError, WireRemoteError) as e:
            return {"ok": False, "reason": f"wire: {e}"}

    def stats(self) -> Dict[str, Any]:
        # the ENGINE-stats shape (Fleet.stats() sums these keys across
        # replicas), fetched from the remote process; zeros + an
        # ``unreachable`` reason when the peer is gone — a stats scrape
        # must degrade, not raise
        out: Dict[str, Any] = {
            k: 0 for k in ("requests", "responses", "overloaded",
                           "timeouts", "batches", "queue_depth",
                           "reloads", "reload_rejects")}
        try:
            _op, data = self.client.request(
                wire.OP_STATS, wire.encode_payload({}), deadline_s=5.0)
            meta, _ = wire.decode_payload(data)
            out.update(meta)
        except (WireError, WireRemoteError) as e:
            out["unreachable"] = str(e)
        out["replica_id"] = self.replica_id
        out["remote"] = True
        out["wire"] = self.client.stats()
        return out

    # --- deploy mutations stay inproc ---------------------------------
    def state_snapshot(self):
        raise RuntimeError(
            "canary/shadow deploys mutate replica state in-place; a "
            "REMOTE replica refuses them over the wire — run the "
            "candidate in its own process instead")

    def install_snapshot(self, state, version, source=""):
        raise RuntimeError(
            "install_snapshot over the wire is not supported — the "
            "remote replica's own SnapshotWatcher reloads it")

    # --- lifecycle ----------------------------------------------------
    def start(self) -> "RemoteEngineClient":
        return self

    def close(self, deadline_s: float = 10.0) -> None:
        self._closed = True
        self.drain_pending()
        self._pool.shutdown(wait=False)
        self.client.close()


# ---------------------------------------------------------------------
# watcher seam: manifest + file fetch over the wire
# ---------------------------------------------------------------------
class SnapshotServer:
    """Serves a publish directory's manifest and files over the wire —
    the trainer-side end of the watcher's delta subscription when the
    watcher runs in another process. Read-only, path-confined."""

    def __init__(self, directory: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.directory = os.path.abspath(directory)
        self._server = WireServer(
            {wire.OP_MANIFEST: self._on_manifest,
             wire.OP_FETCH: self._on_fetch},
            host=host, port=port, seam=SEAM_MANIFEST, name="snapshots")

    def _on_manifest(self, payload: bytes) -> bytes:
        import json
        path = os.path.join(self.directory, "manifest.json")
        if not os.path.isfile(path):
            return wire.encode_payload({"manifest": None})
        with open(path) as f:
            return wire.encode_payload({"manifest": json.load(f)})

    def _on_fetch(self, payload: bytes) -> bytes:
        meta, _ = wire.decode_payload(payload)
        name = str(meta.get("name", ""))
        path = os.path.abspath(os.path.join(self.directory, name))
        if not (path == self.directory
                or path.startswith(self.directory + os.sep)):
            raise ValueError(f"fetch of {name!r} escapes the publish "
                             f"directory")
        with open(path, "rb") as f:
            blob = f.read()
        return wire.encode_payload(
            {"name": name, "bytes": len(blob)},
            {"data": np.frombuffer(blob, np.uint8)})

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.address

    def start(self) -> "SnapshotServer":
        self._server.start()
        return self

    def close(self) -> None:
        self._server.close()


class SnapshotWireSource:
    """The watcher's wire-side reader: manifest polls and file loads
    with the same retry/backoff treatment ``read_with_retries`` gives
    file IO, spooled to a local directory so the existing loaders (zip
    validation, chain CRCs) run unchanged on local paths."""

    def __init__(self, transport, spool_dir: str, *, retries: int = 3,
                 backoff_s: float = 0.05):
        self.transport = transport
        self.spool_dir = os.path.abspath(spool_dir)
        self.retries = max(int(retries), 0)
        self.backoff_s = float(backoff_s)
        self.wire_retries = 0
        self.last_wire_error = ""
        os.makedirs(self.spool_dir, exist_ok=True)

    def _with_retries(self, fn: Callable[[], Any], what: str) -> Any:
        """Transient wire failures absorbed with exponential backoff —
        the wire analog of ``read_with_retries`` (which only knows
        IOError/OSError); cumulative counts surface in stats() and
        ``GET /metrics``."""
        attempt = 0
        while True:
            try:
                return fn()
            except (WireError, FrameError, OSError) as e:
                attempt += 1
                self.wire_retries += 1
                self.last_wire_error = f"{what}: {type(e).__name__}: {e}"
                _TELEMETRY.count(SEAM_MANIFEST, "retries")
                if attempt > self.retries:
                    raise
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))

    def read_manifest(self) -> Optional[Dict[str, Any]]:
        def _poll():
            _op, data = self.transport.request(
                wire.OP_MANIFEST, wire.encode_payload({}))
            meta, _ = wire.decode_payload(data)
            return meta.get("manifest")

        m = self._with_retries(_poll, "manifest poll")
        return m if isinstance(m, dict) else None

    def fetch_file(self, name: str) -> str:
        """Fetch one published file's bytes to the spool and return the
        local path (temp + ``os.replace`` — a crash mid-spool must not
        leave a torn file where a loader will trust it)."""
        def _fetch():
            _op, data = self.transport.request(
                wire.OP_FETCH, wire.encode_payload({"name": name}))
            _meta, arrays = wire.decode_payload(data)
            return arrays["data"].tobytes()

        blob = self._with_retries(_fetch, f"fetch {name}")
        local = os.path.join(self.spool_dir, name.replace(os.sep, "_"))
        tmp = local + ".tmp-spool"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, local)
        return local

    def stats(self) -> Dict[str, Any]:
        return {"wire_retries": self.wire_retries,
                "last_wire_error": self.last_wire_error}

    def close(self) -> None:
        self.transport.close()
