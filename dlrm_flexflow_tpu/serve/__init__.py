"""Online serving: dynamic-batched inference over the AOT eval cache,
with an embedding-row cache for host-resident tables and zero-downtime
snapshot hot reload. See engine.py for the design notes."""

from .cache import EmbeddingCache
from .engine import (DeadlineExceeded, InferenceEngine, Overloaded,
                     Prediction, ServeConfig)
from .watcher import SnapshotWatcher

__all__ = ["InferenceEngine", "ServeConfig", "Prediction", "Overloaded",
           "DeadlineExceeded", "EmbeddingCache", "SnapshotWatcher"]
