"""Online serving: dynamic-batched inference over the AOT eval cache
(continuous iteration-level admission), an embedding-row cache for
host-resident tables, zero-downtime snapshot hot reload, and a
fault-tolerant multi-replica fleet router with canary/shadow rollout.
See engine.py / router.py for the design notes."""

from .autoscale import AutoscaleConfig, Autoscaler
from .cache import EmbeddingCache
from .engine import (DeadlineExceeded, InferenceEngine, Overloaded,
                     Prediction, ReplicaDown, ServeConfig, percentile)
from .fleet import CircuitBreaker, Fleet, Replica
from .replace import ReplaceConfig, ReplacementController
from .router import FleetRouter, FleetUnavailable, RouterConfig
from .shardtier import (EmbeddingShard, EmbeddingShardSet, ShardDown,
                        ShardLookupTimeout, ShardReplica,
                        ShardTierConfig, ShardTierUnavailable,
                        check_serving_feasible, serving_footprint)
from .transport import (EngineServer, InprocTransport,
                        RemoteEngineClient, RemoteShard, ShardServer,
                        SnapshotServer, SnapshotWireSource, WireClient,
                        WireError, WireRemoteError, WireServer,
                        measured_rtt_floor, wire_stats)
from .watcher import SnapshotWatcher
from .wire import FrameError

__all__ = ["InferenceEngine", "ServeConfig", "Prediction", "Overloaded",
           "DeadlineExceeded", "ReplicaDown", "EmbeddingCache",
           "SnapshotWatcher", "Fleet", "Replica", "CircuitBreaker",
           "FleetRouter", "FleetUnavailable", "RouterConfig",
           "percentile", "Autoscaler", "AutoscaleConfig",
           "ReplacementController", "ReplaceConfig",
           "EmbeddingShardSet", "EmbeddingShard", "ShardReplica",
           "ShardTierConfig", "ShardDown", "ShardLookupTimeout",
           "ShardTierUnavailable", "check_serving_feasible",
           "serving_footprint",
           "WireClient", "WireServer", "WireError", "WireRemoteError",
           "InprocTransport", "FrameError", "ShardServer",
           "RemoteShard", "EngineServer", "RemoteEngineClient",
           "SnapshotServer", "SnapshotWireSource", "wire_stats",
           "measured_rtt_floor"]
