"""Online hot/cold re-placement: re-plan a serving fleet for drifted
traffic without restarting it.

The skew-aware placement (PR 11) is searched against ONE id
distribution — the histogram observed at training/publish time. Real
traffic churns: the zipf head rotates onto different rows, the skew
exponent drifts, and the searched hot set goes cold while a new one
pays full exchange + cold-cache prices. This module closes that gap as
a control loop over the serving fleet:

- the controller keeps a LIVE :class:`~..utils.histogram.
  IdFrequencySketch` per embedding op, fed by ``observe()`` from the
  router's request stream (same cheap staging-thread numpy as the
  trainer's ``TouchedRowTracker``);
- each ``tick()`` compares live vs the BASELINE sketch the current
  placement was searched with (total-variation ``divergence``, exposed
  as the ``ff_replace_divergence`` gauge) and debounces the breach
  through ``watchdog.Sustained`` + a cooldown — one sustained episode
  fires exactly one re-placement, because the swap rebases the baseline
  to the drifted distribution (divergence collapses to ~0) and resets
  the debounce;
- :meth:`~ReplacementController.replace_now` performs the swap: ONE
  warm-started re-search (``search.replan.replace_strategies`` — the
  plan-cache key carries a sketch digest, so the pre-drift entry cannot
  answer), then a ROLLING per-replica quiesce → recompile → reshard
  (``parallel.elastic.replace_placement``) executed on each engine's
  batcher thread via ``run_quiesced`` — in-flight batches finish on the
  old placement, the next dispatch runs the new one (old-or-new,
  never a mix, extended from weight swaps to placement swaps). On a
  multi-replica fleet each replica is EJECTED first so its queue drains
  onto siblings (the router retries those futures — zero failed
  requests) and re-admitted by the router's end-to-end probe after the
  swap; a single-replica fleet swaps in place (requests queue for the
  recompile — degraded latency, never a failed or garbage answer).
  Caches re-warm from the new sketch (``EmbeddingCache`` per engine;
  the shard tier gets a health tick so a degraded slot surfaces now,
  not at the next client miss).

Fault hooks: ``FF_FAULT_SKETCH_SKEW=op:factor`` corrupts the live
sketch the trigger reads (consume-once per op) — a lying sketch may
fire a spurious re-placement, but every plan it installs still serves
correct answers, which is the actual safety contract.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import metrics as obsm
from ..obs import trace as obstrace
from ..utils import faults
from ..utils.histogram import IdFrequencySketch
from ..utils.logging import get_logger
from ..utils.watchdog import Sustained

log_replace = get_logger("replace")


@dataclass
class ReplaceConfig:
    """Knobs for the drift trigger and the swap."""

    drift_threshold: float = 0.35   # total-variation trigger level
    sustain: int = 3                # consecutive breached ticks to fire
    cooldown_s: float = 30.0        # min seconds between re-placements
    interval_s: float = 0.5         # policy-thread evaluation period
    min_observations: int = 512     # live draws before divergence counts
    budget: int = 0                 # re-search budget (0 = greedy clamp)
    seed: int = 0
    swap_deadline_s: float = 60.0   # per-replica eject->readmit budget
    prewarm: bool = True            # re-warm EmbeddingCache from sketch
    # sliding-window size for the live sketch, in observed draws:
    # counts are halved whenever the total exceeds it, so recent
    # traffic dominates and a drift can actually reach the threshold
    # (a cumulative sketch dilutes new traffic under the old mass and
    # asymptotes BELOW it). 0 = 2 * min_observations.
    window: int = 0

    def __post_init__(self):
        if not 0.0 < self.drift_threshold <= 1.0:
            raise ValueError(
                f"drift_threshold must be in (0, 1], got "
                f"{self.drift_threshold}")
        if self.sustain < 1:
            raise ValueError(f"sustain must be >= 1, got {self.sustain}")


class ReplacementController:
    """The drift trigger + rolling placement swap over one fleet (see
    module docstring). Drive it with ``observe()`` per served batch and
    either ``tick()`` from your own loop or ``start()`` for the policy
    thread."""

    def __init__(self, router, baseline: Optional[Dict[str, Any]] = None,
                 config: Optional[ReplaceConfig] = None, plan_cache=None):
        self.router = router
        self.fleet = router.fleet
        self.config = config or ReplaceConfig()
        self.plan_cache = plan_cache
        from ..analysis.sanitizer import make_lock
        self._lock = make_lock("ReplacementController._lock")
        self._replace_lock = make_lock("ReplacementController._replace")
        self._sustained = Sustained(self.config.sustain)
        self._window = int(self.config.window
                           or 2 * self.config.min_observations)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._in_progress = False
        self._last_action_t = 0.0
        self._replacements = 0
        self._ticks = 0
        self._last_divergence: Dict[str, float] = {}
        self._last_report: Optional[Dict[str, Any]] = None
        self._decisions: List[Dict[str, Any]] = []
        # live sketches over the ops the trainer's TouchedRowTracker
        # sketches (same criteria, same flat rows*tables id space), fed
        # from SERVED batches instead of trained ones
        model = self.fleet.replicas[0].engine.model
        self._sketch_ops = []
        self._live: Dict[str, IdFrequencySketch] = {}
        for op in getattr(model, "ops", []):
            if (op.inputs and hasattr(op, "flat_lookup_ids")
                    and hasattr(op, "_row_shard_geometry")):
                rows, _pack, tables = op._row_shard_geometry()
                self._live[op.name] = IdFrequencySketch(rows * tables)
                self._sketch_ops.append((op, op.inputs[0].name))
        # the distribution the CURRENT placement was searched with:
        # explicit > whatever the model carries (attach_id_histograms) >
        # self-baselined from the first observed window
        self._baseline: Dict[str, Any] = dict(
            baseline if baseline is not None
            else getattr(model, "_id_histograms", None) or {})
        obsm.register_collector(self._obs_collect)

    # --- lifecycle ----------------------------------------------------
    def start(self) -> "ReplacementController":
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ff-replace-policy")
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(5.0)
        self._thread = None
        obsm.unregister_collector(self._obs_collect)

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:   # noqa: BLE001 — the policy thread must
                log_replace.exception("re-placement tick failed")

    def _obs_collect(self):
        yield "ff_replace_total", {}, self._replacements
        yield "ff_replace_ticks_total", {}, self._ticks
        for name, div in list(self._last_divergence.items()):
            yield "ff_replace_divergence", {"op": name}, div

    # --- the live sketch ----------------------------------------------
    def observe(self, features: Dict[str, np.ndarray]) -> None:
        """Count one served batch's lookup ids into the live sketches
        (cheap numpy; callers run it off the dispatch path)."""
        flats = [(op.name, op.flat_lookup_ids(features[in_name]))
                 for op, in_name in self._sketch_ops
                 if features.get(in_name) is not None]
        with self._lock:
            for name, ids in flats:
                sk = self._live[name]
                sk.observe(ids)
                if sk.total > self._window:
                    # exponential decay: halve the window so the sketch
                    # tracks RECENT traffic (see ReplaceConfig.window)
                    sk.counts //= 2
                    sk.total = int(sk.counts.sum())

    def seed_baseline(self, feature_batches) -> None:
        """Build the reference distribution from explicit traffic — the
        warm-up prefix the placement was actually trained/searched on —
        instead of self-baselining from the first live window (an
        empirical TV against a few-batch baseline is mostly sampling
        noise; give it the same draw count you expect of the live
        side)."""
        base: Dict[str, IdFrequencySketch] = {}
        for op, _in_name in self._sketch_ops:
            rows, _pack, tables = op._row_shard_geometry()
            base[op.name] = IdFrequencySketch(rows * tables)
        for feats in feature_batches:
            for op, in_name in self._sketch_ops:
                x = feats.get(in_name)
                if x is not None:
                    base[op.name].observe(op.flat_lookup_ids(x))
        with self._lock:
            self._baseline = base

    def _apply_sketch_faults(self) -> None:
        """FF_FAULT_SKETCH_SKEW lands HERE, persistently corrupting the
        live counts the trigger reads (consume-once per op)."""
        for name, sk in self._live.items():
            skewed = faults.maybe_skew_sketch(name, sk.counts)
            if skewed is not sk.counts:
                sk.counts = np.asarray(skewed, np.int64)
                sk.total = int(sk.counts.sum())

    def divergence(self) -> Dict[str, float]:
        """Per-op total-variation distance live-vs-baseline (0.0 for
        ops below ``min_observations`` or without a baseline yet)."""
        out: Dict[str, float] = {}
        with self._lock:
            self._apply_sketch_faults()
            for name, live in self._live.items():
                base = self._baseline.get(name)
                if base is None or live.total < \
                        self.config.min_observations:
                    out[name] = 0.0
                    continue
                try:
                    out[name] = live.divergence(base)
                except ValueError as e:
                    # a baseline from a differently-built model cannot
                    # gate this op — surface once, never crash the loop
                    out[name] = 0.0
                    log_replace.warning(
                        "divergence for op %r unavailable: %s", name, e)
        return out

    # --- the policy ----------------------------------------------------
    def tick(self) -> Optional[Dict[str, Any]]:
        """One trigger evaluation; returns the swap report when this
        tick fired a re-placement, else None."""
        self._ticks += 1
        cfg = self.config
        with self._lock:
            ready = all(sk.total >= cfg.min_observations
                        for sk in self._live.values()) \
                and bool(self._live)
            if ready and not self._baseline:
                # self-baseline: the first adequately-observed window IS
                # the reference distribution when none was provided
                self._baseline = {n: sk.copy()
                                  for n, sk in self._live.items()}
                log_replace.info(
                    "re-placement baseline self-initialized from the "
                    "first %d+ observed draws", cfg.min_observations)
                return None
        div = self.divergence()
        self._last_divergence = div
        worst = max(div.values(), default=0.0)
        breach = worst > cfg.drift_threshold
        if not self._sustained.observe(breach):
            return None
        if self._in_progress:
            return None
        if time.monotonic() - self._last_action_t < cfg.cooldown_s:
            return None
        worst_op = max(div, key=div.get)
        return self.replace_now(
            reason=f"sketch divergence {worst:.3f} > "
                   f"{cfg.drift_threshold:g} on {worst_op} "
                   f"({self._sustained.count} sustained periods)")

    # --- the swap -------------------------------------------------------
    def replace_now(self, reason: str = "manual") -> Dict[str, Any]:
        """Search once, swap every replica (rolling, zero failed
        requests), re-warm caches, rebase the trigger. Returns the
        report; raises only on misuse (concurrent calls serialize)."""
        cfg = self.config
        with self._replace_lock:
            self._in_progress = True
            t0 = time.monotonic()
            try:
                with self._lock:
                    sketches = {n: sk.copy()
                                for n, sk in self._live.items()}
                    # an un-observed live op falls back to its baseline:
                    # searching a uniform sketch would UNDO a hot/cold
                    # placement that is still right for it
                    for n, base in self._baseline.items():
                        if sketches.get(n) is None or \
                                sketches[n].total == 0:
                            sk = base.copy() if hasattr(base, "copy") \
                                else base
                            sketches[n] = sk
                from ..search.replan import replace_strategies
                from ..utils.warmcache import strategy_signature
                model0 = self.fleet.replicas[0].engine.model
                old_sig = strategy_signature(model0.strategies)
                with obstrace.span("replace/search"):
                    strategies, info = replace_strategies(
                        model0, sketches=sketches,
                        old=model0.strategies,
                        ndev=model0.mesh.size, budget=cfg.budget,
                        seed=cfg.seed, plan_cache=self.plan_cache)
                swapped = self._rolling_swap(sketches, strategies)
                if self.fleet.shard_set is not None:
                    # the tier serves the same rows either way; a tick
                    # surfaces any degraded slot NOW instead of at the
                    # first post-swap client miss
                    self.fleet.shard_set.health_tick()
                with self._lock:
                    self._baseline = sketches
                    for sk in self._live.values():
                        sk.reset()
                self._sustained.reset()
                self._last_action_t = time.monotonic()
                self._replacements += 1
                report = {
                    "reason": reason,
                    "replicas": swapped,
                    "duration_s": time.monotonic() - t0,
                    "searched": bool(info.get("searched", False)),
                    "plan_cache_hit": bool(info.get("plan_cache_hit",
                                                    False)),
                    "replan_s": float(info.get("replan_s", 0.0)),
                    "strategies_changed":
                        strategy_signature(strategies) != old_sig,
                }
                self._last_report = report
                self._decisions.append(report)
                obsm.counter(
                    "ff_replace_swaps_total",
                    "online placement re-plans executed").inc()
                obstrace.instant("replace/swap", reason=reason,
                                 replicas=len(swapped))
                log_replace.warning(
                    "online re-placement done in %.0f ms over %d "
                    "replica(s) (%s; plan %s): %s",
                    1e3 * report["duration_s"], len(swapped),
                    "strategies changed" if report["strategies_changed"]
                    else "strategies unchanged",
                    "cache" if report["plan_cache_hit"]
                    else ("searched" if report["searched"]
                          else "greedy"), reason)
                return report
            finally:
                self._in_progress = False

    def _rolling_swap(self, sketches: Dict[str, Any],
                      strategies) -> List[Dict[str, Any]]:
        """Swap each replica's placement on its own batcher thread; on a
        multi-replica fleet the replica is ejected first (queue drains
        onto siblings via router retries — zero failed requests) and
        comes back through the router's end-to-end probe."""
        from ..parallel.elastic import replace_placement
        from .fleet import HEALTHY
        cfg = self.config
        out: List[Dict[str, Any]] = []
        for rep in list(self.fleet.replicas):
            healthy = [r for r in self.fleet.replicas
                       if r.state == HEALTHY]
            eject = rep.state == HEALTHY and len(healthy) > 1
            if eject:
                rep.eject("placement swap")
            t0 = time.monotonic()
            engine = rep.engine

            def _swap(m=engine.model):
                return replace_placement(m, sketches=sketches,
                                         strategies=strategies,
                                         budget=cfg.budget,
                                         seed=cfg.seed,
                                         plan_cache=self.plan_cache)

            report = engine.run_quiesced(_swap, label="replace")
            if cfg.prewarm:
                engine.prewarm_cache_from(sketches)
            readmitted = True
            if eject:
                deadline = time.monotonic() + cfg.swap_deadline_s
                while rep.state != HEALTHY and \
                        time.monotonic() < deadline:
                    time.sleep(min(self.router.config.health_interval_s,
                                   0.05))
                readmitted = rep.state == HEALTHY
                if not readmitted:
                    log_replace.warning(
                        "replica %d not re-admitted within %.0fs after "
                        "placement swap (stays ejected; the router "
                        "keeps probing)", rep.rid, cfg.swap_deadline_s)
            out.append({"rid": rep.rid, "ejected": eject,
                        "readmitted": readmitted,
                        "reshard_s": float(getattr(report, "reshard_s",
                                                   0.0)),
                        "swap_s": time.monotonic() - t0})
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            live_total = {n: sk.total for n, sk in self._live.items()}
        return {
            "replacements": self._replacements,
            "ticks": self._ticks,
            "in_progress": self._in_progress,
            "last_divergence": dict(self._last_divergence),
            "live_observations": live_total,
            "baseline_ops": sorted(self._baseline),
            "sustained": self._sustained.count,
            "last_report": self._last_report,
        }
