"""Text-file graph format importer.

Parity with the reference PyTorch text-format interpreter (reference:
python/flexflow/torch/model.py, 149 LoC — reads a file of lines
`name, input1:input2, output, op_type, params...` emitted by its exporter
and replays them as FFModel calls). The same line format is accepted here.
"""

from __future__ import annotations

from typing import Dict, List

from ..core.model import FFModel


class PyTorchModel:
    """ff_model = PyTorchModel('graph.ff').apply(ff, input_tensors)"""

    def __init__(self, filename: str):
        self.filename = filename
        with open(filename) as f:
            self.lines = [l.strip() for l in f if l.strip()]

    def apply(self, ff: FFModel, input_tensors: List):
        env: Dict[str, object] = {}
        in_iter = iter(input_tensors)
        out = None
        for line in self.lines:
            fields = [x.strip() for x in line.split(",")]
            name, in_spec, _out, op_type = fields[:4]
            args = fields[4:]
            ins = [env[x] for x in in_spec.split(":") if x] if in_spec else []

            if op_type == "op_input":
                env[name] = next(in_iter)
            elif op_type == "op_linear":
                out_dim, use_bias = int(args[0]), args[1] == "True" if len(args) > 1 else True
                env[name] = ff.dense(ins[0], out_dim, use_bias=bool(use_bias),
                                     name=name)
            elif op_type == "op_conv2d":
                (oc, kh, kw, sh, sw, ph, pw) = [int(a) for a in args[:7]]
                groups = int(args[7]) if len(args) > 7 else 1
                env[name] = ff.conv2d(ins[0], oc, kh, kw, sh, sw, ph, pw,
                                      groups=groups, name=name)
            elif op_type == "op_pool2d":
                kh, sh, ph = int(args[0]), int(args[1]), int(args[2])
                pool = "max" if (len(args) < 4 or args[3] == "POOL_MAX") \
                    else "avg"
                env[name] = ff.pool2d(ins[0], kh, kh, sh, sh, ph, ph,
                                      pool_type=pool, name=name)
            elif op_type == "op_batchnorm2d":
                env[name] = ff.batch_norm(ins[0], relu=False, name=name)
            elif op_type == "op_embedding":
                env[name] = ff.embedding(ins[0], int(args[0]), int(args[1]),
                                         aggr="none", name=name)
            elif op_type == "op_flat":
                env[name] = ff.flat(ins[0], name=name)
            elif op_type == "op_relu":
                env[name] = ff.relu(ins[0], name=name)
            elif op_type == "op_sigmoid":
                env[name] = ff.sigmoid(ins[0], name=name)
            elif op_type == "op_tanh":
                env[name] = ff.tanh(ins[0], name=name)
            elif op_type == "op_elu":
                env[name] = ff.elu(ins[0], name=name)
            elif op_type == "op_softmax":
                env[name] = ff.softmax(ins[0], name=name)
            elif op_type == "op_dropout":
                env[name] = ff.dropout(ins[0], float(args[0]), name=name)
            elif op_type == "op_concat":
                env[name] = ff.concat(ins, int(args[0]), name=name)
            elif op_type == "op_add":
                env[name] = ff.add(ins[0], ins[1], name=name)
            elif op_type == "op_split":
                sizes = [int(a) for a in args[:-1]]
                env[name] = ff.split(ins[0], sizes, int(args[-1]),
                                     name=name)
            else:
                raise NotImplementedError(
                    f"text-graph import: unknown op {op_type}")
            out = env[name]
        return out
