"""torch.fx importer: symbolic-trace a torch.nn.Module into an FFModel
graph, copying the module's trained weights.

Parity with the reference fx exporter (reference: python/flexflow/torch/
fx.py, 198 LoC — walks the symbolically-traced graph and emits FFModel
calls for Conv2d/Pool/BatchNorm/Linear/Flatten/Relu/add/cat/...). Here we
go straight from the fx graph to ops AND transfer the torch parameters so
an existing trained torch model can continue training on TPU.
"""

from __future__ import annotations

import operator
from typing import Dict, List

import numpy as np

import jax

from ..core.model import FFModel


def from_torch_module(ff: FFModel, module, input_shapes: Dict[str, tuple],
                      copy_weights: bool = True):
    """Trace `module` with torch.fx and rebuild it on `ff`.

    input_shapes: placeholder name -> full shape INCLUDING batch dim.
    Returns (input_names, output_tensor, weight_loader) where weight_loader
    must be called after ff.init_layers() when copy_weights=True.
    """
    import torch
    import torch.fx as fx

    traced = fx.symbolic_trace(module)
    modules = dict(traced.named_modules())
    env: Dict[str, object] = {}
    pending_weights: List = []
    input_names: List[str] = []
    out_tensor = None

    def _weights_of(name, mod):
        w = {}
        if getattr(mod, "weight", None) is not None:
            wt = mod.weight.detach().numpy()
            if isinstance(mod, torch.nn.Linear):
                w["kernel"] = wt.T            # torch (out,in) -> ours (in,out)
            elif isinstance(mod, torch.nn.Conv2d):
                w["kernel"] = wt              # both OIHW
            elif isinstance(mod, torch.nn.Embedding):
                w["kernel"] = wt
            elif isinstance(mod, torch.nn.BatchNorm2d):
                w["scale"] = wt
        if getattr(mod, "bias", None) is not None:
            w["bias"] = mod.bias.detach().numpy()
        return w

    for node in traced.graph.nodes:
        if node.op == "placeholder":
            shape = input_shapes[node.name]
            import jax.numpy as jnp
            dtype = jnp.int32 if "int" in str(
                input_shapes.get(node.name + "__dtype", "")) else jnp.float32
            env[node.name] = ff.create_tensor(shape, dtype=dtype,
                                              name=node.name)
            input_names.append(node.name)

        elif node.op == "call_module":
            mod = modules[node.target]
            x = env[node.args[0].name]
            opname = node.target.replace(".", "_")
            if isinstance(mod, torch.nn.Linear):
                t = ff.dense(x, mod.out_features,
                             use_bias=mod.bias is not None, name=opname)
            elif isinstance(mod, torch.nn.Conv2d):
                t = ff.conv2d(x, mod.out_channels, *mod.kernel_size,
                              *mod.stride, *mod.padding,
                              use_bias=mod.bias is not None,
                              groups=mod.groups, name=opname)
            elif isinstance(mod, torch.nn.MaxPool2d):
                k = mod.kernel_size if isinstance(mod.kernel_size, tuple) \
                    else (mod.kernel_size,) * 2
                s = mod.stride if isinstance(mod.stride, tuple) \
                    else (mod.stride or mod.kernel_size,) * 2
                p = mod.padding if isinstance(mod.padding, tuple) \
                    else (mod.padding,) * 2
                t = ff.pool2d(x, *k, *s, *p, pool_type="max", name=opname)
            elif isinstance(mod, torch.nn.AvgPool2d):
                k = (mod.kernel_size,) * 2 if isinstance(mod.kernel_size, int) else mod.kernel_size
                s = (mod.stride or mod.kernel_size,)
                s = s * 2 if len(s) == 1 else s
                p = (mod.padding,) * 2 if isinstance(mod.padding, int) else mod.padding
                t = ff.pool2d(x, *k, *s, *p, pool_type="avg", name=opname)
            elif isinstance(mod, torch.nn.BatchNorm2d):
                t = ff.batch_norm(x, relu=False, name=opname)
            elif isinstance(mod, torch.nn.ReLU):
                t = ff.relu(x, name=opname)
            elif isinstance(mod, torch.nn.Sigmoid):
                t = ff.sigmoid(x, name=opname)
            elif isinstance(mod, torch.nn.Tanh):
                t = ff.tanh(x, name=opname)
            elif isinstance(mod, torch.nn.ELU):
                t = ff.elu(x, name=opname)
            elif isinstance(mod, torch.nn.Softmax):
                t = ff.softmax(x, name=opname)
            elif isinstance(mod, torch.nn.Dropout):
                t = ff.dropout(x, mod.p, name=opname)
            elif isinstance(mod, torch.nn.Flatten):
                t = ff.flat(x, name=opname)
            elif isinstance(mod, torch.nn.Embedding):
                t = ff.embedding(x, mod.num_embeddings, mod.embedding_dim,
                                 aggr="none", name=opname)
            elif isinstance(mod, torch.nn.EmbeddingBag):
                t = ff.embedding(x, mod.num_embeddings, mod.embedding_dim,
                                 aggr=mod.mode, name=opname)
            else:
                raise NotImplementedError(
                    f"fx import: unsupported module {type(mod).__name__}")
            env[node.name] = t
            if copy_weights:
                w = _weights_of(opname, mod)
                if w:
                    pending_weights.append((opname, w))

        elif node.op == "call_function":
            fn = node.target
            if fn in (operator.add, torch.add):
                env[node.name] = ff.add(env[node.args[0].name],
                                        env[node.args[1].name],
                                        name=node.name)
            elif fn in (operator.sub, torch.sub):
                env[node.name] = ff.subtract(env[node.args[0].name],
                                             env[node.args[1].name],
                                             name=node.name)
            elif fn in (operator.mul, torch.mul):
                env[node.name] = ff.multiply(env[node.args[0].name],
                                             env[node.args[1].name],
                                             name=node.name)
            elif fn is torch.cat:
                tensors = [env[a.name] for a in node.args[0]]
                axis = node.args[1] if len(node.args) > 1 else \
                    node.kwargs.get("dim", 0)
                env[node.name] = ff.concat(tensors, axis, name=node.name)
            elif fn is torch.flatten:
                env[node.name] = ff.flat(env[node.args[0].name],
                                         name=node.name)
            elif fn is torch.relu or fn is torch.nn.functional.relu:
                env[node.name] = ff.relu(env[node.args[0].name],
                                         name=node.name)
            elif fn is torch.sigmoid:
                env[node.name] = ff.sigmoid(env[node.args[0].name],
                                            name=node.name)
            elif fn is torch.tanh:
                env[node.name] = ff.tanh(env[node.args[0].name],
                                         name=node.name)
            elif fn is torch.nn.functional.elu:
                env[node.name] = ff.elu(env[node.args[0].name],
                                        name=node.name)
            elif fn is torch.nn.functional.softmax or fn is torch.softmax:
                x = env[node.args[0].name]
                dim = node.kwargs.get("dim")
                if dim is None and len(node.args) > 1:
                    dim = node.args[1]
                if dim is not None and dim not in (-1, len(x.shape) - 1):
                    raise NotImplementedError(
                        f"fx import: softmax over dim={dim} (only the last "
                        f"axis is supported)")
                env[node.name] = ff.softmax(x, name=node.name)
            else:
                raise NotImplementedError(
                    f"fx import: unsupported function {fn}")

        elif node.op == "call_method":
            x = env[node.args[0].name]
            if node.target == "view" or node.target == "reshape":
                shape = tuple(a if isinstance(a, int) else -1
                              for a in node.args[1:])
                if shape and shape[0] == -1:
                    shape = (x.shape[0],) + shape[1:]
                env[node.name] = ff.reshape(x, shape, name=node.name)
            elif node.target == "flatten":
                env[node.name] = ff.flat(x, name=node.name)
            else:
                raise NotImplementedError(
                    f"fx import: unsupported method {node.target}")

        elif node.op == "output":
            arg = node.args[0]
            out_tensor = env[arg.name if hasattr(arg, "name") else
                             arg[0].name]

        elif node.op == "get_attr":
            raise NotImplementedError("fx import: get_attr not supported")

    def weight_loader(compiled_model):
        from ..utils.checkpoint import set_weights
        for opname, w in pending_weights:
            have = compiled_model.params.get(opname, {})
            set_weights(compiled_model, opname,
                        {k: v for k, v in w.items() if k in have})

    return input_names, out_tensor, weight_loader
