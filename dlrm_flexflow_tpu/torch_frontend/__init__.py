from .fx import from_torch_module
from .model import PyTorchModel

__all__ = ["from_torch_module", "PyTorchModel"]
