"""Keras-compat initializer aliases (reference:
python/flexflow/keras/initializers.py) over the core initializers."""

from __future__ import annotations

from ..core.initializers import (ConstantInitializer, GlorotUniform,
                                 Initializer, NormInitializer,
                                 UniformInitializer, ZeroInitializer)

DefaultInitializer = None  # layer picks its own default (reference sem.)
Zeros = ZeroInitializer


class RandomUniform(UniformInitializer):
    def __init__(self, minval=-0.05, maxval=0.05, seed=None):
        super().__init__(min_val=minval, max_val=maxval)


class RandomNormal(NormInitializer):
    def __init__(self, mean=0.0, stddev=0.05, seed=None):
        super().__init__(mean=mean, stddev=stddev)


Constant = ConstantInitializer

__all__ = ["Initializer", "DefaultInitializer", "Zeros", "GlorotUniform",
           "RandomUniform", "RandomNormal", "Constant"]
