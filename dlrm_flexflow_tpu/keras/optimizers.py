"""Keras optimizer shims (reference: python/flexflow/keras/optimizers.py)."""

from __future__ import annotations

from ..core.optimizers import AdamOptimizer, Optimizer, SGDOptimizer


class SGD(SGDOptimizer):
    def __init__(self, learning_rate=0.01, momentum=0.0, nesterov=False,
                 weight_decay=0.0):
        super().__init__(lr=learning_rate, momentum=momentum,
                         nesterov=nesterov, weight_decay=weight_decay)


class Adam(AdamOptimizer):
    def __init__(self, learning_rate=0.001, beta_1=0.9, beta_2=0.999,
                 epsilon=1e-8, weight_decay=0.0):
        super().__init__(alpha=learning_rate, beta1=beta_1, beta2=beta_2,
                         epsilon=epsilon, weight_decay=weight_decay)


def _resolve_optimizer(opt) -> Optimizer:
    if isinstance(opt, Optimizer):
        return opt
    if isinstance(opt, str):
        name = opt.lower()
        if name == "sgd":
            return SGD()
        if name == "adam":
            return Adam()
        raise ValueError(f"unknown optimizer {opt!r}")
    raise TypeError(f"cannot resolve optimizer from {type(opt)}")
