from .np_utils import normalize, to_categorical

__all__ = ["to_categorical", "normalize"]
