"""Numpy utilities (reference: python/flexflow/keras/utils/np_utils.py)."""

import numpy as np


def to_categorical(y, num_classes=None, dtype="float32"):
    y = np.asarray(y, dtype="int64").ravel()
    if num_classes is None:
        num_classes = int(y.max()) + 1
    out = np.zeros((y.shape[0], num_classes), dtype=dtype)
    out[np.arange(y.shape[0]), y] = 1
    return out


def normalize(x, axis=-1, order=2):
    norm = np.linalg.norm(x, ord=order, axis=axis, keepdims=True)
    return x / np.maximum(norm, np.finfo(np.float64).eps)
