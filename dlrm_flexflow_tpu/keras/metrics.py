"""Keras-compat metric descriptors (reference:
python/flexflow/keras/metrics.py — thin classes whose `type` string selects
the core metric)."""

from __future__ import annotations


class Metric:
    type: str = ""

    def __init__(self, name: str = ""):
        self.name = name or self.type


class Accuracy(Metric):
    type = "accuracy"


class CategoricalCrossentropy(Metric):
    type = "categorical_crossentropy"


class SparseCategoricalCrossentropy(Metric):
    type = "sparse_categorical_crossentropy"


class MeanSquaredError(Metric):
    type = "mean_squared_error"


class RootMeanSquaredError(Metric):
    type = "root_mean_squared_error"


class MeanAbsoluteError(Metric):
    type = "mean_absolute_error"
