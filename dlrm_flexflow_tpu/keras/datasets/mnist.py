"""MNIST loader (reference: python/flexflow/keras/datasets/mnist.py —
returns uint8 (N, 28, 28) images + int labels from mnist.npz)."""

from __future__ import annotations

import numpy as np

from ._common import find_local, synthetic_images


def load_data(path: str = "mnist.npz", n_train: int = 6000,
              n_test: int = 1000):
    local = find_local(path)
    if local:
        with np.load(local, allow_pickle=True) as f:
            return (f["x_train"], f["y_train"]), (f["x_test"], f["y_test"])
    return synthetic_images(10, (28, 28), n_train, n_test, seed=28)
