"""Reuters newswire topic dataset (reference: python/flexflow/keras/
datasets/reuters.py — variable-length token sequences, 46 topics)."""

from __future__ import annotations

import numpy as np

from ._common import find_local, synthetic_sequences

NUM_CLASSES = 46


def load_data(path: str = "reuters.npz", num_words: int = 10000,
              test_split: float = 0.2, n_train: int = 2000,
              n_test: int = 500):
    local = find_local(path)
    if local:
        with np.load(local, allow_pickle=True) as f:
            xs, labels = f["x"], f["y"]
        xs = [[w if w < num_words else 2 for w in seq] for seq in xs]
        n = int(len(xs) * (1 - test_split))
        return (xs[:n], labels[:n]), (xs[n:], labels[n:])
    (xtr, ytr), (xte, yte) = synthetic_sequences(
        NUM_CLASSES, num_words, maxlen_mean=80,
        n_train=n_train, n_test=n_test, seed=46)
    return (xtr, ytr), (xte, yte)


def to_bow(seqs, num_words: int) -> np.ndarray:
    """Bag-of-words featurization used by the reference reuters_mlp
    example (keras preprocessing Tokenizer sequences_to_matrix)."""
    out = np.zeros((len(seqs), num_words), dtype=np.float32)
    for i, s in enumerate(seqs):
        out[i, np.clip(np.asarray(s, dtype=np.int64), 0, num_words - 1)] = 1.0
    return out
