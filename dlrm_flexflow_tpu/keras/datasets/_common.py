"""Shared helpers for dataset loaders: local archive discovery + the
class-template synthetic generator used when no archive exists."""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


def find_local(filename: str) -> Optional[str]:
    """Look for a dataset archive in $FF_DATASETS_DIR, ~/.keras/datasets
    (the reference loaders' cache dir), and ./datasets."""
    candidates = []
    env = os.environ.get("FF_DATASETS_DIR")
    if env:
        candidates.append(os.path.join(env, filename))
    candidates.append(
        os.path.join(os.path.expanduser("~"), ".keras", "datasets", filename))
    candidates.append(os.path.join("datasets", filename))
    for c in candidates:
        if os.path.exists(c):
            return c
    return None


def synthetic_images(num_classes: int, shape, n_train: int, n_test: int,
                     seed: int = 0):
    """Class-conditional images: one fixed random template per class plus
    noise.  uint8 in [0, 255] like the real archives."""
    r = np.random.RandomState(seed)
    templates = r.randint(0, 200, size=(num_classes,) + tuple(shape))

    def make(n, s):
        rr = np.random.RandomState(s)
        y = rr.randint(0, num_classes, size=(n,))
        noise = rr.randint(0, 56, size=(n,) + tuple(shape))
        x = np.clip(templates[y] + noise, 0, 255).astype(np.uint8)
        return x, y.astype(np.int64)

    x_train, y_train = make(n_train, seed + 1)
    x_test, y_test = make(n_test, seed + 2)
    return (x_train, y_train), (x_test, y_test)


def synthetic_sequences(num_classes: int, vocab: int, maxlen_mean: int,
                        n_train: int, n_test: int, seed: int = 0):
    """Class-conditional token sequences: each class draws from a distinct
    zipf-ish slice of the vocabulary (mimics reuters topic clustering)."""
    r = np.random.RandomState(seed)
    # per-class preferred token block
    blocks = r.randint(4, max(5, vocab - 200), size=(num_classes,))

    def make(n, s):
        rr = np.random.RandomState(s)
        y = rr.randint(0, num_classes, size=(n,))
        seqs = []
        for i in range(n):
            length = max(8, int(rr.poisson(maxlen_mean)))
            base = blocks[y[i]]
            toks = base + rr.zipf(1.6, size=length)
            toks = np.clip(toks, 1, vocab - 1)
            seqs.append([1] + toks.tolist())  # 1 = start marker, like keras
        return seqs, y.astype(np.int64)

    x_train, y_train = make(n_train, seed + 1)
    x_test, y_test = make(n_test, seed + 2)
    return (x_train, y_train), (x_test, y_test)
