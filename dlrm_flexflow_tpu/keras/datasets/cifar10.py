"""CIFAR-10 loader (reference: python/flexflow/keras/datasets/cifar.py +
cifar10.py — returns uint8 (N, 3, 32, 32) images, channels-first like the
reference's K.image_data_format()=channels_first examples)."""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from ._common import find_local, synthetic_images


def _from_archive(local: str):
    xs, ys = [], []
    xt = yt = None
    with tarfile.open(local) as tf:
        for m in tf.getmembers():
            base = os.path.basename(m.name)
            if base.startswith("data_batch") or base == "test_batch":
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                x = d[b"data"].reshape(-1, 3, 32, 32)
                y = np.asarray(d[b"labels"], dtype=np.int64)
                if base == "test_batch":
                    xt, yt = x, y
                else:
                    xs.append(x)
                    ys.append(y)
    return (np.concatenate(xs), np.concatenate(ys)), (xt, yt)


def load_data(path: str = "cifar-10-python.tar.gz", n_train: int = 5000,
              n_test: int = 1000):
    local = find_local(path)
    if local:
        return _from_archive(local)
    return synthetic_images(10, (3, 32, 32), n_train, n_test, seed=32)
