"""Keras-compatible datasets (reference: python/flexflow/keras/datasets —
mnist/cifar10/reuters loaders used by the example scripts and python/test.sh).

Each module exposes ``load_data()`` returning ``(x_train, y_train),
(x_test, y_test)`` with the same shapes/dtypes as the reference loaders.
This environment has no network egress, so when the archive is not found
on disk (``$FF_DATASETS_DIR`` or ``~/.keras/datasets``) the loaders fall
back to a *deterministic synthetic* dataset with class-conditional
structure — models trained on it reach non-trivial accuracy, which keeps
the example scripts' accuracy assertions meaningful.
"""

from . import cifar10, mnist, reuters  # noqa: F401

__all__ = ["mnist", "cifar10", "reuters"]
