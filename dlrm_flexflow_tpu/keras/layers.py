"""Keras layers: symbolic graph capture, translated to FFModel ops at fit().

Reference: python/flexflow/keras/layers/* (Dense core.py, Conv2D/pooling
convolutional.py, Embedding embeddings.py, merge.py, normalization.py).
Shapes are batch-less (batch prepended at materialization, like keras).
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Tuple

_ktensor_ids = itertools.count()


class KTensor:
    """Symbolic batch-less tensor: shape excludes the batch dim."""

    def __init__(self, shape: Tuple[int, ...], layer=None, dtype="float32"):
        self.shape = tuple(int(s) for s in shape)
        self.layer = layer          # producing layer (None for inputs)
        self.dtype = dtype
        self.tid = next(_ktensor_ids)


def Input(shape, dtype="float32"):
    """keras.Input (reference: keras input_layer)."""
    return KTensor(tuple(shape), None, dtype)


class Layer:
    _counters = {}

    def __init__(self, name: Optional[str] = None):
        cls = type(self).__name__.lower()
        if name is None:
            n = Layer._counters.get(cls, 0)
            Layer._counters[cls] = n + 1
            name = f"{cls}_{n}" if n else cls
        self.name = name
        self.input_tensors: List[KTensor] = []
        self.output: Optional[KTensor] = None
        self._pending_weights = None

    def __call__(self, inputs):
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.input_tensors = list(ins)
        out_shape, dtype = self.compute_output(ins)
        self.output = KTensor(out_shape, self, dtype)
        # stamp the production step on the TENSOR: a layer may be called
        # at several graph positions (nested-model replays), so the
        # layer's own wiring fields above only reflect the LATEST call —
        # graph capture must read the per-tensor record
        self.output._in_tensors = list(ins)
        return self.output

    def compute_output(self, ins):
        raise NotImplementedError

    def materialize(self, model, ff_inputs):
        """Emit FFModel op(s); ff_inputs are the materialized input
        Tensors."""
        raise NotImplementedError

    # -- weight surgery (reference layer.get_weights/set_weights, used by
    # the net2net examples to move a trained teacher's weights into a
    # wider/deeper student across two separately compiled models,
    # examples/python/keras/func_cifar10_cnn_net2net.py) ---------------
    def get_weights(self, ffmodel):
        """Trained (kernel, bias) as numpy arrays, in the reference's
        layouts (Dense (in, out); Conv2D OIHW)."""
        import numpy as np
        if ffmodel is None or not getattr(ffmodel, "params", None) or \
                self.name not in ffmodel.params:
            raise ValueError(
                f"layer {self.name!r}: no trained weights available — "
                "fit() (or init_layers) the model first")
        p = ffmodel.params[self.name]
        out = [np.asarray(p["kernel"], dtype=np.float32)]
        if "bias" in p:
            out.append(np.asarray(p["bias"], dtype=np.float32))
        return tuple(out) if len(out) > 1 else (out[0], None)

    def set_weights(self, ffmodel, kernel, bias=None):
        """Overwrite this layer's parameters. Before the owning model is
        materialized (the student in the net2net flow calls this right
        after compile()), the arrays are stashed and applied by fit()
        after init_layers."""
        import numpy as np
        kernel = np.asarray(kernel, dtype=np.float32)
        bias = None if bias is None else np.asarray(bias, np.float32)
        if ffmodel is None or not getattr(ffmodel, "params", None) or \
                self.name not in ffmodel.params:
            self._pending_weights = (kernel, bias)
            return
        self.apply_weights(ffmodel, kernel, bias)

    def apply_weights(self, ffmodel, kernel, bias):
        import jax

        import jax.numpy as jnp
        p = ffmodel.params[self.name]
        new = {"kernel": kernel} if bias is None else {"kernel": kernel,
                                                       "bias": bias}
        for k, v in new.items():
            if k not in p:
                raise ValueError(f"layer {self.name!r} has no param {k!r}")
            if tuple(p[k].shape) != tuple(v.shape):
                raise ValueError(
                    f"layer {self.name!r} param {k!r}: shape "
                    f"{v.shape} != expected {tuple(p[k].shape)}")
            arr = jnp.asarray(v, dtype=p[k].dtype)
            sh = getattr(ffmodel, "_param_sharding", {}).get(
                self.name, {}).get(k)
            p[k] = jax.device_put(arr, sh) if sh is not None else arr


def _norm_pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


class Dense(Layer):
    def __init__(self, units, activation=None, use_bias=True, name=None,
                 kernel_initializer=None, bias_initializer=None,
                 input_shape=None):
        super().__init__(name)
        self.units = int(units)
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer
        # keras-style: a first layer can carry the model's input shape
        # (Sequential([Dense(512, input_shape=(784,)), ...]))
        self.input_shape_arg = (tuple(input_shape)
                                if input_shape is not None else None)

    def compute_output(self, ins):
        return ins[0].shape[:-1] + (self.units,), ins[0].dtype

    def materialize(self, model, ff_inputs):
        return model.dense(ff_inputs[0], self.units,
                           activation=self.activation,
                           use_bias=self.use_bias,
                           kernel_initializer=self.kernel_initializer,
                           bias_initializer=self.bias_initializer,
                           name=self.name)


class Conv2D(Layer):
    """NCHW like the reference keras layer (channels_first)."""

    def __init__(self, filters, kernel_size, strides=(1, 1),
                 padding="valid", activation=None, use_bias=True, name=None,
                 input_shape=None):
        super().__init__(name)
        self.filters = int(filters)
        self.kernel = _norm_pair(kernel_size)
        self.strides = _norm_pair(strides)
        self.padding = padding
        self.activation = activation
        self.use_bias = use_bias
        self.input_shape_arg = (tuple(input_shape)
                                if input_shape is not None else None)

    def _pads(self):
        if self.padding == "same":
            return (self.kernel[0] // 2, self.kernel[1] // 2)
        if self.padding == "valid":
            return (0, 0)
        return _norm_pair(self.padding)

    def compute_output(self, ins):
        c, h, w = ins[0].shape
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.kernel[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.kernel[1]) // self.strides[1] + 1
        return (self.filters, oh, ow), ins[0].dtype

    def materialize(self, model, ff_inputs):
        ph, pw = self._pads()
        return model.conv2d(ff_inputs[0], self.filters, *self.kernel,
                            *self.strides, ph, pw,
                            activation=self.activation,
                            use_bias=self.use_bias, name=self.name)


class _Pool2D(Layer):
    pool_type = "max"

    def __init__(self, pool_size=(2, 2), strides=None, padding="valid",
                 name=None):
        super().__init__(name)
        self.pool = _norm_pair(pool_size)
        self.strides = _norm_pair(strides) if strides else self.pool
        self.padding = padding

    def _pads(self):
        if self.padding == "same":
            return (self.pool[0] // 2, self.pool[1] // 2)
        return (0, 0)

    def compute_output(self, ins):
        c, h, w = ins[0].shape
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.pool[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.pool[1]) // self.strides[1] + 1
        return (c, oh, ow), ins[0].dtype

    def materialize(self, model, ff_inputs):
        ph, pw = self._pads()
        return model.pool2d(ff_inputs[0], *self.pool, *self.strides, ph, pw,
                            pool_type=self.pool_type, name=self.name)


class MaxPooling2D(_Pool2D):
    pool_type = "max"


class AveragePooling2D(_Pool2D):
    pool_type = "avg"


class Flatten(Layer):
    def compute_output(self, ins):
        n = 1
        for s in ins[0].shape:
            n *= s
        return (n,), ins[0].dtype

    def materialize(self, model, ff_inputs):
        return model.flat(ff_inputs[0], name=self.name)


class Embedding(Layer):
    def __init__(self, input_dim, output_dim, name=None):
        super().__init__(name)
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)

    def compute_output(self, ins):
        return ins[0].shape + (self.output_dim,), "float32"

    def materialize(self, model, ff_inputs):
        return model.embedding(ff_inputs[0], self.input_dim,
                               self.output_dim, aggr="none", name=self.name)


class Concatenate(Layer):
    def __init__(self, axis=1, name=None):
        super().__init__(name)
        self.axis = axis  # axis counts the batch dim, keras-style

    def compute_output(self, ins):
        ax = self.axis - 1 if self.axis > 0 else len(ins[0].shape) + self.axis
        shape = list(ins[0].shape)
        shape[ax] = sum(t.shape[ax] for t in ins)
        return tuple(shape), ins[0].dtype

    def materialize(self, model, ff_inputs):
        return model.concat(ff_inputs, axis=self.axis, name=self.name)


class _Merge(Layer):
    op = "add"

    def compute_output(self, ins):
        return ins[0].shape, ins[0].dtype

    def materialize(self, model, ff_inputs):
        return getattr(model, self.op)(ff_inputs[0], ff_inputs[1],
                                       name=self.name)


class Add(_Merge):
    op = "add"


class Subtract(_Merge):
    op = "subtract"


class Multiply(_Merge):
    op = "multiply"


class Activation(Layer):
    def __init__(self, activation, name=None):
        super().__init__(name)
        self.activation = activation

    def compute_output(self, ins):
        return ins[0].shape, ins[0].dtype

    def materialize(self, model, ff_inputs):
        if self.activation == "softmax":
            return model.softmax(ff_inputs[0], name=self.name)
        return model._unary(self.activation, ff_inputs[0], name=self.name)


class Reshape(Layer):
    """keras Reshape: batch-less target_shape (reference
    examples/python/keras/reshape.py drives FFModel.reshape through it)."""

    def __init__(self, target_shape, name=None):
        super().__init__(name)
        self.target_shape = tuple(int(s) for s in target_shape)

    def compute_output(self, ins):
        n = 1
        for s in ins[0].shape:
            n *= s
        m = 1
        for s in self.target_shape:
            m *= s
        if n != m:
            raise ValueError(f"Reshape: {ins[0].shape} has {n} elements, "
                             f"target {self.target_shape} has {m}")
        return self.target_shape, ins[0].dtype

    def materialize(self, model, ff_inputs):
        batch = ff_inputs[0].shape[0]
        return model.reshape(ff_inputs[0], (batch,) + self.target_shape,
                             name=self.name)


class Dropout(Layer):
    def __init__(self, rate, seed=0, name=None):
        super().__init__(name)
        self.rate = float(rate)
        self.seed = seed

    def compute_output(self, ins):
        return ins[0].shape, ins[0].dtype

    def materialize(self, model, ff_inputs):
        return model.dropout(ff_inputs[0], self.rate, self.seed,
                             name=self.name)


class BatchNormalization(Layer):
    def __init__(self, relu=False, name=None):
        super().__init__(name)
        self.relu = relu

    def compute_output(self, ins):
        return ins[0].shape, ins[0].dtype

    def materialize(self, model, ff_inputs):
        return model.batch_norm(ff_inputs[0], relu=self.relu, name=self.name)


# functional merge forms (reference keras.layers.merge: concatenate/add/
# subtract/multiply as free functions over tensors)
def concatenate(tensors, axis=1, name=None):
    return Concatenate(axis=axis, name=name)(tensors)


def add(tensors, name=None):
    return Add(name=name)(tensors)


def subtract(tensors, name=None):
    return Subtract(name=name)(tensors)


def multiply(tensors, name=None):
    return Multiply(name=name)(tensors)
