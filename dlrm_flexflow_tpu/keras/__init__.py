"""Keras-compatible frontend.

Parity with the reference Keras compatibility layer (reference:
python/flexflow/keras — Model/Sequential graph capture translated to
FFModel add_* calls in `_create_flexflow_layers` (models/base_model.py:
446-501), fit() training loop with Legion tracing (base_model.py:367-431),
layers Dense/Conv2D/Pooling/Flatten/Embedding/Concatenate/Add/Activation/
Dropout/BatchNormalization, optimizers, losses, metrics, callbacks incl.
the accuracy early-stop hook at base_model.py:416-421).

Graph capture works on batch-less symbolic tensors; the FFModel (with its
static batch size) is materialized at fit()/compile-time, exactly like the
reference's deferred translation.
"""

from .layers import (Activation, Add, AveragePooling2D, BatchNormalization,
                     Concatenate, Conv2D, Dense, Dropout, Embedding, Flatten,
                     Input, MaxPooling2D, Multiply, Reshape, Subtract,
                     add, concatenate, multiply, subtract)
from .models import Model, Sequential
from .callbacks import Callback, EarlyStopping, VerifyMetrics
from .optimizers import SGD, Adam
from . import initializers, losses, metrics, preprocessing, utils

__all__ = ["Input", "Dense", "Conv2D", "MaxPooling2D", "AveragePooling2D",
           "Flatten", "Embedding", "Concatenate", "Add", "Subtract",
           "Multiply", "Reshape", "Activation", "Dropout",
           "BatchNormalization", "concatenate", "add", "subtract",
           "multiply", "Model", "Sequential", "Callback", "EarlyStopping",
           "VerifyMetrics", "SGD", "Adam"]
