"""Text preprocessing (reference: python/flexflow/keras/preprocessing/
text.py re-exports keras_preprocessing; implemented natively here)."""

from __future__ import annotations

import hashlib
from collections import OrderedDict


def text_to_word_sequence(text, filters='!"#$%&()*+,-./:;<=>?@[\\]^_`{|}~\t\n',
                          lower=True, split=" "):
    if lower:
        text = text.lower()
    table = str.maketrans({c: split for c in filters})
    return [w for w in text.translate(table).split(split) if w]


def hashing_trick(text, n, hash_function=None, **kwargs):
    if hash_function is None:
        hash_function = hash
    elif hash_function == "md5":
        hash_function = lambda w: int(  # noqa: E731
            hashlib.md5(w.encode()).hexdigest(), 16)
    words = text_to_word_sequence(text, **kwargs)
    return [(hash_function(w) % (n - 1) + 1) for w in words]


def one_hot(text, n, **kwargs):
    return hashing_trick(text, n, hash_function=hash, **kwargs)


class Tokenizer:
    """Word-index tokenizer (fit_on_texts / texts_to_sequences /
    texts_to_matrix subset)."""

    def __init__(self, num_words=None, oov_token=None, **kwargs):
        self.num_words = num_words
        self.oov_token = oov_token
        self.word_counts = OrderedDict()
        self.word_index = {}
        self._kwargs = kwargs

    def fit_on_texts(self, texts):
        for text in texts:
            for w in text_to_word_sequence(text, **self._kwargs):
                self.word_counts[w] = self.word_counts.get(w, 0) + 1
        sorted_words = sorted(self.word_counts, key=self.word_counts.get,
                              reverse=True)
        offset = 1
        if self.oov_token is not None:
            self.word_index[self.oov_token] = 1
            offset = 2
        for i, w in enumerate(sorted_words):
            self.word_index[w] = i + offset

    def texts_to_sequences(self, texts):
        out = []
        limit = self.num_words
        for text in texts:
            seq = []
            for w in text_to_word_sequence(text, **self._kwargs):
                i = self.word_index.get(w)
                if i is None:
                    if self.oov_token is not None:
                        seq.append(self.word_index[self.oov_token])
                    continue
                if limit and i >= limit:
                    if self.oov_token is not None:
                        seq.append(self.word_index[self.oov_token])
                    continue
                seq.append(i)
            out.append(seq)
        return out

    def texts_to_matrix(self, texts, mode="binary"):
        import numpy as np
        n = self.num_words or (len(self.word_index) + 1)
        m = np.zeros((len(texts), n), np.float32)
        for r, seq in enumerate(self.texts_to_sequences(texts)):
            for i in seq:
                if mode == "count":
                    m[r, i] += 1.0
                else:
                    m[r, i] = 1.0
        return m
