"""Sequence preprocessing (reference: python/flexflow/keras/preprocessing/
sequence.py re-exports keras_preprocessing; implemented natively here)."""

from __future__ import annotations

import numpy as np


def pad_sequences(sequences, maxlen=None, dtype="int32", padding="pre",
                  truncating="pre", value=0.0):
    """Pad/truncate a list of variable-length sequences to a 2-D array."""
    lengths = [len(s) for s in sequences]
    if maxlen is None:
        maxlen = max(lengths) if lengths else 0
    out = np.full((len(sequences), maxlen), value, dtype=dtype)
    for i, s in enumerate(sequences):
        if not len(s):
            continue
        s = list(s)
        if len(s) > maxlen:
            s = s[-maxlen:] if truncating == "pre" else s[:maxlen]
        if padding == "pre":
            out[i, -len(s):] = s
        else:
            out[i, :len(s)] = s
    return out


def make_sampling_table(size, sampling_factor=1e-5):
    """Zipf-based word-sampling probability table (word2vec-style)."""
    gamma = 0.577
    rank = np.arange(size)
    rank[0] = 1
    inv_fq = rank * (np.log(rank) + gamma) + 0.5 - 1.0 / (12.0 * rank)
    f = sampling_factor * inv_fq
    return np.minimum(1.0, f / np.sqrt(f))


def skipgrams(sequence, vocabulary_size, window_size=4, negative_samples=1.0,
              shuffle=True, sampling_table=None, seed=None):
    """(word, context) couples with binary labels, plus negative samples."""
    couples, labels = [], []
    for i, wi in enumerate(sequence):
        if not wi:
            continue
        if sampling_table is not None:
            if sampling_table[wi] < np.random.random():
                continue
        window_start = max(0, i - window_size)
        window_end = min(len(sequence), i + window_size + 1)
        for j in range(window_start, window_end):
            if j != i and sequence[j]:
                couples.append([wi, sequence[j]])
                labels.append(1)
    if negative_samples > 0:
        num_neg = int(len(labels) * negative_samples)
        words = [c[0] for c in couples]
        rng = np.random.RandomState(seed)
        rng.shuffle(words)
        couples += [[w, rng.randint(1, vocabulary_size)]
                    for w in words[:num_neg]]
        labels += [0] * num_neg
    if shuffle:
        rng = np.random.RandomState(seed)
        order = rng.permutation(len(couples))
        couples = [couples[i] for i in order]
        labels = [labels[i] for i in order]
    return couples, labels
