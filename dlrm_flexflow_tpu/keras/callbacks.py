"""Keras callbacks (reference: python/flexflow/keras/callbacks.py and the
accuracy early-stop hook in base_model.py:416-421)."""

from __future__ import annotations


class Callback:
    stop_training = False

    def on_train_begin(self, model):
        """Reset per-run state: a callback reused across fit() calls must
        not carry a stale stop/verdict into the next run."""
        self.stop_training = False

    def on_epoch_end(self, model, epoch: int, metrics: dict):
        pass

    def on_train_end(self, model):
        pass


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (min_delta/patience),
    like keras; the reference's built-in hook stops when accuracy crosses a
    threshold — see VerifyMetrics."""

    def __init__(self, monitor="accuracy", min_delta=0.0, patience=0,
                 mode="auto"):
        self.monitor = monitor
        self.min_delta = float(min_delta)
        self.patience = int(patience)
        self.best = None
        self.wait = 0
        self.mode = mode

    def on_train_begin(self, model):
        super().on_train_begin(model)
        self.best = None
        self.wait = 0

    def _better(self, cur, best):
        if self.mode == "min" or (self.mode == "auto"
                                  and "loss" in self.monitor):
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_epoch_end(self, model, epoch, metrics):
        cur = metrics.get(self.monitor)
        if cur is None:
            return
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True


class VerifyMetrics(Callback):
    """reference base_model.py:416-421: stop (successfully) once the
    metric reaches a threshold; RAISE if training finished without
    reaching it (the examples' accuracy assertion,
    examples/python/keras/accuracy.py). mode="min" verifies
    loss-like metrics (mse under the threshold)."""

    def __init__(self, metric="accuracy", threshold=0.9, mode="max"):
        self.metric = metric
        self.threshold = float(threshold)
        self.mode = mode
        self.reached = False
        self.last = None

    def on_train_begin(self, model):
        # a reused callback must re-verify, not pass on stale state
        super().on_train_begin(model)
        self.reached = False
        self.last = None

    def _ok(self, value):
        if self.mode == "min":
            return value <= self.threshold
        return value >= self.threshold

    def on_epoch_end(self, model, epoch, metrics):
        self.last = metrics.get(self.metric)
        if self.last is not None and self._ok(self.last):
            self.reached = True
            self.stop_training = True

    def on_train_end(self, model):
        if not self.reached:
            op = "<=" if self.mode == "min" else ">="
            raise AssertionError(
                f"VerifyMetrics: {self.metric} never reached {op} "
                f"{self.threshold} (last: {self.last})")
