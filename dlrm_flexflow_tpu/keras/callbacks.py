"""Keras callbacks (reference: python/flexflow/keras/callbacks.py and the
accuracy early-stop hook in base_model.py:416-421)."""

from __future__ import annotations


class Callback:
    stop_training = False

    def on_epoch_end(self, model, epoch: int, metrics: dict):
        pass


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (min_delta/patience),
    like keras; the reference's built-in hook stops when accuracy crosses a
    threshold — see VerifyMetrics."""

    def __init__(self, monitor="accuracy", min_delta=0.0, patience=0,
                 mode="auto"):
        self.monitor = monitor
        self.min_delta = float(min_delta)
        self.patience = int(patience)
        self.best = None
        self.wait = 0
        self.mode = mode

    def _better(self, cur, best):
        if self.mode == "min" or (self.mode == "auto"
                                  and "loss" in self.monitor):
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_epoch_end(self, model, epoch, metrics):
        cur = metrics.get(self.monitor)
        if cur is None:
            return
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True


class VerifyMetrics(Callback):
    """reference base_model.py:416-421: stop (successfully) once accuracy
    reaches a threshold; raise if training finished below it (the examples'
    accuracy assertion, examples/python/keras/accuracy.py)."""

    def __init__(self, metric="accuracy", threshold=0.9):
        self.metric = metric
        self.threshold = float(threshold)
        self.reached = False

    def on_epoch_end(self, model, epoch, metrics):
        if metrics.get(self.metric, 0.0) >= self.threshold:
            self.reached = True
            self.stop_training = True
