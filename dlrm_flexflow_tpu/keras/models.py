"""Keras Model/Sequential (reference: python/flexflow/keras/models/
base_model.py — graph translation at 446-501, fit loop at 367-431 with the
early-stop accuracy hook at 416-421, throughput print at 427)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..config import FFConfig
from ..core.model import FFModel
from .layers import Input, KTensor, Layer
from .optimizers import _resolve_optimizer


def _capture_plan(output: "KTensor"):
    """Topo-ordered [(layer, input_tids, output_tid)] by TENSOR
    traversal (KTensor._in_tensors, stamped at Layer.__call__)."""
    steps, seen = [], set()

    def visit(t):
        if t.layer is None or t.tid in seen:
            return
        seen.add(t.tid)
        ins = getattr(t, "_in_tensors", t.layer.input_tensors)
        for src in ins:
            visit(src)
        steps.append((t.layer, [s.tid for s in ins], t.tid))

    visit(output)
    return steps


class Model:
    """Functional-API model over symbolic KTensors."""

    def __init__(self, inputs, outputs, name: Optional[str] = None):
        self.inputs: List[KTensor] = (inputs if isinstance(inputs, list)
                                      else [inputs])
        self.output: KTensor = outputs if not isinstance(outputs, list) \
            else outputs[0]
        self.name = name or "model"
        self.optimizer = None
        self.loss = None
        self.metrics: List[str] = []
        self.ffmodel: Optional[FFModel] = None
        # FREEZE the graph plan at construction: Layer.__call__ mutates
        # the shared layer objects' wiring, so replaying a Model (nested
        # call) or materializing it later must read this immutable plan,
        # not the live wiring — otherwise a Model could only ever be
        # called/fit once (the tids would drift after the first replay).
        # Captured by TENSOR traversal (each KTensor records its own
        # production step), so a layer called at several positions
        # contributes every step, not just its latest wiring.
        self._plan = _capture_plan(self.output)

    @property
    def input(self):
        """Reference alias: model.input[0] is the first symbolic input."""
        return self.inputs

    # -- keras API ------------------------------------------------------
    def __call__(self, tensor):
        """Call a Model as a layer (reference nested-model examples,
        func_cifar10_cnn_nested.py: output = model2(model1(input))): the
        model's layer graph is replayed onto the new input tensor(s) and
        becomes part of the caller's graph. The SAME layer objects are
        reused, so surgery via set_weights on them still applies."""
        out, _ = self._replay(tensor)
        return out

    def _replay(self, tensor):
        """Replay the frozen plan onto new input(s); returns (output
        KTensor, the replayed steps) — the steps let a CONTAINING model
        (Sequential.add of a whole Model) record the expanded graph."""
        ts = tensor if isinstance(tensor, (list, tuple)) else [tensor]
        if len(ts) != len(self.inputs):
            raise ValueError(f"model {self.name!r} has {len(self.inputs)} "
                             f"inputs, got {len(ts)}")
        mapping = {inp.tid: t for inp, t in zip(self.inputs, ts)}
        steps = []
        for layer, in_tids, _o in self._plan:
            ins = [mapping[t] for t in in_tids]
            out = layer(ins if len(ins) > 1 else ins[0])
            mapping[_o] = out
            steps.append((layer, [mapping[t].tid for t in in_tids],
                          out.tid))
        return mapping[self.output.tid], steps

    def compile(self, optimizer="sgd", loss="mean_squared_error",
                metrics=None):
        self.optimizer = optimizer
        # accept keras-style Loss/Metric objects (reference losses.py /
        # metrics.py classes carry a `type` string) as well as plain strings
        self.loss = getattr(loss, "type", loss)
        metrics = metrics or ["mean_squared_error"]
        self.metrics = [getattr(m, "type", m) for m in metrics]

    def _topo_layers(self) -> List[Layer]:
        order: List[Layer] = []
        seen = set()

        def visit(t: KTensor):
            if t.layer is None or id(t.layer) in seen:
                return
            seen.add(id(t.layer))
            for src in t.layer.input_tensors:
                visit(src)
            order.append(t.layer)

        visit(self.output)
        return order

    def _materialize(self, batch_size: int, seed: int = 0) -> FFModel:
        """reference _create_flexflow_layers: keras graph -> FFModel ops.
        Reads the FROZEN construction-time plan, not the live layer
        wiring (which nested-model replays may have rewired since)."""
        cfg = FFConfig(batch_size=batch_size, seed=seed)
        ff = FFModel(cfg)
        tmap: Dict[int, object] = {}
        for i, kt in enumerate(self.inputs):
            dtype = jnp.int32 if kt.dtype in ("int32", "int64") else jnp.float32
            tmap[kt.tid] = ff.create_tensor((batch_size,) + kt.shape,
                                            dtype=dtype, name=f"input_{i}")
        done = set()
        for layer, in_tids, out_tid in self._plan:
            if id(layer) in done:
                raise NotImplementedError(
                    f"layer {layer.name!r} appears at multiple graph "
                    "positions (weight tying/siamese reuse); "
                    "materializing shared parameters is not supported — "
                    "use separate layer instances (the reference frontend "
                    "has the same single-position semantics)")
            done.add(id(layer))
            ins = [tmap[t] for t in in_tids]
            tmap[out_tid] = layer.materialize(ff, ins)
        self.ffmodel = ff
        self._ff_out = tmap[self.output.tid]
        return ff

    def fit(self, x, y, batch_size: int = 64, epochs: int = 1,
            callbacks=None, verbose: bool = True, seed: int = 0):
        xs = x if isinstance(x, list) else [x]
        if len(xs) != len(self.inputs):
            raise ValueError(f"model has {len(self.inputs)} inputs, got "
                             f"{len(xs)} arrays")
        ff = self._materialize(batch_size, seed)
        ff.compile(_resolve_optimizer(self.optimizer), self.loss,
                   self.metrics, final_tensor=self._ff_out)
        ff.init_layers()
        # weights stashed by Layer.set_weights before materialization
        # (the net2net student flow) land now, over the fresh init
        for layer, _, _ in self._plan:
            if layer._pending_weights is not None:
                k, b = layer._pending_weights
                layer.apply_weights(ff, k, b)
                layer._pending_weights = None
        inputs = {f"input_{i}": np.asarray(a) for i, a in enumerate(xs)}

        stop = {"flag": False}
        cbs = list(callbacks or [])
        for cb in cbs:
            if hasattr(cb, "on_train_begin"):
                cb.on_train_begin(self)

        def on_epoch(model, epoch, report):
            for cb in cbs:
                if hasattr(cb, "on_epoch_end"):
                    cb.on_epoch_end(self, epoch, report)
                    if getattr(cb, "stop_training", False):
                        stop["flag"] = True
            if stop["flag"]:
                raise _StopFit()

        try:
            result = ff.fit(inputs, np.asarray(y), epochs=epochs,
                            batch_size=batch_size, verbose=verbose,
                            callbacks=[on_epoch])
        except _StopFit:
            result = {"metrics": ff.perf.report()}
        for cb in cbs:
            if hasattr(cb, "on_train_end"):
                cb.on_train_end(self)   # VerifyMetrics asserts here
        return result

    def evaluate(self, x, y, batch_size: int = 64):
        xs = x if isinstance(x, list) else [x]
        if self.ffmodel is None:
            ff = self._materialize(batch_size)
            ff.compile(_resolve_optimizer(self.optimizer or "sgd"),
                       self.loss or "mean_squared_error", self.metrics or
                       ["mean_squared_error"], final_tensor=self._ff_out)
            ff.init_layers()
        preds = []
        ff = self.ffmodel
        n = len(np.asarray(y))
        for b in range(n // batch_size):
            sl = slice(b * batch_size, (b + 1) * batch_size)
            batch = {f"input_{i}": np.asarray(a)[sl]
                     for i, a in enumerate(xs)}
            preds.append(np.asarray(ff.forward_batch(batch)))
        return np.concatenate(preds, axis=0)

    def predict(self, x, batch_size: int = 64):
        xs = x if isinstance(x, list) else [x]
        n = len(np.asarray(xs[0]))
        return self.evaluate(xs, np.zeros((n, 1)), batch_size)

    def summary(self) -> str:
        lines = [f'Model: "{self.name}"']
        for layer, _, _ in self._plan:
            lines.append(f"  {layer.name:<28} out={layer.output.shape}")
        return "\n".join(lines)


class _StopFit(Exception):
    pass


class Sequential(Model):
    """reference: keras Sequential — layers stacked on one input."""

    def __init__(self, layers=None, name: Optional[str] = None):
        self._layers: List[Layer] = []
        self._input: Optional[KTensor] = None
        self._out: Optional[KTensor] = None
        self.name = name or "sequential"
        self.optimizer = None
        self.loss = None
        self.metrics = []
        self.ffmodel = None
        self._plan = []     # built incrementally by add()
        for l in layers or []:
            self.add(l)

    def add(self, layer):
        if self._input is None:
            if isinstance(layer, KTensor):
                self._input = layer
                self._out = layer
                return
            # reference seeding forms: the first layer carries
            # input_shape=(...), or the first element is itself a Model
            # (seq_mnist_cnn_nested.py stacks whole sub-models)
            shape = getattr(layer, "input_shape_arg", None)
            if shape is None and isinstance(layer, Model):
                shape = layer.inputs[0].shape
            if shape is not None:
                self._input = Input(shape)
                self._out = self._input
        if self._input is None:
            raise ValueError(
                "Sequential needs an Input first: Sequential([Input(...), "
                "Dense(...), ...]), or give the first layer an "
                "input_shape=")
        if isinstance(layer, Model):
            # a whole nested Model: record its EXPANDED steps so the
            # frozen plan stays materializable (a Model has no
            # .materialize of its own)
            self._out, steps = layer._replay(self._out)
            self._plan.extend(steps)
        else:
            in_tid = self._out.tid
            self._out = layer(self._out)
            self._plan.append((layer, [in_tid], self._out.tid))
        self._layers.append(layer)

    @property
    def inputs(self):
        return [self._input]

    @inputs.setter
    def inputs(self, v):
        pass

    @property
    def output(self):
        return self._out

    @output.setter
    def output(self, v):
        pass
