"""Keras Model/Sequential (reference: python/flexflow/keras/models/
base_model.py — graph translation at 446-501, fit loop at 367-431 with the
early-stop accuracy hook at 416-421, throughput print at 427)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..config import FFConfig
from ..core.model import FFModel
from .layers import Input, KTensor, Layer
from .optimizers import _resolve_optimizer


class Model:
    """Functional-API model over symbolic KTensors."""

    def __init__(self, inputs, outputs, name: Optional[str] = None):
        self.inputs: List[KTensor] = (inputs if isinstance(inputs, list)
                                      else [inputs])
        self.output: KTensor = outputs if not isinstance(outputs, list) \
            else outputs[0]
        self.name = name or "model"
        self.optimizer = None
        self.loss = None
        self.metrics: List[str] = []
        self.ffmodel: Optional[FFModel] = None

    @property
    def input(self):
        """Reference alias: model.input[0] is the first symbolic input."""
        return self.inputs

    # -- keras API ------------------------------------------------------
    def __call__(self, tensor):
        """Call a Model as a layer (reference nested-model examples,
        func_cifar10_cnn_nested.py: output = model2(model1(input))): the
        model's layer graph is replayed onto the new input tensor(s) and
        becomes part of the caller's graph. The SAME layer objects are
        reused, so surgery via set_weights on them still applies."""
        ts = tensor if isinstance(tensor, (list, tuple)) else [tensor]
        if len(ts) != len(self.inputs):
            raise ValueError(f"model {self.name!r} has {len(self.inputs)} "
                             f"inputs, got {len(ts)}")
        # snapshot the original wiring BEFORE re-calling mutates it
        plan = [(layer, [t.tid for t in layer.input_tensors],
                 layer.output.tid) for layer in self._topo_layers()]
        mapping = {inp.tid: t for inp, t in zip(self.inputs, ts)}
        out_tid = self.output.tid
        for layer, in_tids, o_tid in plan:
            ins = [mapping[t] for t in in_tids]
            mapping[o_tid] = layer(ins if len(ins) > 1 else ins[0])
        return mapping[out_tid]

    def compile(self, optimizer="sgd", loss="mean_squared_error",
                metrics=None):
        self.optimizer = optimizer
        # accept keras-style Loss/Metric objects (reference losses.py /
        # metrics.py classes carry a `type` string) as well as plain strings
        self.loss = getattr(loss, "type", loss)
        metrics = metrics or ["mean_squared_error"]
        self.metrics = [getattr(m, "type", m) for m in metrics]

    def _topo_layers(self) -> List[Layer]:
        order: List[Layer] = []
        seen = set()

        def visit(t: KTensor):
            if t.layer is None or id(t.layer) in seen:
                return
            seen.add(id(t.layer))
            for src in t.layer.input_tensors:
                visit(src)
            order.append(t.layer)

        visit(self.output)
        return order

    def _materialize(self, batch_size: int, seed: int = 0) -> FFModel:
        """reference _create_flexflow_layers: keras graph -> FFModel ops."""
        cfg = FFConfig(batch_size=batch_size, seed=seed)
        ff = FFModel(cfg)
        tmap: Dict[int, object] = {}
        for i, kt in enumerate(self.inputs):
            dtype = jnp.int32 if kt.dtype in ("int32", "int64") else jnp.float32
            tmap[kt.tid] = ff.create_tensor((batch_size,) + kt.shape,
                                            dtype=dtype, name=f"input_{i}")
        for layer in self._topo_layers():
            ins = [tmap[t.tid] for t in layer.input_tensors]
            tmap[layer.output.tid] = layer.materialize(ff, ins)
        self.ffmodel = ff
        self._ff_out = tmap[self.output.tid]
        return ff

    def fit(self, x, y, batch_size: int = 64, epochs: int = 1,
            callbacks=None, verbose: bool = True, seed: int = 0):
        xs = x if isinstance(x, list) else [x]
        if len(xs) != len(self.inputs):
            raise ValueError(f"model has {len(self.inputs)} inputs, got "
                             f"{len(xs)} arrays")
        ff = self._materialize(batch_size, seed)
        ff.compile(_resolve_optimizer(self.optimizer), self.loss,
                   self.metrics, final_tensor=self._ff_out)
        ff.init_layers()
        # weights stashed by Layer.set_weights before materialization
        # (the net2net student flow) land now, over the fresh init
        for layer in self._topo_layers():
            if layer._pending_weights is not None:
                k, b = layer._pending_weights
                layer.apply_weights(ff, k, b)
                layer._pending_weights = None
        inputs = {f"input_{i}": np.asarray(a) for i, a in enumerate(xs)}

        stop = {"flag": False}
        cbs = list(callbacks or [])
        for cb in cbs:
            if hasattr(cb, "on_train_begin"):
                cb.on_train_begin(self)

        def on_epoch(model, epoch, report):
            for cb in cbs:
                if hasattr(cb, "on_epoch_end"):
                    cb.on_epoch_end(self, epoch, report)
                    if getattr(cb, "stop_training", False):
                        stop["flag"] = True
            if stop["flag"]:
                raise _StopFit()

        try:
            result = ff.fit(inputs, np.asarray(y), epochs=epochs,
                            batch_size=batch_size, verbose=verbose,
                            callbacks=[on_epoch])
        except _StopFit:
            result = {"metrics": ff.perf.report()}
        for cb in cbs:
            if hasattr(cb, "on_train_end"):
                cb.on_train_end(self)   # VerifyMetrics asserts here
        return result

    def evaluate(self, x, y, batch_size: int = 64):
        xs = x if isinstance(x, list) else [x]
        if self.ffmodel is None:
            ff = self._materialize(batch_size)
            ff.compile(_resolve_optimizer(self.optimizer or "sgd"),
                       self.loss or "mean_squared_error", self.metrics or
                       ["mean_squared_error"], final_tensor=self._ff_out)
            ff.init_layers()
        preds = []
        ff = self.ffmodel
        n = len(np.asarray(y))
        for b in range(n // batch_size):
            sl = slice(b * batch_size, (b + 1) * batch_size)
            batch = {f"input_{i}": np.asarray(a)[sl]
                     for i, a in enumerate(xs)}
            preds.append(np.asarray(ff.forward_batch(batch)))
        return np.concatenate(preds, axis=0)

    def predict(self, x, batch_size: int = 64):
        xs = x if isinstance(x, list) else [x]
        n = len(np.asarray(xs[0]))
        return self.evaluate(xs, np.zeros((n, 1)), batch_size)

    def summary(self) -> str:
        lines = [f'Model: "{self.name}"']
        for layer in self._topo_layers():
            lines.append(f"  {layer.name:<28} out={layer.output.shape}")
        return "\n".join(lines)


class _StopFit(Exception):
    pass


class Sequential(Model):
    """reference: keras Sequential — layers stacked on one input."""

    def __init__(self, layers=None, name: Optional[str] = None):
        self._layers: List[Layer] = []
        self._input: Optional[KTensor] = None
        self._out: Optional[KTensor] = None
        self.name = name or "sequential"
        self.optimizer = None
        self.loss = None
        self.metrics = []
        self.ffmodel = None
        for l in layers or []:
            self.add(l)

    def add(self, layer):
        if self._input is None:
            if isinstance(layer, KTensor):
                self._input = layer
                self._out = layer
                return
            # reference seeding forms: the first layer carries
            # input_shape=(...), or the first element is itself a Model
            # (seq_mnist_cnn_nested.py stacks whole sub-models)
            shape = getattr(layer, "input_shape_arg", None)
            if shape is None and isinstance(layer, Model):
                shape = layer.inputs[0].shape
            if shape is not None:
                self._input = Input(shape)
                self._out = self._input
        if self._input is None:
            raise ValueError(
                "Sequential needs an Input first: Sequential([Input(...), "
                "Dense(...), ...]), or give the first layer an "
                "input_shape=")
        self._out = layer(self._out)
        self._layers.append(layer)

    @property
    def inputs(self):
        return [self._input]

    @inputs.setter
    def inputs(self, v):
        pass

    @property
    def output(self):
        return self._out

    @output.setter
    def output(self, v):
        pass
