"""Keras-compat loss descriptors (reference: python/flexflow/keras/losses.py
— thin classes whose `type` string selects the core loss)."""

from __future__ import annotations


class Loss:
    type: str = ""

    def __init__(self, name: str = ""):
        self.name = name or self.type


class CategoricalCrossentropy(Loss):
    type = "categorical_crossentropy"


class SparseCategoricalCrossentropy(Loss):
    type = "sparse_categorical_crossentropy"


class MeanSquaredError(Loss):
    type = "mean_squared_error"
