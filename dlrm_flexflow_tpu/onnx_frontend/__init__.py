from .model import ONNXModel

__all__ = ["ONNXModel"]
