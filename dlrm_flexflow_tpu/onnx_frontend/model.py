"""ONNX importer.

Parity with the reference ONNX frontend (reference: python/flexflow/onnx/
model.py, 128 LoC — node-by-node translation of Conv/Pool/BN/Dropout/
Flatten/Add/Concat/Gemm(Dense)/Relu/Softmax onto FFModel). The environment
has no `onnx` package, so .onnx files are parsed with a vendored
wire-compatible proto subset (onnx_subset.proto compiled by protoc);
initializer tensors are loaded as weights.
"""

from __future__ import annotations

import struct
from typing import Dict, List

import numpy as np

from ..core.model import FFModel
from . import onnx_subset_pb2 as P

_DT = {1: np.float32, 6: np.int32, 7: np.int64, 11: np.float64}


def _tensor_to_np(t) -> np.ndarray:
    shape = tuple(t.dims)
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=_DT.get(t.data_type,
                                                      np.float32))
    elif t.float_data:
        arr = np.asarray(t.float_data, np.float32)
    elif t.int64_data:
        arr = np.asarray(t.int64_data, np.int64)
    elif t.int32_data:
        arr = np.asarray(t.int32_data, np.int32)
    elif t.double_data:
        arr = np.asarray(t.double_data, np.float64)
    else:
        arr = np.zeros(shape, np.float32)
    return arr.reshape(shape) if shape else arr


def _attrs(node) -> Dict:
    out = {}
    for a in node.attribute:
        if a.type == 1:
            out[a.name] = a.f
        elif a.type == 2:
            out[a.name] = a.i
        elif a.type == 3:
            out[a.name] = a.s.decode()
        elif a.type == 6:
            out[a.name] = list(a.floats)
        elif a.type == 7:
            out[a.name] = list(a.ints)
        else:
            out[a.name] = a
    return out


class ONNXModel:
    def __init__(self, filename: str):
        self.model = P.ModelProto()
        with open(filename, "rb") as f:
            self.model.ParseFromString(f.read())
        self.graph = self.model.graph
        self.weights = {t.name: _tensor_to_np(t)
                        for t in self.graph.initializer}

    def apply(self, ff: FFModel, input_tensors: Dict[str, object]):
        """input_tensors: graph-input name -> created FFModel tensor.
        Returns (output_tensor, weight_loader)."""
        env: Dict[str, object] = dict(input_tensors)
        pending: List = []

        for i, node in enumerate(self.graph.node):
            op = node.op_type
            name = node.name or f"{op.lower()}_{i}"
            at = _attrs(node)
            ins = node.input
            if op == "Constant":
                # fold into weights so downstream consumers (Pad pads,
                # Reshape shape) resolve it exactly like an initializer —
                # exporters emit these when constant folding is off
                # (torch.onnx.export(do_constant_folding=False), tf2onnx)
                val = None
                for a in node.attribute:
                    if a.name == "value" and a.type == 4:
                        val = _tensor_to_np(a.t)
                    elif a.name == "value_ints":
                        val = np.asarray(list(a.ints), np.int64)
                    elif a.name == "value_floats":
                        val = np.asarray(list(a.floats), np.float32)
                    elif a.name == "value_int":
                        val = np.asarray(a.i, np.int64)
                    elif a.name == "value_float":
                        val = np.asarray(a.f, np.float32)
                if val is None:
                    raise NotImplementedError(
                        f"ONNX import: Constant {name!r} carries an "
                        "unsupported value attribute form")
                self.weights[node.output[0]] = val
                continue

            if op == "Gemm":
                w = self.weights[ins[1]]
                out_dim = w.shape[0] if at.get("transB", 0) else w.shape[1]
                t = ff.dense(env[ins[0]], int(out_dim),
                             use_bias=len(ins) > 2, name=name)
                kernel = w.T if at.get("transB", 0) else w
                wd = {"kernel": kernel.astype(np.float32)}
                if len(ins) > 2:
                    wd["bias"] = self.weights[ins[2]].astype(np.float32)
                pending.append((name, wd))
            elif op == "MatMul":
                w = self.weights[ins[1]]
                t = ff.dense(env[ins[0]], int(w.shape[1]), use_bias=False,
                             name=name)
                pending.append((name, {"kernel": w.astype(np.float32)}))
            elif op == "Conv":
                w = self.weights[ins[1]]
                kh, kw = at.get("kernel_shape", w.shape[2:])
                sh, sw = at.get("strides", [1, 1])
                pads = at.get("pads", [0, 0, 0, 0])
                t = ff.conv2d(env[ins[0]], int(w.shape[0]), int(kh), int(kw),
                              int(sh), int(sw), int(pads[0]), int(pads[1]),
                              use_bias=len(ins) > 2,
                              groups=int(at.get("group", 1)), name=name)
                wd = {"kernel": w.astype(np.float32)}
                if len(ins) > 2:
                    wd["bias"] = self.weights[ins[2]].astype(np.float32)
                pending.append((name, wd))
            elif op in ("MaxPool", "AveragePool"):
                kh, kw = at["kernel_shape"]
                sh, sw = at.get("strides", [1, 1])
                pads = at.get("pads", [0, 0, 0, 0])
                t = ff.pool2d(env[ins[0]], int(kh), int(kw), int(sh),
                              int(sw), int(pads[0]), int(pads[1]),
                              pool_type="max" if op == "MaxPool" else "avg",
                              name=name)
            elif op == "GlobalAveragePool":
                x = env[ins[0]]
                hw = x.shape[2]
                t = ff.pool2d(x, hw, hw, 1, 1, 0, 0, pool_type="avg",
                              name=name)
            elif op == "BatchNormalization":
                t = ff.batch_norm(env[ins[0]], relu=False, name=name)
                pending.append((name, {
                    "scale": self.weights[ins[1]].astype(np.float32),
                    "bias": self.weights[ins[2]].astype(np.float32)}))
            elif op == "Relu":
                t = ff.relu(env[ins[0]], name=name)
            elif op == "Sigmoid":
                t = ff.sigmoid(env[ins[0]], name=name)
            elif op == "Tanh":
                t = ff.tanh(env[ins[0]], name=name)
            elif op == "Elu":
                t = ff.elu(env[ins[0]], name=name)
            elif op == "Softmax":
                t = ff.softmax(env[ins[0]], name=name)
            elif op == "Dropout":
                t = ff.dropout(env[ins[0]], float(at.get("ratio", 0.5)),
                               name=name)
            elif op == "Flatten":
                t = ff.flat(env[ins[0]], name=name)
            elif op == "Reshape":
                shape = self.weights[ins[1]].astype(int).tolist()
                x = env[ins[0]]
                if shape[0] in (-1, 0):
                    shape[0] = x.shape[0]
                if -1 in shape:
                    import math
                    known = -np.prod([s for s in shape if s != -1])
                    shape[shape.index(-1)] = int(math.prod(x.shape) / -known)
                t = ff.reshape(x, tuple(shape), name=name)
            elif op == "Add":
                t = ff.add(env[ins[0]], env[ins[1]], name=name)
            elif op == "Sub":
                t = ff.subtract(env[ins[0]], env[ins[1]], name=name)
            elif op == "Mul":
                t = ff.multiply(env[ins[0]], env[ins[1]], name=name)
            elif op == "Concat":
                t = ff.concat([env[x] for x in ins],
                              int(at.get("axis", 1)), name=name)
            elif op == "Transpose":
                t = ff.transpose(env[ins[0]], name=name)
            elif op == "Pad":
                # reference handlePad is an explicit pass-through
                # (python/flexflow/onnx/model.py:107-111: "pass-through
                # pad") — exporters emit standalone Pads whose padding the
                # following Conv/Pool already carries. Only an all-zero
                # pad may pass silently; dropping REAL padding would
                # corrupt numerics without an error
                pads = list(at.get("pads", []))
                if ins[1:] and ins[1]:  # "" = absent optional input
                    if ins[1] in self.weights:
                        pads = self.weights[ins[1]].astype(
                            int).ravel().tolist()
                    else:
                        # opset>=11 pads produced by a node, not an
                        # initializer: unresolvable here — refusing keeps
                        # the invariant that nonzero pads NEVER pass
                        # silently (an all-zero default would)
                        raise NotImplementedError(
                            f"ONNX import: Pad {name!r} takes pads from "
                            f"node output {ins[1]!r}, which cannot be "
                            "resolved to constants at import time")
                if any(int(p) != 0 for p in pads):
                    raise NotImplementedError(
                        f"ONNX import: standalone Pad {name!r} carries "
                        f"nonzero pads {pads}; fold it into the following "
                        "Conv/Pool's pads attribute")
                t = env[ins[0]]
            elif op == "Identity":
                t = env[ins[0]]
            else:
                raise NotImplementedError(f"ONNX import: unsupported op "
                                          f"{op}")
            for o in node.output:
                env[o] = t

        out_name = self.graph.output[0].name
        out = env[out_name]

        def weight_loader(compiled_model):
            from ..utils.checkpoint import set_weights
            for opname, wd in pending:
                have = compiled_model.params.get(opname, {})
                set_weights(compiled_model, opname,
                            {k: v for k, v in wd.items() if k in have})

        return out, weight_loader

    def input_shapes(self) -> Dict[str, tuple]:
        out = {}
        init_names = set(self.weights)
        for vi in self.graph.input:
            if vi.name in init_names:
                continue
            dims = tuple(d.dim_value
                         for d in vi.type.tensor_type.shape.dim)
            out[vi.name] = dims
        return out
