"""Quantized row-block storage for the serving tier.

A :class:`QuantTable` is the in-memory form of one quantized table (or
one shard's row block of it): the codes at the storage dtype plus one
fp32 scale per row. It is what an :class:`~..serve.shardtier.
EmbeddingShard` holds under an int8/fp8 policy (the rows-per-MB win),
what its lookups ship to the ranker (payload bytes at the storage
width; the ranker dequantizes), and what the warm cache persists
(codes + scales round-trip npz bit-exactly).

Writes quantize per row (`set_rows`) — each row's scale is recomputed
from the incoming fp32 values, independent of its neighbours, so a
delta publish routed across shards produces the same stored rows on
every shard that owns them.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .codec import (decode_q, dequantize_rows_np, encode_q,
                    quantize_rows_np)


class QuantTable:
    """(rows, dim) quantized storage: ``q`` codes + ``(rows,)`` fp32
    scales. Not thread-safe — callers hold their own lock (the shard's
    lock already serializes all block access)."""

    __slots__ = ("q", "scales", "dtype")

    def __init__(self, q: np.ndarray, scales: np.ndarray, dtype: str):
        self.q = q
        self.scales = np.ascontiguousarray(scales, np.float32)
        self.dtype = dtype

    @classmethod
    def from_dense(cls, arr: np.ndarray, dtype: str) -> "QuantTable":
        arr = np.asarray(arr, np.float32)
        q, s = quantize_rows_np(arr.reshape(-1, arr.shape[-1]), dtype)
        return cls(q, s, dtype)

    # --- geometry / accounting ----------------------------------------
    @property
    def shape(self) -> Tuple[int, int]:
        return tuple(self.q.shape)

    @property
    def nbytes(self) -> int:
        """Stored bytes: codes + scales — what ``hbm_bytes``/rows-per-MB
        report (the fp32 equivalent is 4x the code bytes)."""
        return int(np.asarray(self.q).view(np.uint8).nbytes
                   + self.scales.nbytes)

    # --- reads ---------------------------------------------------------
    def take(self, idx: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """The QUANTIZED row payload for ``idx`` — what ships to the
        ranker: (codes, scales)."""
        idx = np.asarray(idx, np.int64)
        return self.q[idx], self.scales[idx]

    def dense_rows(self, idx: np.ndarray) -> np.ndarray:
        q, s = self.take(idx)
        return dequantize_rows_np(q, s, self.dtype)

    def to_dense(self) -> np.ndarray:
        return dequantize_rows_np(self.q, self.scales, self.dtype)

    # --- writes --------------------------------------------------------
    def set_rows(self, idx: np.ndarray, vals: np.ndarray) -> None:
        """Quantize-and-store fp32 rows at ``idx`` (a delta publish's
        slice). Per-row scales — neighbours are untouched."""
        idx = np.asarray(idx, np.int64)
        q, s = quantize_rows_np(np.asarray(vals, np.float32), self.dtype)
        self.q[idx] = q
        self.scales[idx] = s

    def set_all(self, arr: np.ndarray) -> None:
        q, s = quantize_rows_np(
            np.asarray(arr, np.float32).reshape(-1, arr.shape[-1]),
            self.dtype)
        self.q = q
        self.scales = s

    def copy(self) -> "QuantTable":
        return QuantTable(self.q.copy(), self.scales.copy(), self.dtype)

    # --- npz round trip (warm cache) -----------------------------------
    def encoded(self) -> np.ndarray:
        """npz-portable codes (fp8 bit patterns as uint8)."""
        return encode_q(self.q, self.dtype)

    @classmethod
    def from_encoded(cls, raw: np.ndarray, scales: np.ndarray,
                     dtype: str) -> "QuantTable":
        return cls(decode_q(raw, dtype), scales, dtype)


def dequantize_payload(q_rows, scales, dtype: str) -> np.ndarray:
    """The RANKER-boundary dequant: turn a shipped (codes, scales)
    lookup payload back into fp32 rows."""
    return dequantize_rows_np(q_rows, scales, dtype)
