"""The per-table storage policy and its byte accounting.

A :class:`QuantPolicy` describes how ONE embedding table's rows are
stored: element dtype, the row-wise scale layout, and the update rule.
It is carried per op by ``ParallelConfig.quant_dtype``/``quant_update``
(strategy files round-trip it; legacy files stay byte-identical) with
``FFConfig.emb_dtype``/``emb_update_rule`` as the model-wide default —
the same raw-strategy-overrides-config precedence the row-shard fields
use. Everything that prices table bytes (``hbm_footprint_report``,
``cost_model`` exchange payloads, ``serving_footprint``, shardcheck
FLX503/513, the delta publisher, the serving caches) resolves the policy
through :func:`effective_policy` so they can never disagree on a row's
size.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

DTYPES = ("fp32", "bf16", "int8", "fp8")
UPDATE_RULES = ("master_weight", "stochastic_rounding")

# one fp32 scale per stored row (symmetric: zero-point is structurally 0,
# so only the scale is stored — Guan 2019's row-wise min/max layout
# degenerates to this for symmetric codes)
SCALE_BYTES = 4.0

_ITEMSIZE = {"fp32": 4.0, "bf16": 2.0, "int8": 1.0, "fp8": 1.0}


@dataclass(frozen=True)
class QuantPolicy:
    """How one table's rows are stored. ``dtype`` is the element storage
    type; quantized dtypes (int8/fp8) carry one fp32 scale per row;
    ``update_rule`` picks master-weight (exact, fp32 master beside the
    optimizer state) vs stochastic-rounding (no master, re-quantize
    after every update) semantics."""

    dtype: str = "fp32"
    update_rule: str = "master_weight"
    scale_block: str = "row"     # row-wise scales are the only layout

    def __post_init__(self):
        if self.dtype not in DTYPES:
            raise ValueError(
                f"invalid quant dtype {self.dtype!r} (expected one of "
                f"{DTYPES})")
        if self.update_rule not in UPDATE_RULES:
            raise ValueError(
                f"invalid quant update rule {self.update_rule!r} "
                f"(expected one of {UPDATE_RULES})")
        if self.scale_block != "row":
            raise ValueError(
                f"invalid scale layout {self.scale_block!r} (row-wise "
                f"scales are the only supported layout)")

    @property
    def is_quantized(self) -> bool:
        """True for the scaled integer/float8 codes (int8/fp8) — the
        dtypes that carry a per-row scale."""
        return self.dtype in ("int8", "fp8")

    @property
    def is_default(self) -> bool:
        return self.dtype == "fp32" and self.update_rule == "master_weight"

    @property
    def itemsize(self) -> float:
        return _ITEMSIZE[self.dtype]

    def row_bytes(self, dim: int) -> float:
        """Stored bytes of one ``dim``-wide row, scale included."""
        b = dim * self.itemsize
        if self.is_quantized:
            b += SCALE_BYTES
        return b

    def table_bytes(self, rows: int, dim: int) -> float:
        return rows * self.row_bytes(dim)


FP32 = QuantPolicy()


def policy_from_pc(pc) -> Optional[QuantPolicy]:
    """The policy a strategy entry requests, or None when the entry is
    silent (empty ``quant_dtype`` = inherit the model default)."""
    if pc is None:
        return None
    dt = getattr(pc, "quant_dtype", "")
    if not dt:
        return None
    return QuantPolicy(dt, getattr(pc, "quant_update", "master_weight")
                       or "master_weight")


def policy_from_config(config) -> Optional[QuantPolicy]:
    """The model-wide default policy from FFConfig (``--emb-dtype`` /
    ``--emb-update-rule``), or None when unset/fp32-default."""
    dt = getattr(config, "emb_dtype", "fp32") or "fp32"
    ur = getattr(config, "emb_update_rule",
                 "master_weight") or "master_weight"
    pol = QuantPolicy(dt, ur)
    return None if pol.is_default else pol


def effective_policy(op, pc=None) -> QuantPolicy:
    """THE policy resolution every byte-accounting and storage site
    uses: an explicit strategy entry wins, else the policy compile()
    resolved onto the op (``op._quant_policy``), else the model-config
    default, else fp32. ``pc`` lets search-time callers price a
    CANDIDATE strategy the op was never configured with."""
    pol = policy_from_pc(pc)
    if pol is not None:
        return pol
    pol = getattr(op, "_quant_policy", None)
    if pol is not None:
        return pol
    model = getattr(op, "model", None)
    if model is not None:
        pol = policy_from_config(getattr(model, "config", None))
        if pol is not None:
            return pol
    return FP32


def param_storage_bytes(op, pc, shapes) -> float:
    """Stored bytes of ``op``'s parameter shapes under its effective
    policy: table params (``kernel``/``hot_kernel`` of embedding ops)
    at the policy's row bytes, everything else at its declared dtype.
    ``shapes`` maps param name -> (sharded) shape — pass
    ``op.param_shard_shapes(pc, ndev)`` for per-device residency or
    ``{n: d.shape for n, d in op.param_defs().items()}`` for the whole
    table. Under ``master_weight`` the fp32 master slab is NOT counted
    here: in the production layout it lives host-side beside the
    optimizer state (the same place ZCM tables live), so HBM holds only
    the quantized rows."""
    import numpy as np
    pol = effective_policy(op, pc) if hasattr(op, "host_lookup") else None
    defs = op.param_defs()
    total = 0.0
    for pname, shape in shapes.items():
        if pol is not None and not pol.is_default \
                and pname in ("kernel", "hot_kernel"):
            total += table_storage_bytes(shape, pol)
            continue
        d = defs.get(pname)
        isz = float(np.dtype(d.dtype).itemsize) if d is not None else 4.0
        total += math.prod(shape) * isz
    return total


def table_storage_bytes(shape, policy: Optional[QuantPolicy]) -> float:
    """Stored bytes of a table-shaped parameter under ``policy``: the
    last axis is the row width, everything before it multiplies into the
    row count (stacked (T, rows, d) tables count T*rows scales)."""
    if policy is None:
        policy = FP32
    if not shape:
        return policy.itemsize
    dim = int(shape[-1])
    rows = int(math.prod(shape[:-1])) if len(shape) > 1 else 1
    return policy.table_bytes(rows, dim)
