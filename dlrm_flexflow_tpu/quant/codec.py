"""Row-wise symmetric quantize/dequantize codecs (numpy + jax).

One codec, two hosts: the numpy half runs on storage boundaries (delta
publishes, shard-tier blocks, warm-cache entries, the serving row
cache), the jax half runs inside the jitted train step (init-time
quantize + the stochastic-rounding re-quantize hook) and in the Pallas
gather's reference oracle.

Layout: the LAST axis is the row; every leading axis multiplies into
the row count (a stacked (T, rows, d) table carries T*rows scales).
Codes are symmetric — ``scale = amax / QMAX`` per row, zero-point 0 —
so the row max always maps to the top code. Consequence (pinned in
tests/test_quant.py): re-quantizing a dequantized payload reproduces
the CODES bit-exactly (the recomputed scale can differ from the stored
one by at most 1 ulp, which moves ``q*s/s'`` by ~1e-5 of a code — far
from any rounding boundary), so fp32 arrays can flow between
subsystems while quantized storage round-trips losslessly.

fp8 uses the e4m3 format (max 448) via ml_dtypes; its codes are stored
on disk as uint8 bit patterns (``encode_q``/``decode_q``) because npz
cannot serialize the extension dtype portably.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# quantized-code ranges: int8 symmetric uses +-127 (not -128: symmetry
# keeps dequantization zero-point-free); fp8 e4m3's largest finite is 448
_QMAX = {"int8": 127.0, "fp8": 448.0}


def _f8_dtype():
    import ml_dtypes
    return ml_dtypes.float8_e4m3fn


def _row_amax_np(arr: np.ndarray) -> np.ndarray:
    return np.max(np.abs(arr), axis=-1)


def _scales_from_amax(amax, qmax: float):
    # all-zero rows get scale 0 (codes are 0, dequant is exact 0)
    return np.where(amax > 0, amax / qmax, 0.0).astype(np.float32)


def quantize_rows_np(arr: np.ndarray, dtype: str
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """fp32 rows -> (codes, scales). ``codes`` has ``arr``'s shape in
    the storage dtype (int8, or ml_dtypes float8_e4m3fn); ``scales`` is
    fp32 with the leading (row) shape."""
    if dtype not in _QMAX:
        raise ValueError(f"quantize_rows_np: {dtype!r} is not a "
                         f"quantized dtype (int8/fp8)")
    arr = np.asarray(arr, np.float32)
    qmax = _QMAX[dtype]
    scales = _scales_from_amax(_row_amax_np(arr), qmax)
    safe = np.where(scales > 0, scales, 1.0)[..., None]
    scaled = arr / safe
    if dtype == "int8":
        q = np.clip(np.rint(scaled), -127, 127).astype(np.int8)
    else:
        q = np.clip(scaled, -qmax, qmax).astype(_f8_dtype())
    return q, scales


def dequantize_rows_np(q: np.ndarray, scales: np.ndarray,
                       dtype: str) -> np.ndarray:
    """(codes, scales) -> fp32 rows."""
    if dtype not in _QMAX:
        raise ValueError(f"dequantize_rows_np: {dtype!r} is not a "
                         f"quantized dtype (int8/fp8)")
    return (np.asarray(q, np.float32)
            * np.asarray(scales, np.float32)[..., None])


def fake_quant_np(arr: np.ndarray, dtype: str) -> np.ndarray:
    """Quantize-dequantize in one hop: the exact fp32 image of the
    stored representation (what the master-resident simulated path
    keeps as the parameter value). fp32 is the identity; bf16 is a
    precision round-trip with no scales."""
    if dtype == "fp32":
        return np.asarray(arr, np.float32)
    if dtype == "bf16":
        import ml_dtypes
        return np.asarray(arr, np.float32).astype(
            ml_dtypes.bfloat16).astype(np.float32)
    q, s = quantize_rows_np(arr, dtype)
    return dequantize_rows_np(q, s, dtype)


def fake_quant_stochastic_np(arr: np.ndarray, dtype: str,
                             rng: np.random.RandomState) -> np.ndarray:
    """Numpy twin of :func:`fake_quant_stochastic` for HOST-resident
    tables (the touched-rows re-quantize after a host scatter)."""
    if dtype != "int8":
        return fake_quant_np(arr, dtype)
    arr = np.asarray(arr, np.float32)
    amax = _row_amax_np(arr)
    scales = _scales_from_amax(amax, _QMAX["int8"])
    safe = np.where(scales > 0, scales, 1.0)[..., None]
    u = rng.random_sample(arr.shape).astype(np.float32)
    q = np.clip(np.floor(arr / safe + u), -127, 127)
    return q * scales[..., None]


# --- npz-portable code encoding ---------------------------------------
def encode_q(q: np.ndarray, dtype: str) -> np.ndarray:
    """Codes -> an npz-portable array (fp8 bit patterns as uint8)."""
    if dtype == "fp8":
        return np.ascontiguousarray(q).view(np.uint8)
    return np.ascontiguousarray(q, np.int8)


def decode_q(raw: np.ndarray, dtype: str) -> np.ndarray:
    """Inverse of :func:`encode_q`."""
    if dtype == "fp8":
        return np.ascontiguousarray(raw, np.uint8).view(_f8_dtype())
    return np.ascontiguousarray(raw, np.int8)


# --- scale validation (the serving reject-with-reason gate) -----------
def validate_scales(key: str, scales: np.ndarray,
                    bound: Optional[float] = None) -> None:
    """Reject garbage scales BEFORE they are served: every scale must be
    finite, non-negative, and (when the payload recorded its publish-time
    bound) at most a whisker above it. A corrupt scale is silent score
    garbage — amplitudes blow up by the corruption factor with no NaN to
    trip the anomaly sentinel — so the load path must refuse the payload
    with a reason, not serve it (FF_FAULT_QUANT_SCALE drills this)."""
    s = np.asarray(scales)
    if s.size == 0:
        return
    if not np.all(np.isfinite(s)):
        raise ValueError(
            f"quantized payload {key!r}: non-finite row scale(s) — "
            f"corrupt scales would serve garbage rows; payload rejected")
    if float(s.min()) < 0:
        raise ValueError(
            f"quantized payload {key!r}: negative row scale "
            f"{float(s.min()):g} — symmetric codes never store one; "
            f"payload rejected")
    if bound is not None and float(s.max()) > float(bound) * 1.001:
        raise ValueError(
            f"quantized payload {key!r}: max row scale "
            f"{float(s.max()):g} exceeds the publish-time bound "
            f"{float(bound):g} — scales corrupted after publish; "
            f"payload rejected")


# --- jax half ---------------------------------------------------------
def fake_quant(x, dtype: str):
    """jnp quantize-dequantize (nearest), same semantics as
    :func:`fake_quant_np`. Elementwise + a last-axis reduce — safe under
    any GSPMD sharding of the leading (row) axes."""
    import jax.numpy as jnp
    if dtype == "fp32":
        return x.astype(jnp.float32)
    if dtype == "bf16":
        return x.astype(jnp.bfloat16).astype(jnp.float32)
    qmax = _QMAX[dtype]
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 0.0)
    safe = jnp.where(scale > 0, scale, 1.0)
    if dtype == "int8":
        q = jnp.clip(jnp.round(xf / safe), -127, 127)
    else:
        q = jnp.clip(xf / safe, -qmax, qmax).astype(
            jnp.float8_e4m3fn).astype(jnp.float32)
    return q * scale


def fake_quant_stochastic(x, dtype: str, key):
    """jnp quantize-dequantize with STOCHASTIC rounding for the integer
    code (int8): ``floor(x/s + u)``, u ~ U[0,1) — unbiased, so repeated
    small updates accumulate in expectation instead of rounding away
    (the classic low-precision-training fix). bf16/fp8 round to nearest
    (their rounding error is already below the update noise at these
    widths); fp32 is the identity."""
    import jax.numpy as jnp
    if dtype != "int8":
        return fake_quant(x, dtype)
    import jax
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / _QMAX["int8"], 0.0)
    safe = jnp.where(scale > 0, scale, 1.0)
    u = jax.random.uniform(key, xf.shape, jnp.float32)
    q = jnp.clip(jnp.floor(xf / safe + u), -127, 127)
    return q * scale
