"""Quantized embedding storage: int8/fp8 rows with row-wise scales as a
per-table policy.

Embedding tables dominate DLRM memory AND bytes-moved (Naumov 2019);
low-precision row storage with row-wise scales is the standard
production answer (Guan 2019, post-training 4/8-bit embedding tables).
A :class:`QuantPolicy` is a per-table STORAGE policy — dtype in
{fp32, bf16, int8, fp8}, row-wise symmetric scales (zero-point 0), and
an update rule — threaded through ``ParallelConfig``/``strategy_io`` so
the MCMC search, shardcheck, and the serving tier all price the same
row bytes. One policy multiplies against nearly every subsystem:

- HBM: int8 rows cut per-table residency ~4x
  (``simulator.hbm_footprint_report`` / shardcheck FLX503);
- exchange: the row payloads of the row-sharded all-to-all ship at the
  storage width (``cost_model`` / FLX513 predicted bytes);
- freshness: delta publishes ship ``int8 rows + fp32 scales``
  (``utils/delta.py``), shrinking the measured ~150 KB publish ~4x;
- serving: ``EmbeddingCache`` / the shard tier / the warm cache hold
  ~4x more rows per MB, dequantizing at the RANKER boundary.

Execution model (two halves, one semantics):

- **TPU storage path**: the Pallas gather kernel dequantizes int8/fp8
  row tiles in VMEM (scales ride beside the row tiles via scalar
  prefetch, ``ops/pallas/embedding_kernel.embedding_bag_quant``).
- **Portable (XLA / CPU) path**: *master-resident simulated
  quantization* — the trainable parameter remains an fp32 master whose
  values are exact dequantizations of the quantized representation, so
  every existing update path (replicated / row-sharded / hybrid,
  SGD / momentum / Adam, superstep scan) runs unchanged while storage
  boundaries (checkpoints' delta publishes, serving tables, caches)
  ship true ``q + scale`` payloads bit-exactly.

Update rules:

- ``master_weight``: updates apply to the fp32 master — BIT-IDENTICAL
  to the fp32-accumulator reference by construction (pinned by
  tests/test_quant.py across the optimizer x placement matrix). In the
  production TPU layout the master slab lives host-side beside the
  optimizer state; HBM holds the quantized rows.
- ``stochastic_rounding``: no master — the table re-quantizes after
  every update with stochastic rounding (unbiased; deterministic per
  step via the step-folded RNG), trading exactness for the full
  training-time memory win.

Quantize(dequantize(q, s)) == (q, s) for the row-wise symmetric codec
(the row max always maps to the top code), so re-quantizing a
dequantized payload is IDEMPOTENT — the property that lets fp32 arrays
flow between subsystems while quantized storage round-trips bit-exactly
(pinned in tests/test_quant.py).
"""

from .policy import (DTYPES, SCALE_BYTES, UPDATE_RULES, QuantPolicy,
                     effective_policy, policy_from_pc, table_storage_bytes)
from .codec import (decode_q, dequantize_rows_np, encode_q, fake_quant,
                    fake_quant_np, fake_quant_stochastic,
                    fake_quant_stochastic_np, quantize_rows_np,
                    validate_scales)

__all__ = [
    "DTYPES", "UPDATE_RULES", "SCALE_BYTES", "QuantPolicy",
    "policy_from_pc", "effective_policy", "table_storage_bytes",
    "quantize_rows_np", "dequantize_rows_np", "fake_quant_np",
    "fake_quant", "fake_quant_stochastic", "fake_quant_stochastic_np",
    "encode_q", "decode_q", "validate_scales",
]
