"""InceptionV3 (reference: examples/cpp/InceptionV3/inception.cc ~330 LoC —
the concat-heavy model used to show hybrid SOAP strategies). Full v3
topology: stem, 3×InceptionA, InceptionB, 4×InceptionC, InceptionD,
2×InceptionE, global pool, fc, softmax. NCHW."""

from __future__ import annotations

from ..core.model import FFModel


def _conv_bn(model, t, ch, kh, kw, sh, sw, ph, pw, name):
    t = model.conv2d(t, ch, kh, kw, sh, sw, ph, pw, use_bias=False,
                     name=f"{name}_conv")
    return model.batch_norm(t, relu=True, name=f"{name}_bn")


def _inception_a(model, t, pool_ch, name):
    b1 = _conv_bn(model, t, 64, 1, 1, 1, 1, 0, 0, f"{name}_b1")
    b2 = _conv_bn(model, t, 48, 1, 1, 1, 1, 0, 0, f"{name}_b2a")
    b2 = _conv_bn(model, b2, 64, 5, 5, 1, 1, 2, 2, f"{name}_b2b")
    b3 = _conv_bn(model, t, 64, 1, 1, 1, 1, 0, 0, f"{name}_b3a")
    b3 = _conv_bn(model, b3, 96, 3, 3, 1, 1, 1, 1, f"{name}_b3b")
    b3 = _conv_bn(model, b3, 96, 3, 3, 1, 1, 1, 1, f"{name}_b3c")
    b4 = model.pool2d(t, 3, 3, 1, 1, 1, 1, pool_type="avg",
                      name=f"{name}_pool")
    b4 = _conv_bn(model, b4, pool_ch, 1, 1, 1, 1, 0, 0, f"{name}_b4")
    return model.concat([b1, b2, b3, b4], axis=1, name=f"{name}_cat")


def _inception_b(model, t, name):
    b1 = _conv_bn(model, t, 384, 3, 3, 2, 2, 0, 0, f"{name}_b1")
    b2 = _conv_bn(model, t, 64, 1, 1, 1, 1, 0, 0, f"{name}_b2a")
    b2 = _conv_bn(model, b2, 96, 3, 3, 1, 1, 1, 1, f"{name}_b2b")
    b2 = _conv_bn(model, b2, 96, 3, 3, 2, 2, 0, 0, f"{name}_b2c")
    b3 = model.pool2d(t, 3, 3, 2, 2, 0, 0, name=f"{name}_pool")
    return model.concat([b1, b2, b3], axis=1, name=f"{name}_cat")


def _inception_c(model, t, ch7, name):
    b1 = _conv_bn(model, t, 192, 1, 1, 1, 1, 0, 0, f"{name}_b1")
    b2 = _conv_bn(model, t, ch7, 1, 1, 1, 1, 0, 0, f"{name}_b2a")
    b2 = _conv_bn(model, b2, ch7, 1, 7, 1, 1, 0, 3, f"{name}_b2b")
    b2 = _conv_bn(model, b2, 192, 7, 1, 1, 1, 3, 0, f"{name}_b2c")
    b3 = _conv_bn(model, t, ch7, 1, 1, 1, 1, 0, 0, f"{name}_b3a")
    b3 = _conv_bn(model, b3, ch7, 7, 1, 1, 1, 3, 0, f"{name}_b3b")
    b3 = _conv_bn(model, b3, ch7, 1, 7, 1, 1, 0, 3, f"{name}_b3c")
    b3 = _conv_bn(model, b3, ch7, 7, 1, 1, 1, 3, 0, f"{name}_b3d")
    b3 = _conv_bn(model, b3, 192, 1, 7, 1, 1, 0, 3, f"{name}_b3e")
    b4 = model.pool2d(t, 3, 3, 1, 1, 1, 1, pool_type="avg",
                      name=f"{name}_pool")
    b4 = _conv_bn(model, b4, 192, 1, 1, 1, 1, 0, 0, f"{name}_b4")
    return model.concat([b1, b2, b3, b4], axis=1, name=f"{name}_cat")


def _inception_d(model, t, name):
    b1 = _conv_bn(model, t, 192, 1, 1, 1, 1, 0, 0, f"{name}_b1a")
    b1 = _conv_bn(model, b1, 320, 3, 3, 2, 2, 0, 0, f"{name}_b1b")
    b2 = _conv_bn(model, t, 192, 1, 1, 1, 1, 0, 0, f"{name}_b2a")
    b2 = _conv_bn(model, b2, 192, 1, 7, 1, 1, 0, 3, f"{name}_b2b")
    b2 = _conv_bn(model, b2, 192, 7, 1, 1, 1, 3, 0, f"{name}_b2c")
    b2 = _conv_bn(model, b2, 192, 3, 3, 2, 2, 0, 0, f"{name}_b2d")
    b3 = model.pool2d(t, 3, 3, 2, 2, 0, 0, name=f"{name}_pool")
    return model.concat([b1, b2, b3], axis=1, name=f"{name}_cat")


def _inception_e(model, t, name):
    b1 = _conv_bn(model, t, 320, 1, 1, 1, 1, 0, 0, f"{name}_b1")
    b2 = _conv_bn(model, t, 384, 1, 1, 1, 1, 0, 0, f"{name}_b2a")
    b2a = _conv_bn(model, b2, 384, 1, 3, 1, 1, 0, 1, f"{name}_b2b")
    b2b = _conv_bn(model, b2, 384, 3, 1, 1, 1, 1, 0, f"{name}_b2c")
    b2 = model.concat([b2a, b2b], axis=1, name=f"{name}_b2cat")
    b3 = _conv_bn(model, t, 448, 1, 1, 1, 1, 0, 0, f"{name}_b3a")
    b3 = _conv_bn(model, b3, 384, 3, 3, 1, 1, 1, 1, f"{name}_b3b")
    b3a = _conv_bn(model, b3, 384, 1, 3, 1, 1, 0, 1, f"{name}_b3c")
    b3b = _conv_bn(model, b3, 384, 3, 1, 1, 1, 1, 0, f"{name}_b3d")
    b3 = model.concat([b3a, b3b], axis=1, name=f"{name}_b3cat")
    b4 = model.pool2d(t, 3, 3, 1, 1, 1, 1, pool_type="avg",
                      name=f"{name}_pool")
    b4 = _conv_bn(model, b4, 192, 1, 1, 1, 1, 0, 0, f"{name}_b4")
    return model.concat([b1, b2, b3, b4], axis=1, name=f"{name}_cat")


def build_inception_v3(model: FFModel, num_classes: int = 1000,
                       image_hw: int = 299):
    batch = model.config.batch_size
    x = model.create_tensor((batch, 3, image_hw, image_hw), name="image")
    t = _conv_bn(model, x, 32, 3, 3, 2, 2, 0, 0, "stem1")
    t = _conv_bn(model, t, 32, 3, 3, 1, 1, 0, 0, "stem2")
    t = _conv_bn(model, t, 64, 3, 3, 1, 1, 1, 1, "stem3")
    t = model.pool2d(t, 3, 3, 2, 2, 0, 0, name="stem_pool1")
    t = _conv_bn(model, t, 80, 1, 1, 1, 1, 0, 0, "stem4")
    t = _conv_bn(model, t, 192, 3, 3, 1, 1, 0, 0, "stem5")
    t = model.pool2d(t, 3, 3, 2, 2, 0, 0, name="stem_pool2")
    t = _inception_a(model, t, 32, "mix0")
    t = _inception_a(model, t, 64, "mix1")
    t = _inception_a(model, t, 64, "mix2")
    t = _inception_b(model, t, "mix3")
    t = _inception_c(model, t, 128, "mix4")
    t = _inception_c(model, t, 160, "mix5")
    t = _inception_c(model, t, 160, "mix6")
    t = _inception_c(model, t, 192, "mix7")
    t = _inception_d(model, t, "mix8")
    t = _inception_e(model, t, "mix9")
    t = _inception_e(model, t, "mix10")
    hw = t.shape[2]
    t = model.pool2d(t, hw, hw, 1, 1, 0, 0, pool_type="avg", name="gap")
    t = model.flat(t, name="flat")
    t = model.dense(t, num_classes, name="fc")
    out = model.softmax(t, name="prob")
    return {"image": (batch, 3, image_hw, image_hw)}, out
