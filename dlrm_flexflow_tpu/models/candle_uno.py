"""CANDLE Uno (reference: examples/cpp/candle_uno/candle_uno.cc ~400 LoC —
multi-tower MLP: per-feature-set towers built by build_feature_model, concat,
deep top MLP with residual option, 1-output regression;
candle_uno.cc:115-126)."""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..core.model import FFModel

# reference defaults (candle_uno.cc DefaultConfig / feature shapes)
DEFAULT_FEATURE_SHAPES = {"dose": 1, "cell.rnaseq": 942, "drug.descriptors": 5270,
                          "drug.fingerprints": 2048}
DEFAULT_INPUT_FEATURES = ["dose1", "dose2", "cell.rnaseq",
                          "drug1.descriptors", "drug1.fingerprints",
                          "drug2.descriptors", "drug2.fingerprints"]
DENSE_LAYERS = [1000, 1000, 1000]
DENSE_FEATURE_LAYERS = [1000, 1000, 1000]


def _feature_model(model, t, layers, prefix):
    """reference candle_uno.cc build_feature_model: MLP tower."""
    for i, w in enumerate(layers):
        t = model.dense(t, w, activation="relu", name=f"{prefix}_fc{i}")
    return t


def build_candle_uno(model: FFModel,
                     feature_shapes: Dict[str, int] = None,
                     input_features: List[str] = None,
                     dense_layers: List[int] = None,
                     dense_feature_layers: List[int] = None):
    feature_shapes = feature_shapes or DEFAULT_FEATURE_SHAPES
    input_features = input_features or DEFAULT_INPUT_FEATURES
    dense_layers = dense_layers or DENSE_LAYERS
    dense_feature_layers = dense_feature_layers or DENSE_FEATURE_LAYERS
    batch = model.config.batch_size

    # one shared tower per feature *type*, applied to each input feature of
    # that type (reference builds feature models keyed by shape name)
    inputs = {}
    towers = []
    for feat in input_features:
        base = feat
        for k in feature_shapes:
            if feat == k or (feat[:-1].rstrip(".") in k) or k in feat:
                base = k
        # normalize names like drug1.descriptors -> drug.descriptors
        key = next((k for k in feature_shapes if
                    feat.replace("1", "").replace("2", "") == k), base)
        dim = feature_shapes.get(key) or feature_shapes[base]
        x = model.create_tensor((batch, dim), name=feat)
        inputs[feat] = (batch, dim)
        if dim == 1:
            towers.append(x)  # dose inputs go straight to concat
        else:
            towers.append(_feature_model(model, x, dense_feature_layers,
                                         f"tower_{feat.replace('.', '_')}"))
    merged = model.concat(towers, axis=1, name="uno_concat")
    t = merged
    for i, w in enumerate(dense_layers):
        t = model.dense(t, w, activation="relu", name=f"top_fc{i}")
    out = model.dense(t, 1, name="uno_out")
    return inputs, out
