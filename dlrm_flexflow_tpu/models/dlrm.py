"""DLRM — the flagship model.

Parity with the reference DLRM app (reference: examples/cpp/DLRM/dlrm.cc,
642 LoC): per-table embedding bags, bottom MLP over dense features, feature
interaction (`interact_features`, dlrm.cc:49-65 — "cat" implemented, "dot"
left unimplemented there; we implement BOTH, the dot path exercising the
fork's 3-D batch ops Reshape/Transpose/BatchMatmul), top MLP with sigmoid
head, MSE loss — and the reference's run configs (run_random.sh,
run_criteo_kaggle.sh).

TPU-native: embeddings fuse by default (`fuse_embeddings=True`) — uniform
tables stack into one (T, rows, dim) parameter sharded on the table dim;
non-uniform tables (Criteo-Kaggle) concatenate row-wise into one
(sum_rows, dim) parameter that is row-block-sharded. Both are the GSPMD
form of the reference strategy "each embedding whole on one device"
(dlrm_strategy.cc:252-256); the batch↔table all-to-all the reference got
from Legion DMA is emitted by XLA from the sharding constraints. MLPs run
data-parallel, matmuls in bfloat16 on the MXU. Pass
`fuse_embeddings=False` for the per-table layout (emb_0..emb_N parameter
names — needed to resume checkpoints written by per-table builds).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from ..config import FFConfig
from ..core.model import FFModel
from ..core.initializers import UniformInitializer
from ..parallel.pconfig import ParallelConfig, StrategyMap


@dataclass
class DLRMConfig:
    """Reference DLRMConfig + arch flags (dlrm.cc:201-264):
    --arch-embedding-size dash-separated rows per table, --embedding-bag-size,
    --arch-sparse-feature-size, --arch-mlp-bot / --arch-mlp-top,
    --arch-interaction-op, --loss-threshold."""

    embedding_size: List[int] = field(default_factory=lambda: [4] * 8)
    embedding_bag_size: int = 1
    sparse_feature_size: int = 2
    mlp_bot: List[int] = field(default_factory=lambda: [4, 2])
    mlp_top: List[int] = field(default_factory=lambda: [8, 2])
    arch_interaction_op: str = "cat"     # "cat" | "dot"
    loss_threshold: float = 0.0
    # synthetic-data skew: zipf exponent for the categorical ids drawn
    # by synthetic_batch (0 = the legacy uniform draws, bit-compatible
    # seeds). Real traffic is zipfian; --zipf-alpha makes skewed
    # workloads reproducible in tests and benches.
    zipf_alpha: float = 0.0
    # convenience run configs
    @staticmethod
    def random_benchmark() -> "DLRMConfig":
        """run_random.sh:1-10 shapes: 8 × 1M-row × 64-d tables, bot
        64-512-512-64, top 576-1024-1024-1024-1."""
        return DLRMConfig(
            embedding_size=[1000000] * 8,
            embedding_bag_size=1,
            sparse_feature_size=64,
            mlp_bot=[64, 512, 512, 64],
            mlp_top=[576, 1024, 1024, 1024, 1],
        )

    @staticmethod
    def criteo_kaggle() -> "DLRMConfig":
        """run_criteo_kaggle.sh:1-8: 26 tables × 16-d, bot 13-512-256-64-16,
        top 224-512-256-1."""
        return DLRMConfig(
            embedding_size=[1396, 550, 2481689, 687, 20, 15, 204, 96, 14,
                            1400181, 397059, 3166985, 10, 2208, 11156, 155,
                            4, 976, 14, 1398149, 1263872, 1246444, 13107,
                            336, 101, 30],
            embedding_bag_size=1,
            sparse_feature_size=16,
            mlp_bot=[13, 512, 256, 64, 16],
            mlp_top=[224, 512, 256, 1],
        )

    @staticmethod
    def terabyte() -> "DLRMConfig":
        """Criteo-Terabyte (MLPerf DLRM) shapes: 26 tables up to ~40M rows
        × 128-d, bot 13-512-256-128, top 1024-1024-512-256-1. The driver's
        north-star config (BASELINE.md): ≥1.5× pure-DP on v5e-64."""
        return DLRMConfig(
            embedding_size=[39884406, 39043, 17289, 7420, 20263, 3, 7120,
                            1543, 63, 38532951, 2953546, 403346, 10, 2208,
                            11938, 155, 4, 976, 14, 39979771, 25641295,
                            39664984, 585935, 12972, 108, 36],
            embedding_bag_size=1,
            sparse_feature_size=128,
            mlp_bot=[13, 512, 256, 128],
            mlp_top=[1024, 1024, 512, 256, 1],
        )

    @staticmethod
    def parse_args(argv: List[str]) -> "DLRMConfig":
        cfg = DLRMConfig()
        i = 0
        while i < len(argv):
            a = argv[i]

            def take():
                nonlocal i
                i += 1
                if i >= len(argv):
                    raise ValueError(f"flag {argv[i - 1]!r} requires a value")
                return argv[i]

            if a == "--arch-embedding-size":
                cfg.embedding_size = [int(x) for x in take().split("-")]
            elif a == "--embedding-bag-size":
                cfg.embedding_bag_size = int(take())
            elif a == "--arch-sparse-feature-size":
                cfg.sparse_feature_size = int(take())
            elif a == "--arch-mlp-bot":
                cfg.mlp_bot = [int(x) for x in take().split("-")]
            elif a == "--arch-mlp-top":
                cfg.mlp_top = [int(x) for x in take().split("-")]
            elif a == "--arch-interaction-op":
                cfg.arch_interaction_op = take()
            elif a == "--loss-threshold":
                cfg.loss_threshold = float(take())
            elif a == "--zipf-alpha":
                cfg.zipf_alpha = float(take())
                if cfg.zipf_alpha < 0:
                    raise ValueError(
                        f"--zipf-alpha expects a >= 0 exponent, got "
                        f"{cfg.zipf_alpha}")
            i += 1
        return cfg


def create_mlp(model: FFModel, input_tensor, sizes: List[int],
               sigmoid_last: bool = False, prefix: str = "mlp"):
    """Reference create_mlp (dlrm.cc:31-47): dense+relu per layer, sigmoid on
    the final top-MLP layer."""
    t = input_tensor
    for i, out_dim in enumerate(sizes[1:]):
        last = i == len(sizes) - 2
        act = "sigmoid" if (last and sigmoid_last) else "relu"
        t = model.dense(t, out_dim, activation=act,
                        name=f"{prefix}_dense_{i}")
    return t


def interact_features(model: FFModel, bottom_out, embedding_outs_3d,
                      arch_op: str, cfg: DLRMConfig):
    """Reference interact_features (dlrm.cc:49-65). `cat`: concat along the
    feature dim. `dot`: pairwise dot products via the 3-D batch ops
    (Reshape → BatchMatmul(Z=X·Xᵀ) → take lower triangle ≈ reference fork's
    intended path through batch_matmul.cu/transpose.cu/reshape.cu)."""
    d = cfg.sparse_feature_size
    T = len(cfg.embedding_size)
    batch = bottom_out.shape[0]
    if arch_op == "cat":
        flat_embs = [model.reshape(e, (batch, T * d), name="emb_flatten")
                     if e.num_dims == 3 else e
                     for e in embedding_outs_3d]
        return model.concat([bottom_out] + flat_embs, axis=1,
                            name="interaction_concat")
    if arch_op == "dot":
        # stack bottom + embeddings into (batch, T+1, d)
        bot3 = model.reshape(bottom_out, (batch, 1, d), name="bot3d")
        parts = [bot3]
        for e in embedding_outs_3d:
            parts.append(e if e.num_dims == 3
                         else model.reshape(e, (batch, 1, d)))
        x = model.concat(parts, axis=1, name="interaction_stack")  # (b,F,d)
        # Z = X · Xᵀ : (b,F,d)×(b,F,d) -> (b,F,F); batch_matmul default is
        # A^T*B over (d,k,m) layouts (model.h:1350) — here we want X Xᵀ so
        # use trans_a=False, trans_b=True
        z = model.batch_matmul(x, x, trans_a=False, trans_b=True,
                               name="interaction_bmm")
        F = x.shape[1]
        zf = model.reshape(z, (batch, F * F), name="interaction_flat")
        # strictly-lower-triangle selection (i > j): the F(F-1)/2 unique
        # pairwise dots, matching DLRM's dot interaction definition
        tril = [i * F + j for i in range(F) for j in range(i)]
        zt = model.index_select(zf, tril, axis=1, name="interaction_tril")
        return model.concat([bottom_out, zt], axis=1,
                            name="interaction_concat")
    raise ValueError(f"unknown interaction op {arch_op}")


def build_dlrm(model: FFModel, cfg: DLRMConfig,
               fuse_embeddings: Optional[bool] = None,
               fuse_interaction: bool = False
               ) -> Tuple[Dict[str, tuple], "object"]:
    """Build the DLRM graph on `model` (reference top_level_task graph build,
    dlrm.cc:103-128). Returns (input_specs, output_tensor); input names:
    'dense' float (batch, mlp_bot[0]), 'sparse' int (batch, T, bag).

    ``fuse_interaction=True`` (dot interaction + uniform tables only)
    replaces the gather→stack→bmm→tril→first-top-dense chain with ONE
    FusedDotInteraction op (Pallas-fused on TPU — the (B, F, F)
    interaction tensor never materializes). Default off: the op graph,
    parameter names and strategies are unchanged unless asked for."""
    batch = model.config.batch_size
    T = len(cfg.embedding_size)
    d = cfg.sparse_feature_size
    uniform = len(set(cfg.embedding_size)) == 1
    if fuse_embeddings is None:
        fuse_embeddings = True

    dense_in = model.create_tensor((batch, cfg.mlp_bot[0]), name="dense")
    sparse_in = model.create_tensor((batch, T, cfg.embedding_bag_size),
                                    dtype=jnp.int32, name="sparse")

    bottom = create_mlp(model, dense_in, cfg.mlp_bot, sigmoid_last=False,
                        prefix="bot")

    emb_init = UniformInitializer(min_val=-0.05, max_val=0.05)
    if fuse_interaction:
        if cfg.arch_interaction_op != "dot":
            raise ValueError("fuse_interaction=True needs "
                             "--arch-interaction-op dot (the fused kernel "
                             "computes the pairwise-dot interaction)")
        if not uniform:
            raise ValueError("fuse_interaction=True needs uniform table "
                             "sizes (the fused gather stacks the tables "
                             "row-wise)")
        if len(cfg.mlp_top) < 2:
            raise ValueError("fuse_interaction=True needs at least one "
                             "top-MLP layer to fold into the kernel")
        # the fused op IS the first top-MLP layer; it takes the sigmoid
        # head when it is also the last
        fused_last = len(cfg.mlp_top) == 2
        fused = model.fused_dot_interaction(
            sparse_in, bottom, cfg.embedding_size[0], cfg.mlp_top[1],
            activation="sigmoid" if fused_last else "relu",
            emb_initializer=emb_init, name="fused_interaction")
        if fused_last:
            out = fused
        else:
            out = create_mlp(model, fused,
                             [cfg.mlp_top[1]] + cfg.mlp_top[2:],
                             sigmoid_last=True, prefix="top")
        inputs = {"dense": (batch, cfg.mlp_bot[0]),
                  "sparse": (batch, T, cfg.embedding_bag_size)}
        return inputs, out
    if fuse_embeddings and uniform:
        embs = [model.embedding_stacked(
            sparse_in, T, cfg.embedding_size[0], d, aggr="sum",
            kernel_initializer=emb_init, name="emb_stack")]  # (b,T,d)
    elif fuse_embeddings:
        # non-uniform row counts (e.g. Criteo-Kaggle's 26 tables): fuse
        # into one concatenated-rows table — a single gather/scatter
        # instead of T ops
        embs = [model.embedding_concat(
            sparse_in, cfg.embedding_size, d, aggr="sum",
            kernel_initializer=emb_init, name="emb_concat")]  # (b,T,d)
    else:
        cols = model.split(sparse_in, [1] * T, axis=1, name="sparse_split")
        embs = []
        for i, (rows, col) in enumerate(zip(cfg.embedding_size, cols)):
            idx2d = model.reshape(col, (batch, cfg.embedding_bag_size),
                                  name=f"idx_{i}")
            embs.append(model.embedding(
                idx2d, rows, d, aggr="sum", kernel_initializer=emb_init,
                name=f"emb_{i}"))

    inter = interact_features(model, bottom, embs, cfg.arch_interaction_op,
                              cfg)
    out = create_mlp(model, inter, [inter.shape[1]] + cfg.mlp_top[1:],
                     sigmoid_last=True, prefix="top")
    inputs = {"dense": (batch, cfg.mlp_bot[0]),
              "sparse": (batch, T, cfg.embedding_bag_size)}
    return inputs, out


def dlrm_strategy(model: FFModel, cfg: DLRMConfig,
                  num_devices: int,
                  row_shard: bool = False) -> StrategyMap:
    """Hand-written DLRM strategy, the GSPMD analog of the reference
    generator (src/runtime/dlrm_strategy.cc:242-296): embedding tables
    table-parallel (stacked dim or width sharding), MLPs/bmm/concat
    data-parallel over all chips. ``row_shard=True`` instead splits the
    ROW space of every embedding table over the whole mesh (PARAM-axis
    degree, explicit all-to-all lookup routing) — the pod-scale shape
    for tables that fit no single device."""
    strat: StrategyMap = {}
    batch = model.config.batch_size
    for op in model.ops:
        tname = type(op).__name__
        nd = op.outputs[0].num_dims if op.outputs else 0
        if row_shard and batch % max(num_devices, 1) == 0 and tname in (
                "EmbeddingBagStacked", "EmbeddingBagConcat", "Embedding"):
            strat[op.name] = ParallelConfig(
                (num_devices,) + (1,) * (nd - 1),
                param_degree=num_devices)
        elif tname == "EmbeddingBagStacked":
            # (batch, T, d): shard the table dim with the largest common
            # divisor of table count and device count
            dt = next(d for d in range(min(num_devices, op.num_tables), 0, -1)
                      if op.num_tables % d == 0 and num_devices % d == 0)
            strat[op.name] = ParallelConfig((1, dt, 1))
        elif tname == "EmbeddingBagConcat":
            # any table-dim degree >1 triggers full-mesh row-block sharding
            # of the concatenated table (param_axes)
            dt = 2 if num_devices > 1 else 1
            strat[op.name] = ParallelConfig((1, dt, 1))
        elif tname == "Embedding":
            # width-shard each table's out_dim
            dc = next(d for d in range(min(num_devices, op.out_dim), 0, -1)
                      if op.out_dim % d == 0 and num_devices % d == 0)
            strat[op.name] = ParallelConfig((1, dc))
        elif nd > 0:
            strat[op.name] = ParallelConfig.data_parallel(nd, num_devices)
    return strat


def synthetic_batch(cfg: DLRMConfig, batch: int, seed: int = 0,
                    zipf_alpha: Optional[float] = None):
    """Random data generator (reference dlrm.cc data_loader with
    --dataset '' generates random ints/floats, dlrm.cc:384-484).
    `zipf_alpha` (default: cfg.zipf_alpha) skews the categorical ids
    zipf(alpha)-style — id 0 hottest — so skewed workloads are
    reproducible; 0 keeps the legacy uniform draws bit-compatible."""
    from ..data.dataloader import zipf_indices
    rng = np.random.RandomState(seed)
    T = len(cfg.embedding_size)
    alpha = cfg.zipf_alpha if zipf_alpha is None else float(zipf_alpha)
    dense = rng.rand(batch, cfg.mlp_bot[0]).astype(np.float32)
    sparse = np.stack(
        [zipf_indices(rng, rows, (batch, cfg.embedding_bag_size), alpha)
         for rows in cfg.embedding_size], axis=1).astype(np.int32)
    labels = rng.randint(0, 2, size=(batch, 1)).astype(np.float32)
    return {"dense": dense, "sparse": sparse}, labels
