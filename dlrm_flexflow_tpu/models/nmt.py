"""NMT LSTM seq2seq (reference: nmt/ — a self-contained pre-FFModel Legion
RNN framework, ~3,650 LoC: RnnModel with per-cell ParallelConfig placement,
SharedVariable parameter-server sync, cuDNN LSTM cells, data-parallel
softmax; nmt/nmt.cc:32-77).

Here NMT is just a model on the unified framework (SURVEY.md §7 step 8:
"as a model on the new framework, not a second runtime"): reversed source
(the reference Reverse op's use case) → embedding → stacked encoder LSTMs →
stacked decoder LSTMs over target embeddings conditioned by concatenating
the encoder's final-layer outputs (Luong-style simplified) → per-position
dense softmax. The reference's per-(layer, seq-chunk) device placement
becomes batch/hidden sharding configs on the LSTM ops."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from ..core.model import FFModel


def build_nmt(model: FFModel, src_vocab: int = 32 * 1024,
              tgt_vocab: int = 32 * 1024, embed_dim: int = 1024,
              hidden: int = 1024, num_layers: int = 2,
              src_len: int = 40, tgt_len: int = 40):
    """Shapes default to the reference scale (nmt/rnn.h: LSTM_PER_NODE_LENGTH
    chunks over seq len up to 40, 1024-wide cells, 32k vocab)."""
    batch = model.config.batch_size
    src = model.create_tensor((batch, src_len), dtype=jnp.int32, name="src")
    tgt = model.create_tensor((batch, tgt_len), dtype=jnp.int32, name="tgt")

    rsrc = model.reverse(src, axis=1, name="src_rev")
    senc = model.embedding(rsrc, src_vocab, embed_dim, aggr="none",
                           name="src_embed")  # (b, s, e)
    # all encoder layers in ONE fused scan: seq serial iterations total
    # instead of num_layers x seq (the per-iteration latency dominates
    # at reference batch sizes — ops/rnn.LSTMStack)
    enc_out = model.lstm_stack(senc, hidden, num_layers,
                               name="enc_lstm")  # (b, s, h)

    demb = model.embedding(tgt, tgt_vocab, embed_dim, aggr="none",
                           name="tgt_embed")
    # condition decoder on encoder: concat encoder outputs (aligned by
    # position, truncated/padded lengths equal here) with target embeddings
    if src_len != tgt_len:
        raise ValueError("this NMT build uses src_len == tgt_len")
    d = model.concat([demb, enc_out], axis=2, name="dec_in")
    d = model.lstm_stack(d, hidden, num_layers, name="dec_lstm")
    # per-position logits: fold seq into batch for the big projection
    d2 = model.reshape(d, (batch * tgt_len, hidden), name="dec_fold")
    logits = model.dense(d2, tgt_vocab, name="proj")
    probs = model.softmax(logits, name="prob")
    return {"src": (batch, src_len), "tgt": (batch, tgt_len)}, probs
