"""AlexNet (reference: examples/cpp/AlexNet/alexnet.cc ~450 LoC; python
analog examples/python/native/alexnet.py:7-70). NCHW, same layer stack:
conv11x11s4-64 → pool → conv5x5-192 → pool → 3×conv3x3(384/256/256) →
pool → flat → dense4096 → dense4096 → dense(num_classes) → softmax."""

from __future__ import annotations

from ..core.model import FFModel


def build_alexnet(model: FFModel, num_classes: int = 1000,
                  image_hw: int = 224):
    batch = model.config.batch_size
    x = model.create_tensor((batch, 3, image_hw, image_hw), name="image")
    t = model.conv2d(x, 64, 11, 11, 4, 4, 2, 2, activation="relu",
                     name="conv1")
    t = model.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool1")
    t = model.conv2d(t, 192, 5, 5, 1, 1, 2, 2, activation="relu",
                     name="conv2")
    t = model.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool2")
    t = model.conv2d(t, 384, 3, 3, 1, 1, 1, 1, activation="relu",
                     name="conv3")
    t = model.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation="relu",
                     name="conv4")
    t = model.conv2d(t, 256, 3, 3, 1, 1, 1, 1, activation="relu",
                     name="conv5")
    t = model.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool5")
    t = model.flat(t, name="flat")
    t = model.dense(t, 4096, activation="relu", name="fc6")
    t = model.dense(t, 4096, activation="relu", name="fc7")
    t = model.dense(t, num_classes, name="fc8")
    out = model.softmax(t, name="prob")
    return {"image": (batch, 3, image_hw, image_hw)}, out
