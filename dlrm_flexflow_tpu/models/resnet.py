"""ResNet (reference: examples/cpp/ResNet/resnet.cc ~400 LoC — bottleneck
blocks with BatchNorm and residual adds). Builders for ResNet-18/34
(basic blocks) and ResNet-50 (bottleneck), NCHW."""

from __future__ import annotations

from ..core.model import FFModel


def _basic_block(model, t, channels, stride, prefix):
    shortcut = t
    u = model.conv2d(t, channels, 3, 3, stride, stride, 1, 1, use_bias=False,
                     name=f"{prefix}_conv1")
    u = model.batch_norm(u, relu=True, name=f"{prefix}_bn1")
    u = model.conv2d(u, channels, 3, 3, 1, 1, 1, 1, use_bias=False,
                     name=f"{prefix}_conv2")
    u = model.batch_norm(u, relu=False, name=f"{prefix}_bn2")
    if stride != 1 or shortcut.shape[1] != channels:
        shortcut = model.conv2d(shortcut, channels, 1, 1, stride, stride,
                                0, 0, use_bias=False, name=f"{prefix}_proj")
        shortcut = model.batch_norm(shortcut, relu=False,
                                    name=f"{prefix}_projbn")
    u = model.add(u, shortcut, name=f"{prefix}_add")
    return model.relu(u, name=f"{prefix}_out")


def _bottleneck(model, t, channels, stride, prefix):
    """reference resnet.cc BottleneckBlock: 1x1 reduce, 3x3, 1x1 expand x4."""
    shortcut = t
    u = model.conv2d(t, channels, 1, 1, 1, 1, 0, 0, use_bias=False,
                     name=f"{prefix}_conv1")
    u = model.batch_norm(u, relu=True, name=f"{prefix}_bn1")
    u = model.conv2d(u, channels, 3, 3, stride, stride, 1, 1, use_bias=False,
                     name=f"{prefix}_conv2")
    u = model.batch_norm(u, relu=True, name=f"{prefix}_bn2")
    u = model.conv2d(u, 4 * channels, 1, 1, 1, 1, 0, 0, use_bias=False,
                     name=f"{prefix}_conv3")
    u = model.batch_norm(u, relu=False, name=f"{prefix}_bn3")
    if stride != 1 or shortcut.shape[1] != 4 * channels:
        shortcut = model.conv2d(shortcut, 4 * channels, 1, 1, stride, stride,
                                0, 0, use_bias=False, name=f"{prefix}_proj")
        shortcut = model.batch_norm(shortcut, relu=False,
                                    name=f"{prefix}_projbn")
    u = model.add(u, shortcut, name=f"{prefix}_add")
    return model.relu(u, name=f"{prefix}_out")


_CONFIGS = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
}


def build_resnet(model: FFModel, depth: int = 50, num_classes: int = 1000,
                 image_hw: int = 224):
    kind, blocks = _CONFIGS[depth]
    block = _basic_block if kind == "basic" else _bottleneck
    batch = model.config.batch_size
    x = model.create_tensor((batch, 3, image_hw, image_hw), name="image")
    t = model.conv2d(x, 64, 7, 7, 2, 2, 3, 3, use_bias=False, name="conv1")
    t = model.batch_norm(t, relu=True, name="bn1")
    t = model.pool2d(t, 3, 3, 2, 2, 1, 1, name="pool1")
    channels = 64
    for stage, n in enumerate(blocks):
        for i in range(n):
            stride = 2 if (stage > 0 and i == 0) else 1
            t = block(model, t, channels, stride, f"s{stage}b{i}")
        channels *= 2
    hw = t.shape[2]
    t = model.pool2d(t, hw, hw, 1, 1, 0, 0, pool_type="avg", name="gap")
    t = model.flat(t, name="flat")
    t = model.dense(t, num_classes, name="fc")
    out = model.softmax(t, name="prob")
    return {"image": (batch, 3, image_hw, image_hw)}, out
