"""Process-wide metrics registry: Counter / Gauge / Histogram with
labels, bounded reservoir percentiles, and Prometheus-text exposition.

Fourteen PRs grew a fleet of cooperating subsystems (prefetch ring,
superstep dispatch, delta publisher, snapshot watcher, serving engine /
router / shard tier, autoscaler, warm caches) that each exposed its own
one-shot ``stats()`` dict. This module is the shared substrate under
them: every ``stats()`` contract is unchanged, but the numbers behind
the hot ones now live in registry instruments, so a scraper (``GET
/metrics`` in serve_dlrm.py), the autoscaler, the benches, and a human
operator all read ONE source that is a time series instead of a
snapshot.

Design rules, in the spirit of :func:`~..analysis.sanitizer.make_lock`:

- **Off is free.** ``--obs off`` (the default) makes every module-level
  factory return a shared NO-OP singleton — ``counter(...) is
  NULL_COUNTER`` — so the hot paths pay a dict-free method call that
  does nothing. Tests pin the type identity.
- **Stats never lie about silence.** The bounded :class:`Reservoir`
  replaces the serving stack's private latency deques; an empty window
  still cuts a ``None`` percentile, never a flawless p99 (the same
  contract :func:`percentile` has enforced since the fleet PR).
- **Bounded by construction.** Every sample window is a ring: a
  long-lived server cannot grow a latency list without bound (flexcheck
  FLX109 ``unbounded-sample-list`` now flags the anti-pattern
  statically).

Naming scheme: ``ff_<subsystem>_<what>[_total]`` — counters end in
``_total``, latencies are ``*_ms`` histograms, point-in-time values are
gauges. Labels are low-cardinality only (replica id, action, loop).
"""

from __future__ import annotations

import re
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

_ENABLED = False

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Flip the process-wide obs switch (``--obs on``). Instruments are
    resolved at creation time: components built BEFORE enabling keep
    their no-op instruments (build the engine/fleet after configure —
    serve_dlrm.py and fit() both do)."""
    global _ENABLED
    _ENABLED = bool(on)


def override(on: bool):
    """Context manager flipping the switch for tests (mirrors
    ``sanitizer.override``). Only affects instruments CREATED inside
    the scope."""
    import contextlib

    @contextlib.contextmanager
    def _scope():
        global _ENABLED
        prev = _ENABLED
        _ENABLED = bool(on)
        try:
            yield
        finally:
            _ENABLED = prev

    return _scope()


# ---------------------------------------------------------------------
# percentiles + the bounded sample window
# ---------------------------------------------------------------------
def percentile(sorted_vals, p: float) -> Optional[float]:
    """Linear-interpolated percentile over an ASCENDING sequence
    (numpy's default method), ``None`` on an empty window.

    THE percentile of the codebase (serve.engine re-exports it): an
    empty window must report None — 0.0 ms would be a flawless p99 for
    a server that has answered nothing, which reads as healthy to an
    SLO monitor — and tiny windows interpolate instead of snapping to
    a sample.
    """
    n = len(sorted_vals)
    if n == 0:
        return None
    if n == 1:
        return float(sorted_vals[0])
    k = (p / 100.0) * (n - 1)
    f = int(k)
    c = min(f + 1, n - 1)
    return float(sorted_vals[f] + (k - f) * (sorted_vals[c] - sorted_vals[f]))


class Reservoir:
    """Bounded sample window: a ring of the last ``maxlen`` observations
    plus lifetime count/sum.

    This is the storage every latency window in the serving stack now
    shares (engine, router cohorts, shard tier): deque-compatible where
    the fleet code iterates/extends it, but with the percentile cut and
    the lifetime accounting built in — and registered as a Histogram
    child when obs is on, so the same window that backs ``stats()`` is
    scrapeable. Thread-safe; iteration and ``samples()`` return copies.
    """

    __slots__ = ("maxlen", "_buf", "_head", "_lock", "count", "total")

    def __init__(self, maxlen: int = 2048):
        if maxlen < 1:
            raise ValueError(f"Reservoir maxlen must be >= 1, got {maxlen}")
        self.maxlen = int(maxlen)
        self._buf: List[float] = []
        self._head = 0          # ring insertion point once full
        self._lock = threading.Lock()
        self.count = 0          # lifetime observations
        self.total = 0.0        # lifetime sum

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if len(self._buf) < self.maxlen:
                self._buf.append(v)
            else:
                self._buf[self._head] = v
                self._head = (self._head + 1) % self.maxlen

    # deque-compatible verbs (fleet.stats() extends/iterates the
    # engine windows; tests seed them with .extend)
    append = observe

    def extend(self, vals: Iterable[float]) -> None:
        for v in vals:
            self.observe(v)

    def samples(self) -> List[float]:
        with self._lock:
            return list(self._buf)

    def __iter__(self):
        return iter(self.samples())

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf = []
            self._head = 0

    def percentile(self, p: float) -> Optional[float]:
        return percentile(sorted(self.samples()), p)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            vals = sorted(self._buf)
            count, total = self.count, self.total
        return {
            "count": count,
            "sum": total,
            "window": len(vals),
            "min": vals[0] if vals else None,
            "max": vals[-1] if vals else None,
            "p50": percentile(vals, 50),
            "p99": percentile(vals, 99),
        }


# ---------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------
def _label_key(labelnames: Tuple[str, ...], kv: Dict[str, str]
               ) -> Tuple[str, ...]:
    if set(kv) != set(labelnames):
        raise ValueError(
            f"labels {sorted(kv)} do not match the instrument's "
            f"labelnames {sorted(labelnames)}")
    return tuple(str(kv[n]) for n in labelnames)


class _Bound:
    """One (instrument, label-values) pair: the object ``labels()``
    hands back for counters/gauges."""

    __slots__ = ("_inst", "_key")

    def __init__(self, inst, key):
        self._inst = inst
        self._key = key

    def inc(self, n: float = 1.0) -> None:
        self._inst._add(self._key, n)

    def dec(self, n: float = 1.0) -> None:
        self._inst._add(self._key, -n)

    def set(self, v: float) -> None:
        self._inst._set(self._key, v)


class Counter:
    """Monotonic counter with optional labels. ``inc(n, **labels)`` or
    ``labels(**kv).inc(n)``."""

    TYPE = "counter"

    def __init__(self, name: str, help: str = "",
                 labelnames: Tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], float] = {}

    def _add(self, key, n: float) -> None:
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(n)

    def _set(self, key, v: float) -> None:
        raise TypeError(f"counter {self.name} is monotonic; use inc()")

    def labels(self, **kv) -> _Bound:
        return _Bound(self, _label_key(self.labelnames, kv))

    def inc(self, n: float = 1.0, **kv) -> None:
        self._add(_label_key(self.labelnames, kv), n)

    def value(self, **kv) -> float:
        with self._lock:
            return self._values.get(_label_key(self.labelnames, kv), 0.0)

    def _samples(self):
        with self._lock:
            items = list(self._values.items())
        for key, v in items:
            yield dict(zip(self.labelnames, key)), v


class Gauge(Counter):
    """Point-in-time value; ``set`` and ``inc``/``dec`` both work."""

    TYPE = "gauge"

    def _set(self, key, v: float) -> None:
        with self._lock:
            self._values[key] = float(v)

    def set(self, v: float, **kv) -> None:
        self._set(_label_key(self.labelnames, kv), v)

    def dec(self, n: float = 1.0, **kv) -> None:
        self.inc(-n, **kv)


class Histogram:
    """Labeled family of bounded :class:`Reservoir` windows. Exposed in
    Prometheus text as a summary (count/sum + p50/p90/p99 quantiles cut
    from the ring — honest about being windowed, never averaged)."""

    TYPE = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Tuple[str, ...] = (), reservoir: int = 2048):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.reservoir = int(reservoir)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Reservoir] = {}

    def labels(self, **kv) -> Reservoir:
        key = _label_key(self.labelnames, kv)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = Reservoir(self.reservoir)
            return child

    def observe(self, v: float, **kv) -> None:
        self.labels(**kv).observe(v)

    def _samples(self):
        with self._lock:
            items = list(self._children.items())
        for key, res in items:
            yield dict(zip(self.labelnames, key)), res.snapshot()


# --- no-op twins (the --obs off fast path; type identity is pinned) ---
class NullInstrument:
    """Shared do-nothing instrument: every mutator is a no-op and
    ``labels()`` returns self, so component code is branch-free."""

    __slots__ = ()

    def labels(self, **kv):
        return self

    def inc(self, n: float = 1.0, **kv) -> None:
        pass

    def dec(self, n: float = 1.0, **kv) -> None:
        pass

    def set(self, v: float, **kv) -> None:
        pass

    def observe(self, v: float, **kv) -> None:
        pass


class NullCounter(NullInstrument):
    __slots__ = ()


class NullGauge(NullInstrument):
    __slots__ = ()


class NullHistogram(NullInstrument):
    __slots__ = ()


NULL_COUNTER = NullCounter()
NULL_GAUGE = NullGauge()
NULL_HISTOGRAM = NullHistogram()


# ---------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------
class MetricsRegistry:
    """Name -> instrument map plus pull-time collectors.

    Two ways in:

    - **Instruments** (``counter``/``gauge``/``histogram``): created
      once, mutated on the hot path. Get-or-create by name; a name
      re-registered with a different type or label set raises.
    - **Collectors** (``register_collector``): a zero-arg callable
      yielding ``(name, labels_dict, value)`` tuples, run at
      ``collect()``/scrape time. This is how components with existing
      ``stats()`` counters expose them without double-counting — the
      stats dict stays the source of truth, the scrape reads through.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}
        self._collectors: List[Callable] = []

    def _get_or_make(self, kind, name, help, labelnames, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        # label NAMES are a set in the data model; normalize the order
        # so two call sites naming the same labels get the same
        # instrument regardless of spelling order
        labelnames = tuple(sorted(labelnames))
        with self._lock:
            inst = self._metrics.get(name)
            if inst is None:
                inst = self._metrics[name] = kind(
                    name, help, tuple(labelnames), **kw)
                return inst
        if type(inst) is not kind or \
                tuple(inst.labelnames) != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}{inst.labelnames}; cannot "
                f"re-register as {kind.__name__}{tuple(labelnames)}")
        return inst

    def counter(self, name: str, help: str = "",
                labelnames: Tuple[str, ...] = ()) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Tuple[str, ...] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Tuple[str, ...] = (),
                  reservoir: int = 2048) -> Histogram:
        return self._get_or_make(Histogram, name, help, labelnames,
                                 reservoir=reservoir)

    def register_collector(self, fn: Callable) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn: Callable) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def reset(self) -> None:
        """Drop every instrument and collector (test isolation)."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()

    # --- exposition ----------------------------------------------------
    def collect(self) -> Dict[str, Any]:
        """Structured snapshot: instruments plus collector output.
        Collector errors are swallowed per collector (a wedged
        subsystem must not take the metrics endpoint down with it)."""
        with self._lock:
            metrics = dict(self._metrics)
            collectors = list(self._collectors)
        out: Dict[str, Any] = {}
        for name, inst in sorted(metrics.items()):
            out[name] = {
                "type": inst.TYPE,
                "help": inst.help,
                "samples": [{"labels": lab, "value": v}
                            for lab, v in inst._samples()],
            }
        for fn in collectors:
            try:
                rows = list(fn())
            except Exception:   # noqa: BLE001 — scrape must survive a
                continue        # dying component's collector
            for name, labels, value in rows:
                entry = out.setdefault(
                    name, {"type": "gauge", "help": "", "samples": []})
                entry["samples"].append(
                    {"labels": dict(labels or {}), "value": float(value)})
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition (version 0.0.4). Histograms emit
        as summaries (windowed quantiles + lifetime count/sum)."""
        lines: List[str] = []
        for name, entry in self.collect().items():
            if entry["help"]:
                lines.append(f"# HELP {name} {entry['help']}")
            kind = entry["type"]
            lines.append(f"# TYPE {name} "
                         f"{'summary' if kind == 'histogram' else kind}")
            for sample in entry["samples"]:
                labels, value = sample["labels"], sample["value"]
                if kind == "histogram":
                    for q, key in (("0.5", "p50"), ("0.99", "p99")):
                        if value[key] is not None:
                            lines.append(
                                f"{name}{_fmt_labels(labels, quantile=q)}"
                                f" {_fmt_value(value[key])}")
                    lines.append(f"{name}_count{_fmt_labels(labels)} "
                                 f"{value['count']}")
                    lines.append(f"{name}_sum{_fmt_labels(labels)} "
                                 f"{_fmt_value(value['sum'])}")
                else:
                    lines.append(f"{name}{_fmt_labels(labels)} "
                                 f"{_fmt_value(value)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_value(v: float) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _fmt_labels(labels: Dict[str, str], **extra) -> str:
    kv = dict(labels)
    kv.update(extra)
    if not kv:
        return ""
    parts = []
    for k in sorted(kv):
        v = str(kv[k]).replace("\\", r"\\").replace('"', r"\"") \
            .replace("\n", r"\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


# ---------------------------------------------------------------------
# module-level factories (the component-facing API)
# ---------------------------------------------------------------------
def counter(name: str, help: str = "",
            labelnames: Tuple[str, ...] = ()):
    """A registry Counter when obs is on, the shared no-op otherwise."""
    if not _ENABLED:
        return NULL_COUNTER
    return _REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames: Tuple[str, ...] = ()):
    if not _ENABLED:
        return NULL_GAUGE
    return _REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "",
              labelnames: Tuple[str, ...] = (), reservoir: int = 2048):
    if not _ENABLED:
        return NULL_HISTOGRAM
    return _REGISTRY.histogram(name, help, labelnames, reservoir)


def latency_reservoir(name: str, help: str = "", maxlen: int = 2048,
                      **labels) -> Reservoir:
    """The serving stack's latency-window factory: ALWAYS a live
    bounded :class:`Reservoir` (the component's ``stats()`` percentiles
    need one either way); when obs is on it is additionally registered
    as a Histogram child under ``name`` with the given labels, so the
    same window is scrapeable as a time series."""
    if not _ENABLED:
        return Reservoir(maxlen)
    h = _REGISTRY.histogram(name, help,
                            labelnames=tuple(sorted(labels)),
                            reservoir=maxlen)
    return h.labels(**labels)


def register_collector(fn: Callable) -> None:
    """Register a pull-time collector iff obs is on (no-op otherwise,
    so components can call unconditionally)."""
    if _ENABLED:
        _REGISTRY.register_collector(fn)


def unregister_collector(fn: Callable) -> None:
    _REGISTRY.unregister_collector(fn)
