"""Unified observability layer: metrics registry, structured tracing,
and the live predicted-vs-measured drift monitor.

Three modules, one switch:

- :mod:`.metrics` — process-wide Counter/Gauge/Histogram registry with
  labels, bounded-reservoir percentiles, and Prometheus-text exposition
  (``GET /metrics`` in serve_dlrm.py). The serving stack's ``stats()``
  dicts keep their shapes; their latency windows and hot counters are
  now backed by registry instruments.
- :mod:`.trace` — named spans in a bounded in-memory ring, tagged with
  the emitting ``ff-*`` thread, exported as Chrome-trace/Perfetto JSON:
  prefetch → superstep dispatch on the training side, enqueue →
  batch-form → dispatch → swap on the serving side, publish →
  watcher-apply → swap for freshness.
- :mod:`.drift` — the runtime twin of shardcheck FLX513: measured step
  wall time and lowered-HLO collective bytes compared online against
  the simulator's predictions, with gauges and a loud (debounced)
  structured warning when measured/predicted exceeds the threshold.

Everything is OFF by default and free when off (no-op singletons, type
identity pinned like ``make_lock``). Turn it on with ``--obs on``
(plus ``--obs-trace-dir DIR`` to export traces) or programmatically via
:func:`configure` / the per-module ``override`` context managers.
Configure BEFORE building engines/fleets — instruments resolve at
creation time.
"""

from __future__ import annotations

from . import metrics, trace


def configure(cfg) -> bool:
    """Apply an FFConfig's ``--obs`` flags process-wide. Returns True
    when observability ended up enabled. Idempotent; never turns obs
    OFF (a second model with the default config must not disable the
    first one's instruments mid-run)."""
    if str(getattr(cfg, "obs", "off")) != "on":
        return metrics.enabled()
    metrics.set_enabled(True)
    trace.set_enabled(True)
    d = str(getattr(cfg, "obs_trace_dir", "") or "")
    if d:
        trace.set_trace_dir(d)
    return True


__all__ = ["metrics", "trace", "configure"]
