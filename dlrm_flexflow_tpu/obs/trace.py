"""Structured tracing: named spans in a bounded in-memory ring,
exported as Chrome-trace / Perfetto JSON.

``utils/profiling.py`` covers the two reference layers (per-op timing,
whole-run xprof capture); what neither shows is the CROSS-SUBSYSTEM
story — where a request spent its time between the prefetch ring, the
superstep dispatch, the delta publisher, the snapshot watcher, and the
serving batcher. This module instruments those seams:

- training: ``prefetch/produce`` → ``train/step`` / ``train/superstep``
- serving:  ``serve/enqueue`` → ``serve/batch-form`` →
  ``serve/dispatch`` → ``serve/swap``
- freshness: ``publish/full`` / ``publish/delta`` →
  ``publish/watcher-apply`` → ``serve/swap``

Events land in a bounded ring (oldest overwritten — a long-lived server
cannot leak; ``dropped()`` counts the overwritten tail) and are tagged
with the emitting thread, so the existing ``ff-*`` thread-naming
discipline (flexcheck FLX101) becomes the trace's lane structure for
free. :func:`chrome_trace` renders the ring as Chrome's trace-event
JSON — load it at ``chrome://tracing`` or https://ui.perfetto.dev —
with complete ("X") events whose ts/dur nesting reconstructs the span
tree per thread.

Off (the default) is free: :func:`span` returns a shared no-op context
manager (type identity pinned, like ``make_lock`` and the metrics
twins), and :func:`instant` returns immediately.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_ENABLED = False
_TRACE_DIR = ""
_CAPACITY = 65536

# the ring: plain deque — append on a maxlen deque is GIL-atomic, so
# emitters never take a lock; exporters snapshot with list(_RING)
_RING: "deque[Dict[str, Any]]" = deque(maxlen=_CAPACITY)
_APPENDED = 0                      # lifetime events (dropped = this - len)
_THREAD_NAMES: Dict[int, str] = {}  # tid -> last seen thread name
_PID = os.getpid()


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def set_trace_dir(path: str) -> None:
    global _TRACE_DIR
    _TRACE_DIR = str(path or "")


def trace_dir() -> str:
    return _TRACE_DIR


def set_capacity(n: int) -> None:
    """Resize the ring (keeps the newest events)."""
    global _RING, _CAPACITY
    if n < 1:
        raise ValueError(f"trace ring capacity must be >= 1, got {n}")
    _CAPACITY = int(n)
    _RING = deque(_RING, maxlen=_CAPACITY)


def clear() -> None:
    global _APPENDED
    _RING.clear()
    _THREAD_NAMES.clear()
    _APPENDED = 0


def events() -> List[Dict[str, Any]]:
    return list(_RING)


def dropped() -> int:
    """Events overwritten by the ring so far."""
    return max(0, _APPENDED - len(_RING))


def override(on: bool, trace_dir: Optional[str] = None,
             capacity: Optional[int] = None):
    """Context manager flipping tracing for tests; restores the ring
    contents, capacity, and trace dir on exit."""
    import contextlib

    @contextlib.contextmanager
    def _scope():
        global _ENABLED, _TRACE_DIR
        prev = (_ENABLED, _TRACE_DIR, _CAPACITY)
        _ENABLED = bool(on)
        if trace_dir is not None:
            _TRACE_DIR = trace_dir
        if capacity is not None:
            set_capacity(capacity)
        try:
            yield
        finally:
            _ENABLED, _TRACE_DIR, cap = prev
            set_capacity(cap)

    return _scope()


def _now_us() -> float:
    return time.perf_counter() * 1e6


def _emit(ev: Dict[str, Any]) -> None:
    global _APPENDED
    t = threading.current_thread()
    tid = t.ident or 0
    _THREAD_NAMES[tid] = t.name
    ev["pid"] = _PID
    ev["tid"] = tid
    _RING.append(ev)
    _APPENDED += 1


class _NullSpan:
    """Shared reusable no-op context manager — the obs-off fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class Span:
    """One named duration. Records a complete ("X") event on exit, so
    an abandoned span (thread died mid-work) simply never lands — the
    instants around it still tell the story."""

    __slots__ = ("name", "cat", "args", "_t0")

    def __init__(self, name: str, cat: str, args: Dict[str, Any]):
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = 0.0

    def __enter__(self) -> "Span":
        self._t0 = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = _now_us()
        args = self.args
        if exc_type is not None:
            args = dict(args)
            args["error"] = exc_type.__name__
        _emit({"name": self.name, "cat": self.cat or "ff", "ph": "X",
               "ts": self._t0, "dur": t1 - self._t0, "args": args})
        return False


def span(name: str, cat: str = "", **args):
    """Context manager timing one named unit of work. The shared no-op
    singleton when tracing is off — ``span(...) is NULL_SPAN``."""
    if not _ENABLED:
        return NULL_SPAN
    return Span(name, cat, args)


def complete(name: str, t0_s: float, cat: str = "", **args) -> None:
    """Record an already-timed duration: ``t0_s`` is the
    ``time.perf_counter()`` reading at its start. For call sites that
    cannot wrap their work in a ``with`` (a batch formed across a
    condition-variable wait, say)."""
    if not _ENABLED:
        return
    t0 = t0_s * 1e6
    _emit({"name": name, "cat": cat or "ff", "ph": "X", "ts": t0,
           "dur": _now_us() - t0, "args": args})


def instant(name: str, cat: str = "", **args) -> None:
    """Record a zero-duration marker (stall reports, anomaly sentinel
    fires, autoscaler decisions, drift warnings): visible even when the
    subsystem that emitted it is wedged and will never close a span."""
    if not _ENABLED:
        return
    _emit({"name": name, "cat": cat or "ff", "ph": "i", "s": "t",
           "ts": _now_us(), "args": args})


# ---------------------------------------------------------------------
# export
# ---------------------------------------------------------------------
def chrome_trace() -> Dict[str, Any]:
    """The ring as a Chrome trace-event JSON object: thread-name
    metadata first (so Perfetto labels each lane with the ff-* worker
    name), then the events oldest-first."""
    evs = list(_RING)
    meta = [{"name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
             "args": {"name": name}}
            for tid, name in sorted(_THREAD_NAMES.items())]
    return {
        "traceEvents": meta + evs,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "dlrm_flexflow_tpu.obs.trace",
            "dropped_events": dropped(),
        },
    }


def export(path: str) -> str:
    """Write the current ring as Chrome-trace JSON to ``path``."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(chrome_trace(), f)
    os.replace(tmp, path)
    return path


def export_to_dir(directory: Optional[str] = None) -> Optional[str]:
    """Export to the configured ``--obs-trace-dir`` (or an explicit
    directory); None when neither is set. File names are unique per
    (pid, monotonic-ns) so concurrent exporters never clobber."""
    d = directory or _TRACE_DIR
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    name = f"ff-trace-{_PID}-{time.monotonic_ns()}.json"
    return export(os.path.join(d, name))
