"""Live predicted-vs-measured drift monitor: the runtime twin of the
static auditors.

shardcheck's FLX513 compares the cost model's collective-bytes
prediction against the LOWERED HLO — statically, before a step runs.
This module closes the remaining gap: during ``fit()``/``fit_stream()``
it watches the numbers the simulator actually promised —

- **step time**: measured per-dispatch wall time vs the simulator's
  predicted makespan (``Simulator.simulate`` — the same number the MCMC
  search ranked strategies by). A plan the search blessed at 2 ms that
  runs at 20 ms means the cost model is mispricing THIS model on THIS
  hardware, and every future search on the box inherits the error.
- **collective bytes**: the lowered executable's per-step collective
  payloads vs the cost model's pricing (reusing
  ``analysis.hlo_audit``); the replicated-table plan that FLX513 flags
  statically (full-table gradient all-reduce the search never charged
  for) is re-found here at runtime, on the program that is actually
  executing.

Both drifts land as registry gauges
(``ff_drift_step_time_ratio{loop=...}``,
``ff_drift_collective_bytes_ratio{kind=...}``), trace instants, and —
past ``threshold`` for ``sustain`` consecutive steps — ONE loud
structured warning per breach episode (debounced with the autoscaler's
:class:`~..utils.watchdog.Sustained`; a single slow step from a GC
pause must not cry wolf).

When no prediction is available (no compiled strategies, a config-stub
model, an off-calibration CPU test mesh) the monitor **calibrates**: the
median of the first ``calibrate_steps`` measured steps becomes the
baseline, and drift is measured against the run's own steady state —
quiet at calibration by construction, loud when the run later slows
down (a leaking host gather, a throttling chip, an injected
``FF_FAULT_SERVE_DELAY``).
"""

from __future__ import annotations

import statistics
from typing import Any, Dict, Optional

from ..utils.logging import get_logger
from ..utils.watchdog import Sustained
from . import metrics, trace

log_drift = get_logger("obs.drift")


class DriftMonitor:
    """Online measured/predicted comparison for one training loop.

    Not thread-safe by design: one loop owns one monitor (the same
    contract as ``Sustained``).
    """

    def __init__(self, predicted_step_s: Optional[float] = None,
                 threshold: float = 1.5, calibrate_steps: int = 16,
                 sustain: int = 5, name: str = "fit"):
        if threshold <= 0:
            raise ValueError(f"drift threshold must be > 0, "
                             f"got {threshold}")
        self.name = name
        self.threshold = float(threshold)
        self.calibrate_steps = max(int(calibrate_steps), 1)
        self.predicted_step_s = (float(predicted_step_s)
                                 if predicted_step_s else None)
        # where the baseline came from: "simulator" when a prediction
        # was handed in, "calibration" once self-measured
        self.baseline_source = ("simulator" if self.predicted_step_s
                                else None)
        self._model = None
        self._cal: list = []
        self._sustained = Sustained(max(int(sustain), 1))
        self._in_breach = False
        self.steps = 0
        self.fired = 0
        self.last_ratio: Optional[float] = None
        self.max_ratio: Optional[float] = None
        self.collective_drift: Dict[str, Any] = {}
        self._g_ratio = metrics.gauge(
            "ff_drift_step_time_ratio",
            "measured / predicted step wall time", labelnames=("loop",))
        self._g_bytes = metrics.gauge(
            "ff_drift_collective_bytes_ratio",
            "lowered-HLO / cost-model collective bytes per step",
            labelnames=("loop", "kind"))
        self._c_warn = metrics.counter(
            "ff_drift_warnings_total",
            "sustained drift breaches (one per episode)",
            labelnames=("loop", "kind"))

    # --- construction ---------------------------------------------------
    @classmethod
    def from_model(cls, model, name: str = "fit",
                   threshold: Optional[float] = None) -> "DriftMonitor":
        """Monitor for a compiled model: predicted step time from the
        simulator when the model carries searched/compiled strategies,
        self-calibrating otherwise. Never raises — a model the
        simulator cannot price still gets the calibrated monitor."""
        thr = (float(threshold) if threshold is not None
               else float(getattr(model.config, "obs_drift_threshold",
                                  1.5) or 1.5))
        pred = None
        try:
            strategies = getattr(model, "strategies", None)
            if strategies:
                from ..search.simulator import Simulator
                pred = float(Simulator(model).simulate(dict(strategies)))
                if pred <= 0 or pred != pred or pred == float("inf"):
                    pred = None
        except Exception as e:   # noqa: BLE001 — an unpriceable model
            log_drift.debug("simulator prediction unavailable (%s); "
                            "drift monitor will self-calibrate", e)
        mon = cls(predicted_step_s=pred, threshold=thr, name=name)
        mon._model = model
        return mon

    # --- one-shot collective-bytes audit (the FLX513 runtime twin) ------
    def audit_collectives(self) -> Dict[str, Any]:
        """Lower the train step and compare its collective bytes against
        the cost model's pricing, once per attach. Emits the per-kind
        ratio gauges; measured ≫ predicted (the replicated-plan
        signature) warns loudly. Returns the audit report ({} when the
        model cannot lower — e.g. not initialized)."""
        model = self._model
        if model is None:
            return {}
        try:
            from ..analysis.hlo_audit import audit_model
            findings, report = audit_model(model, path=f"<{self.name}>")
        except Exception as e:   # noqa: BLE001 — obs must never take
            # the training loop down; no audit beats no training
            log_drift.debug("collective-bytes audit unavailable (%s)", e)
            return {}
        measured = report.get("measured_bytes", {})
        predicted = report.get("predicted_bytes", {})
        ratios = {}
        for kind in ("all-to-all", "all-reduce"):
            pred = float(predicted.get(kind, 0.0))
            meas = float(measured.get(kind, 0.0))
            if pred > 0:
                ratios[kind] = meas / pred
                self._g_bytes.set(meas / pred, loop=self.name, kind=kind)
            elif meas > 0:
                ratios[kind] = float("inf")
                self._g_bytes.set(float("inf"), loop=self.name,
                                  kind=kind)
        self.collective_drift = {
            "measured_bytes": measured,
            "predicted_bytes": predicted,
            "ratios": {k: (round(v, 4) if v != float("inf") else "inf")
                       for k, v in ratios.items()},
            "findings": [f.render() for f in findings
                         if f.rule == "FLX513"],
        }
        for f in findings:
            if f.rule != "FLX513":
                continue
            self.fired += 1
            self._c_warn.inc(loop=self.name, kind="collective-bytes")
            trace.instant("drift/collective-bytes", cat="drift",
                          loop=self.name, message=f.message[:200])
            log_drift.warning(
                "DRIFT [%s] collective bytes: %s", self.name, f.message)
        return self.collective_drift

    # --- per-step step-time drift ---------------------------------------
    def observe_step(self, wall_s: float) -> Optional[float]:
        """Feed one measured per-step wall time (a superstep caller
        divides by K first). Returns the measured/predicted ratio, or
        None while calibrating."""
        self.steps += 1
        pred = self.predicted_step_s
        if pred is None:
            self._cal.append(float(wall_s))
            if len(self._cal) >= self.calibrate_steps:
                self.predicted_step_s = max(
                    statistics.median(self._cal), 1e-9)
                self.baseline_source = "calibration"
                log_drift.info(
                    "drift monitor [%s] calibrated: baseline step time "
                    "%.3f ms over %d steps", self.name,
                    1e3 * self.predicted_step_s, len(self._cal))
            return None
        ratio = float(wall_s) / pred
        self.last_ratio = ratio
        self.max_ratio = (ratio if self.max_ratio is None
                          else max(self.max_ratio, ratio))
        self._g_ratio.set(ratio, loop=self.name)
        breach = ratio > self.threshold
        if self._sustained.observe(breach):
            if not self._in_breach:
                # one loud report per episode, not one per step
                self._in_breach = True
                self.fired += 1
                self._c_warn.inc(loop=self.name, kind="step-time")
                trace.instant("drift/step-time", cat="drift",
                              loop=self.name, ratio=round(ratio, 3),
                              measured_ms=round(1e3 * wall_s, 3),
                              predicted_ms=round(
                                  1e3 * pred, 3),
                              baseline=self.baseline_source)
                log_drift.warning(
                    "DRIFT [%s] step time: measured %.3f ms is %.2fx "
                    "the %s baseline %.3f ms (> %.2gx for %d "
                    "consecutive steps) — the %s is mispricing this "
                    "run", self.name, 1e3 * wall_s, ratio,
                    self.baseline_source, 1e3 * pred, self.threshold,
                    self._sustained.periods,
                    "cost model" if self.baseline_source == "simulator"
                    else "calibrated steady state")
        elif not breach:
            self._in_breach = False
        return ratio

    def report(self) -> Dict[str, Any]:
        return {
            "loop": self.name,
            "steps": self.steps,
            "threshold": self.threshold,
            "baseline_source": self.baseline_source,
            "predicted_step_ms": (None if self.predicted_step_s is None
                                  else round(1e3 * self.predicted_step_s,
                                             4)),
            "last_ratio": (None if self.last_ratio is None
                           else round(self.last_ratio, 4)),
            "max_ratio": (None if self.max_ratio is None
                          else round(self.max_ratio, 4)),
            "fired": self.fired,
            "in_breach": self._in_breach,
            "collective_drift": self.collective_drift,
        }
