"""Strategy re-planning for a changed device count (elastic recovery).

The MCMC search (`search/mcmc.py`) auto-discovers a SOAP strategy for a
FIXED machine model, like the reference's simulator-driven search
(model.cc:1093-1144). When preemption shrinks the fleet mid-run, the
surviving devices need a NEW strategy — Varuna-style re-planning: keep
what transfers from the old plan, re-search under the new constraint,
and always have a cheap greedy answer when the search budget is zero or
the search itself fails (recovery must never be the thing that dies).

Two layers:

- :func:`clamp_strategies` — deterministic, search-free projection of an
  existing strategy map onto a smaller device count: every partition
  degree drops to the largest feasible degree on the new factorized mesh
  that divides into the old intent, and joint assignability is repaired
  per-op. This is the greedy fallback AND the warm start for the search.
  A projection the plan verifier would flag INFEASIBLE on the survivors
  (row shards forced into replicating a table the survivor mesh cannot
  hold) is REJECTED with op + reason (:class:`ClampError`) instead of
  shipped silently — dying with a named cause beats OOMing during
  recovery with no cause at all. :func:`clamp_report` exposes the same
  hazards non-fatally for the static verifier (shardcheck FLX505).
- :func:`replan_strategies` — clamp, then (budget permitting) re-run the
  simulated-annealing search constrained to the surviving count, seeded
  from the clamped map so the walk starts from a feasible, near-optimal
  point. Deterministic for a fixed seed — the elastic bit-identity test
  relies on an independent caller reproducing the same plan.
- :func:`expand_strategies` — the INVERSE projection, for scale-UP
  (``parallel.elastic.expand``): un-clamp a running plan onto a GROWN
  device count. The machinery is the clamp run in reverse: the intent
  plan (the remembered pre-shrink map when the elastic layer has one,
  else the running plan) projects onto the larger factorized mesh with
  the same per-op feasibility repair, so row-shard degrees grow back
  only to counts that still equal-block the table rows (the row-shard
  quantum) and a growth that would force an infeasible layout is
  REJECTED with op + reason (:class:`ClampError`), exactly like an
  infeasible shrink.

Both re-planners consult an optional :class:`~..utils.warmcache.PlanCache`
keyed by (graph, topology, warm-start, budget, seed): the search is
deterministic per key, so a cache hit returns byte-for-byte the plan a
fresh search would have produced — recovery skips the MCMC walk without
touching the bit-identity contract. Corrupt or wrong-topology entries are
rejected by the cache itself (reject-with-reason) and the search runs
fresh.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..core.op import InputOp
from ..parallel.mesh import structural_axis_sizes
from ..parallel.pconfig import ParallelConfig, StrategyMap
from ..parallel.sharding import clamp_degrees, clamp_param_degree
from ..utils.logging import get_logger

log_replan = get_logger("replan")


class ClampError(ValueError):
    """A strategy projection onto a survivor mesh is infeasible. The
    message always names the op and the reason — the silent alternative
    is a plan that replicates a >HBM table and OOMs mid-recovery with
    neither."""

    def __init__(self, op: str, reason: str, ndev: int):
        super().__init__(
            f"cannot project strategy for op {op!r} onto {ndev} "
            f"device(s): {reason}")
        self.op = op
        self.reason = reason
        self.ndev = ndev


def _survivor_hbm_bytes(hbm_bytes: Optional[float]) -> float:
    if hbm_bytes is not None:
        return float(hbm_bytes)
    from .cost_model import TPUSpec
    return float(TPUSpec.detect().hbm_capacity_bytes)


def _project_op(op, pc: ParallelConfig, axis_sizes,
                hbm_cap: float) -> Tuple[ParallelConfig,
                                         Optional[Tuple[str, bool]]]:
    """Clamp one op's config onto the survivor axes. Returns the
    projected config plus an optional (reason, fatal) hazard: non-fatal
    = row sharding was shed into replication but the table still fits;
    fatal = the replicated fallback cannot fit the survivor's HBM."""
    pd_old = max(getattr(pc, "param_degree", 1), 1)
    rows = pack = None
    if pd_old > 1 and hasattr(op, "_row_shard_geometry"):
        rows, pack, _tables = op._row_shard_geometry()
    pd_new = clamp_param_degree(pd_old, axis_sizes, rows=rows, pack=pack)
    new_pc = ParallelConfig(
        clamp_degrees(pc.degrees, axis_sizes),
        device_type=pc.device_type,
        memory_types=pc.memory_types,
        # row-sharded tables RESHARD onto the survivors (the largest
        # feasible shard count that still equal-blocks the rows), they
        # don't fall back to replication — replicating a >HBM table is
        # exactly what cannot happen
        param_degree=pd_new,
        # skew policies follow the exchange they refine: kept while row
        # sharding survives (the hot quantum is degree-independent, so
        # the hot block's SHAPE — and the checkpoint — survive the
        # reshard), dropped with it
        exchange=(getattr(pc, "exchange", "dense") if pd_new > 1
                  else "dense"),
        hot_fraction=(getattr(pc, "hot_fraction", 0.0) if pd_new > 1
                      else 0.0),
        # the pipelined exchange follows the exchange too: it has no
        # cross-step state (every dispatch drains), so a resharded
        # survivor keeps pipelining — there is nothing to migrate
        overlap=(bool(getattr(pc, "overlap", False)) if pd_new > 1
                 else False),
        # the quantized-storage policy is layout-independent — it
        # survives ANY clamp (the stored rows just reshard)
        quant_dtype=getattr(pc, "quant_dtype", ""),
        quant_update=getattr(pc, "quant_update", ""))
    hazard: Optional[Tuple[str, bool]] = None
    if pd_old > 1 and new_pc.param_degree == 1:
        table_bytes = float(op.param_bytes()) if op.param_defs() else 0.0
        sizes = [int(a) for a in axis_sizes]
        if table_bytes > 0.9 * hbm_cap:
            hazard = (
                f"row shards (param_degree={pd_old}) cannot reshard "
                f"over survivor axes {sizes} (rows={rows}, lane pack "
                f"{pack}) and the replicated fallback needs "
                f"{table_bytes / 1e9:.2f} GB of the "
                f"{hbm_cap / 1e9:.2f} GB per-device HBM", True)
        else:
            hazard = (
                f"sheds row sharding (param_degree={pd_old} -> 1): no "
                f"degree > 1 both factorizes survivor axes {sizes} and "
                f"divides the {rows} rows — the table replicates",
                False)
    return new_pc, hazard


def clamp_report(model, strategies: Optional[StrategyMap], ndev: int,
                 hbm_bytes: Optional[float] = None
                 ) -> List[Tuple[str, str, bool]]:
    """Non-fatal projection analysis: [(op, reason, fatal)] hazards the
    clamp of `strategies` onto `ndev` devices would incur. The static
    plan verifier (shardcheck FLX505) reports these; fatal entries are
    exactly the ones :func:`clamp_strategies` refuses to ship."""
    axis_sizes = structural_axis_sizes(ndev)
    cap = _survivor_hbm_bytes(hbm_bytes)
    out: List[Tuple[str, str, bool]] = []
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        pc = (strategies or {}).get(op.name)
        if pc is None:
            continue
        _, hazard = _project_op(op, pc, axis_sizes, cap)
        if hazard is not None:
            out.append((op.name, hazard[0], hazard[1]))
    return out


def clamp_strategies(model, strategies: Optional[StrategyMap],
                     ndev: int,
                     hbm_bytes: Optional[float] = None) -> StrategyMap:
    """Project `strategies` onto an `ndev`-device target (greedy re-plan).

    Per op: `parallel.sharding.clamp_degrees` drops every dim's degree
    to the largest feasible one on the ndev factorized mesh and repairs
    joint assignability; row-shard degrees reshard via
    `clamp_param_degree` (rows-divisibility aware). Ops missing from the
    old map (or with no map at all) get their default data-parallel
    config for ndev.

    Raises :class:`ClampError` (op + reason) when the projection is
    INFEASIBLE — a row-sharded table that can neither reshard onto the
    survivors nor fit replicated in per-device HBM (`hbm_bytes`,
    default: the detected chip's capacity). A merely-degraded projection
    (row shards shed but the table fits) ships with a loud warning.
    """
    axis_sizes = structural_axis_sizes(ndev)
    cap = _survivor_hbm_bytes(hbm_bytes)
    strategies = dict(strategies or {})
    out: StrategyMap = {}
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        pc = strategies.get(op.name)
        if pc is None:
            out[op.name] = op.default_parallel_config(ndev)
            continue
        new_pc, hazard = _project_op(op, pc, axis_sizes, cap)
        if hazard is not None:
            reason, fatal = hazard
            if fatal:
                raise ClampError(op.name, reason, ndev)
            log_replan.warning("clamp to %d device(s): op %r %s",
                               ndev, op.name, reason)
        out[op.name] = new_pc
    return out


def _plan_cache_key(model, intent: StrategyMap, ndev: int, budget: int,
                    seed: int, key_extra: str = "") -> str:
    from ..parallel.mesh import structural_axis_sizes as _sas
    from ..utils.warmcache import (PlanCache, graph_fingerprint,
                                   strategy_signature)
    return (PlanCache.key(graph_fingerprint(model), ndev, _sas(ndev),
                          budget, seed)
            + f"|start={strategy_signature(intent)}" + key_extra)


def _searched_plan(model, intent: StrategyMap, ndev: int, budget: int,
                   seed: int, cost_model, plan_cache,
                   hbm_bytes=None, key_extra: str = ""
                   ) -> Tuple[StrategyMap, Dict[str, float]]:
    """Shared shrink/grow core: project `intent` onto `ndev` (may raise
    ClampError), then search from the projection under `budget` —
    consulting/filling the plan cache around the whole thing. The cache
    key pins (graph, topology, warm-start, budget, seed), every input
    the deterministic result depends on; callers whose result depends on
    MORE (the drift re-planner's observed distribution) extend it via
    ``key_extra``."""
    t0 = time.perf_counter()
    info: Dict[str, float] = {"searched": False, "greedy_fallback": True,
                              "plan_cache_hit": False}
    key = None
    if plan_cache is not None:
        key = _plan_cache_key(model, intent, ndev, budget, seed,
                              key_extra)
        hit = plan_cache.get(key, ndev)
        if hit is not None:
            info["searched"] = bool(hit["searched"])
            info["greedy_fallback"] = not hit["searched"]
            info["plan_cache_hit"] = True
            info["replan_s"] = time.perf_counter() - t0
            return hit["strategies"], info
    greedy = clamp_strategies(model, intent, ndev,
                              hbm_bytes=hbm_bytes)
    best = greedy
    if budget and budget > 0:
        try:
            from .mcmc import optimize
            best = optimize(model, budget=budget, ndev=ndev,
                            seed=seed, start=greedy,
                            cost_model=cost_model)
            info["searched"] = True
            info["greedy_fallback"] = False
        except Exception as e:
            # the search is an OPTIMIZATION of recovery, never a
            # requirement: a cost-model/simulator failure must not turn
            # a survivable preemption into a dead job
            log_replan.warning(
                "strategy re-search failed (%s); recovering on the "
                "greedy clamped plan", e)
            best = greedy
    if plan_cache is not None:
        plan_cache.put(key, best, ndev, searched=bool(info["searched"]))
    info["replan_s"] = time.perf_counter() - t0
    return best, info


def replan_strategies(model, ndev: int,
                      old: Optional[StrategyMap] = None,
                      budget: int = 100, seed: int = 0,
                      cost_model=None, plan_cache=None,
                      hbm_bytes=None,
                      ) -> Tuple[StrategyMap, Dict[str, float]]:
    """Re-plan the per-op strategy map for `ndev` surviving devices.

    Returns ``(strategies, info)`` where info carries ``replan_s`` (wall
    time), ``searched`` (whether the MCMC walk actually ran),
    ``greedy_fallback`` (True when the search failed or the budget was
    exhausted and the clamped map shipped as-is) and ``plan_cache_hit``.
    Deterministic for fixed (model, ndev, old, budget, seed) — with or
    without a `plan_cache` (the cache key pins all of those, so a hit IS
    the plan a fresh search would produce). An INFEASIBLE projection
    raises :class:`ClampError` before any search — there is no
    survivable plan to fall back to, and the caller's recovery must
    surface the named op + reason rather than OOM blind.
    """
    old = old if old is not None else dict(model.strategies or {})
    return _searched_plan(model, old, ndev, budget, seed, cost_model,
                          plan_cache, hbm_bytes=hbm_bytes)


def expand_strategies(model, ndev: int,
                      old: Optional[StrategyMap] = None,
                      orig: Optional[StrategyMap] = None,
                      budget: int = 100, seed: int = 0,
                      cost_model=None, plan_cache=None,
                      hbm_bytes=None,
                      ) -> Tuple[StrategyMap, Dict[str, float]]:
    """Un-clamp the per-op strategy map onto a GROWN `ndev` (scale-UP).

    The intent projected onto the larger mesh is `orig` — the remembered
    pre-shrink plan, when the elastic layer has one for this device
    count — falling back to the running plan `old` per op. Projection is
    the PR 8 clamp machinery run in reverse: degrees grow back to the
    largest feasible values dividing the intent, row-shard degrees only
    to counts that still equal-block the table rows (the row-shard
    quantum), and a growth that would force an infeasible layout (a
    row-sharded table that can neither reshard onto the grown mesh nor
    fit replicated in HBM) raises :class:`ClampError` with op + reason
    instead of shipping a plan that OOMs mid-expand.

    Returns ``(strategies, info)`` with the same info keys (and the same
    determinism + plan-cache contract) as :func:`replan_strategies`.
    """
    old = old if old is not None else dict(model.strategies or {})
    intent = dict(orig or {})
    for name, pc in old.items():
        intent.setdefault(name, pc)
    return _searched_plan(model, intent, ndev, budget, seed, cost_model,
                          plan_cache, hbm_bytes=hbm_bytes)


def replace_strategies(model, sketches=None,
                       old: Optional[StrategyMap] = None,
                       ndev: Optional[int] = None,
                       budget: int = 100, seed: int = 0,
                       cost_model=None, plan_cache=None,
                       hbm_bytes=None,
                       ) -> Tuple[StrategyMap, Dict[str, float]]:
    """Re-plan hot/cold placement for DRIFTED traffic on an UNCHANGED
    device count (the online re-placement path, ``serve/replace.py``).

    The device topology is the same — what moved is the observed id
    distribution: `sketches` ({op -> IdFrequencySketch}, the live
    serving-side counts) is attached to the model so the skew cost terms
    (dedup pricing, hot-mass pricing — PR 11) see the NEW hot set, then
    the search runs warm-started from the running plan `old` exactly
    like a shrink/grow re-plan. Because (graph, topology, budget, seed,
    warm-start) are all unchanged from the original search, the plan
    cache key is extended with a digest of the sketches — without it the
    cache would return the pre-drift plan and online re-placement would
    be a cache-shaped no-op.

    Returns ``(strategies, info)`` with the :func:`replan_strategies`
    info keys. Deterministic for fixed (model, sketches, old, budget,
    seed); with ``budget=0`` the clamp of the running plan onto the same
    device count is the identity, which callers use as a bitwise-safe
    rehearsal of the swap machinery.
    """
    from ..utils.histogram import sketch_signature
    n = int(ndev if ndev is not None else model.mesh.size)
    if sketches:
        model.attach_id_histograms(sketches)
    old = old if old is not None else dict(model.strategies or {})
    return _searched_plan(model, old, n, budget, seed, cost_model,
                          plan_cache, hbm_bytes=hbm_bytes,
                          key_extra=f"|sketch={sketch_signature(sketches)}")
