"""Strategy re-planning for a changed device count (elastic recovery).

The MCMC search (`search/mcmc.py`) auto-discovers a SOAP strategy for a
FIXED machine model, like the reference's simulator-driven search
(model.cc:1093-1144). When preemption shrinks the fleet mid-run, the
surviving devices need a NEW strategy — Varuna-style re-planning: keep
what transfers from the old plan, re-search under the new constraint,
and always have a cheap greedy answer when the search budget is zero or
the search itself fails (recovery must never be the thing that dies).

Two layers:

- :func:`clamp_strategies` — deterministic, search-free projection of an
  existing strategy map onto a smaller device count: every partition
  degree drops to the largest feasible degree on the new factorized mesh
  that divides into the old intent, and joint assignability is repaired
  per-op. This is the greedy fallback AND the warm start for the search.
- :func:`replan_strategies` — clamp, then (budget permitting) re-run the
  simulated-annealing search constrained to the surviving count, seeded
  from the clamped map so the walk starts from a feasible, near-optimal
  point. Deterministic for a fixed seed — the elastic bit-identity test
  relies on an independent caller reproducing the same plan.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from ..core.op import InputOp
from ..parallel.mesh import structural_axis_sizes
from ..parallel.pconfig import ParallelConfig, StrategyMap
from ..parallel.sharding import clamp_degrees, clamp_param_degree
from ..utils.logging import get_logger

log_replan = get_logger("replan")


def clamp_strategies(model, strategies: Optional[StrategyMap],
                     ndev: int) -> StrategyMap:
    """Project `strategies` onto an `ndev`-device target (greedy re-plan).

    Per op: `parallel.sharding.clamp_degrees` drops every dim's degree
    to the largest feasible one on the ndev factorized mesh and repairs
    joint assignability. Ops missing from the old map (or with no map at
    all) get their default data-parallel config for ndev.
    """
    axis_sizes = structural_axis_sizes(ndev)
    strategies = dict(strategies or {})
    out: StrategyMap = {}
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        pc = strategies.get(op.name)
        if pc is None:
            out[op.name] = op.default_parallel_config(ndev)
            continue
        out[op.name] = ParallelConfig(
            clamp_degrees(pc.degrees, axis_sizes),
            device_type=pc.device_type,
            memory_types=pc.memory_types,
            # row-sharded tables RESHARD onto the survivors (the largest
            # feasible shard count), they don't fall back to replication
            # — replicating a >HBM table is exactly what cannot happen
            param_degree=clamp_param_degree(
                getattr(pc, "param_degree", 1), axis_sizes))
    return out


def replan_strategies(model, ndev: int,
                      old: Optional[StrategyMap] = None,
                      budget: int = 100, seed: int = 0,
                      cost_model=None,
                      ) -> Tuple[StrategyMap, Dict[str, float]]:
    """Re-plan the per-op strategy map for `ndev` surviving devices.

    Returns ``(strategies, info)`` where info carries ``replan_s`` (wall
    time), ``searched`` (whether the MCMC walk actually ran) and
    ``greedy_fallback`` (True when the search failed or the budget was
    exhausted and the clamped map shipped as-is). Deterministic for fixed
    (model, ndev, old, budget, seed).
    """
    t0 = time.perf_counter()
    old = old if old is not None else dict(model.strategies or {})
    greedy = clamp_strategies(model, old, ndev)
    info: Dict[str, float] = {"searched": False, "greedy_fallback": True}
    best = greedy
    if budget and budget > 0:
        try:
            from .mcmc import optimize
            best = optimize(model, budget=budget, ndev=ndev,
                            seed=seed, start=greedy,
                            cost_model=cost_model)
            info["searched"] = True
            info["greedy_fallback"] = False
        except Exception as e:
            # the search is an OPTIMIZATION of recovery, never a
            # requirement: a cost-model/simulator failure must not turn
            # a survivable preemption into a dead job
            log_replan.warning(
                "strategy re-search failed (%s); recovering on the "
                "greedy clamped plan", e)
            best = greedy
    info["replan_s"] = time.perf_counter() - t0
    return best, info
