"""MCMC (simulated-annealing) strategy search — the SOAP auto-parallelizer.

Port of the reference search (reference: FFModel::optimize
src/runtime/model.cc:1093-1144 — start from data-parallel; each iteration
`rewrite` re-randomizes one op's ParallelConfig (model.cc:1082-1091);
accept better always, worse with probability exp(-alpha * diff); runs at
compile() when --budget > 0, exports the best via --export).

The search space per op comes from Op.candidate_parallel_configs — the
GSPMD analog of Op::get_random_parallel_config (model.cc:295-324) — and
candidate feasibility is constrained by the factorized mesh axes
(parallel/sharding.AxisAssigner.feasible_degrees).
"""

from __future__ import annotations

import random
from typing import Dict, Optional, Tuple

from ..core.op import InputOp
from ..parallel.pconfig import ParallelConfig, StrategyMap
from ..parallel.sharding import AxisAssigner
from .cost_model import CostModel
from .simulator import Simulator


def default_strategy(model, ndev: int) -> StrategyMap:
    return {op.name: op.default_parallel_config(ndev)
            for op in model.ops if not isinstance(op, InputOp)}


def rewrite(model, strategies: StrategyMap, ndev: int,
            feasible, rng: random.Random) -> Tuple[StrategyMap, str]:
    """Re-randomize one op's config (reference FFModel::rewrite,
    model.cc:1082-1091)."""
    ops = [op for op in model.ops if not isinstance(op, InputOp)]
    op = rng.choice(ops)
    cands = op.feasible_parallel_configs(ndev, feasible)
    if not cands:
        return strategies, op.name
    new = dict(strategies)
    new[op.name] = rng.choice(cands)
    return new, op.name


def optimize(model, budget: int = 1000, alpha: float = 1.2,
             ndev: Optional[int] = None,
             cost_model: Optional[CostModel] = None,
             seed: int = 0, verbose: bool = False,
             start: Optional[StrategyMap] = None,
             topology=None) -> StrategyMap:
    """Simulated-annealing search over per-op parallel configs (reference
    FFModel::optimize, model.cc:1093-1144). Returns the best strategy map.
    `topology` targets a specific device topology (e.g.
    [("dcn", 2), ("ici", 4)] for a 2-host slice pair) — comm-heavy
    configs price differently than on the default flat ICI mesh.
    """
    import math


    if ndev is None:
        ndev = model.config.num_devices
    if model.mesh is not None and model.mesh.size == ndev:
        feasible = AxisAssigner(model.mesh).feasible_degrees()
    else:
        # OFFLINE search for an ndev-device target from a smaller host
        # (e.g. planning a v5e-64 strategy on one chip — the reference
        # must run its search ON the target cluster, simulator.cu:79-109;
        # the analytical/measured cost model frees us from that): use the
        # structural factorization make_mesh would produce
        from ..parallel.mesh import structural_axis_sizes
        from ..parallel.sharding import feasible_degrees_for
        feasible = feasible_degrees_for(structural_axis_sizes(ndev))
    rng = random.Random(seed)
    sim = Simulator(model, cost_model, topology=topology)

    current = dict(start or default_strategy(model, ndev))
    current_t = sim.simulate(current, ndev)
    best, best_t = dict(current), current_t

    for it in range(budget):
        proposal, changed = rewrite(model, current, ndev, feasible, rng)
        t = sim.simulate(proposal, ndev)
        # reference acceptance: always if faster, else exp(-alpha * diff)
        # with diff in the simulator's time units (model.cc:1118-1126).
        # Infeasible (inf-cost) states need care: inf - inf is NaN, which
        # would reject every move and freeze the walk — accept free moves
        # within the infeasible region so the search can escape it.
        if not math.isfinite(t) and not math.isfinite(current_t):
            accept = True
        elif t < current_t:
            accept = True
        else:
            diff = (t - current_t) * 1e3   # s -> ms, the reference's unit
            accept = (math.isfinite(diff)
                      and rng.random() < math.exp(-alpha * diff))
        if accept:
            current, current_t = proposal, t
            if t < best_t:
                best, best_t = dict(proposal), t
                if verbose:
                    print(f"[search] iter {it}: {t * 1e3:.3f} ms "
                          f"(changed {changed})")
    if verbose:
        print(f"[search] best simulated step: {best_t * 1e3:.3f} ms "
              f"vs DP {sim.simulate(default_strategy(model, ndev), ndev) * 1e3:.3f} ms")
    return best
