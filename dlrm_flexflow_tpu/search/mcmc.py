"""MCMC (simulated-annealing) strategy search — the SOAP auto-parallelizer.

Port of the reference search (reference: FFModel::optimize
src/runtime/model.cc:1093-1144 — start from data-parallel; each iteration
`rewrite` re-randomizes one op's ParallelConfig (model.cc:1082-1091);
accept better always, worse with probability exp(-alpha * diff); runs at
compile() when --budget > 0, exports the best via --export).

The search space per op comes from Op.candidate_parallel_configs — the
GSPMD analog of Op::get_random_parallel_config (model.cc:295-324) — and
candidate feasibility is constrained by the factorized mesh axes
(parallel/sharding.AxisAssigner.feasible_degrees).
"""

from __future__ import annotations

import random
from typing import Dict, Optional, Tuple

from ..core.op import InputOp
from ..parallel.pconfig import ParallelConfig, StrategyMap
from ..parallel.sharding import AxisAssigner
from .cost_model import CostModel
from .simulator import Simulator


def default_strategy(model, ndev: int) -> StrategyMap:
    return {op.name: op.default_parallel_config(ndev)
            for op in model.ops if not isinstance(op, InputOp)}


def rewrite(model, strategies: StrategyMap, ndev: int,
            feasible, rng: random.Random) -> Tuple[StrategyMap, str]:
    """Re-randomize one op's config (reference FFModel::rewrite,
    model.cc:1082-1091)."""
    ops = [op for op in model.ops if not isinstance(op, InputOp)]
    op = rng.choice(ops)
    cands = op.feasible_parallel_configs(ndev, feasible)
    if not cands:
        return strategies, op.name
    new = dict(strategies)
    new[op.name] = rng.choice(cands)
    return new, op.name


def optimize(model, budget: int = 1000, alpha: float = 1.2,
             ndev: Optional[int] = None,
             cost_model: Optional[CostModel] = None,
             seed: int = 0, verbose: bool = False,
             start: Optional[StrategyMap] = None,
             topology=None) -> StrategyMap:
    """Simulated-annealing search over per-op parallel configs (reference
    FFModel::optimize, model.cc:1093-1144). Returns the best strategy map.
    `topology` targets a specific device topology (e.g.
    [("dcn", 2), ("ici", 4)] for a 2-host slice pair) — comm-heavy
    configs price differently than on the default flat ICI mesh.
    """
    import math


    if ndev is None:
        ndev = model.config.num_devices
    if model.mesh is not None and model.mesh.size == ndev:
        feasible = AxisAssigner(model.mesh).feasible_degrees()
    else:
        # OFFLINE search for an ndev-device target from a smaller host
        # (e.g. planning a v5e-64 strategy on one chip — the reference
        # must run its search ON the target cluster, simulator.cu:79-109;
        # the analytical/measured cost model frees us from that): use the
        # structural factorization make_mesh would produce
        from ..parallel.mesh import structural_axis_sizes
        from ..parallel.sharding import feasible_degrees_for
        feasible = feasible_degrees_for(structural_axis_sizes(ndev))
    rng = random.Random(seed)
    sim = Simulator(model, cost_model, topology=topology)

    def _overlap_flip(pc: ParallelConfig) -> ParallelConfig:
        return ParallelConfig(
            pc.degrees, pc.device_type, pc.device_ids, pc.memory_types,
            param_degree=getattr(pc, "param_degree", 1),
            exchange=getattr(pc, "exchange", "dense"),
            hot_fraction=getattr(pc, "hot_fraction", 0.0),
            quant_dtype=getattr(pc, "quant_dtype", ""),
            quant_update=getattr(pc, "quant_update", ""),
            overlap=not getattr(pc, "overlap", False))

    def _overlap_sweep(plan, plan_t):
        """Greedy per-op minimization over the binary exchange-schedule
        toggle, holding the sharding fixed."""
        if plan_t is None:
            plan_t = sim.simulate(plan, ndev)
        improved = True
        while improved:
            improved = False
            for op in model.ops:
                if isinstance(op, InputOp):
                    continue
                pc = plan.get(op.name)
                if pc is None or getattr(pc, "param_degree", 1) <= 1:
                    continue
                trial = dict(plan)
                trial[op.name] = _overlap_flip(pc)
                t = sim.simulate(trial, ndev)
                if t < plan_t:
                    plan, plan_t = trial, t
                    improved = True
        return plan, plan_t

    # the warm start is schedule-minimized too: a replan handing in a
    # serial row-sharded plan should not need the walk to rediscover
    # the pipelined variant of the very shards it started with
    current, current_t = _overlap_sweep(
        dict(start or default_strategy(model, ndev)), None)
    best, best_t = dict(current), current_t

    for it in range(budget):
        proposal, changed = rewrite(model, current, ndev, feasible, rng)
        t = sim.simulate(proposal, ndev)
        # nested schedule minimization: ParallelConfig.overlap moves the
        # SAME bytes over the same shards and only changes the exchange
        # schedule, so it is never a separate candidate in the proposal
        # space (twin candidates would dilute the walk exactly where
        # budgets are tight — see _row_shard_candidates' skew gating for
        # the same reasoning). Instead each row-sharded move is priced
        # under BOTH schedules and takes the better: the simulator's
        # overlapped task graph decides, so plans with an exposed-compute
        # window pipeline their exchange and window-less plans keep the
        # fused collective (whose decomposition overhead overlap would
        # pay for nothing).
        pcc = proposal.get(changed)
        if pcc is not None and getattr(pcc, "param_degree", 1) > 1:
            alt = dict(proposal)
            alt[changed] = _overlap_flip(pcc)
            t_alt = sim.simulate(alt, ndev)
            if t_alt < t:
                proposal, t = alt, t_alt
        # reference acceptance: always if faster, else exp(-alpha * diff)
        # with diff in the simulator's time units (model.cc:1118-1126).
        # Infeasible (inf-cost) states need care: inf - inf is NaN, which
        # would reject every move and freeze the walk — accept free moves
        # within the infeasible region so the search can escape it.
        if not math.isfinite(t) and not math.isfinite(current_t):
            accept = True
        elif t < current_t:
            accept = True
        else:
            diff = (t - current_t) * 1e3   # s -> ms, the reference's unit
            accept = (math.isfinite(diff)
                      and rng.random() < math.exp(-alpha * diff))
        if accept:
            current, current_t = proposal, t
            if t < best_t:
                best, best_t = dict(proposal), t
                if verbose:
                    print(f"[search] iter {it}: {t * 1e3:.3f} ms "
                          f"(changed {changed})")
    # final sweep of the same schedule toggle over ops the walk never
    # revisited — joint windows only exist once ALL the accepted
    # shardings are in place
    best, best_t = _overlap_sweep(best, best_t)
    if verbose:
        print(f"[search] best simulated step: {best_t * 1e3:.3f} ms "
              f"vs DP {sim.simulate(default_strategy(model, ndev), ndev) * 1e3:.3f} ms")
    return best
