"""TPU cost model for the strategy-search simulator.

Parity with the reference device model (reference: include/simulator.h:29-129,
src/runtime/simulator.cu:21-76 — per-GPU compute devices plus comm devices
with fixed bandwidths: inter-GPU 20 MB/ms, inter-node 12/numNodes, GPU⇄DRAM
16, simulator.cu:27-29; per-op times measured by running the real kernels,
memoized by (op, config) hash, simulator.cc:235-273).

TPU redesign: per-op compute time is a roofline estimate —
max(FLOPs / MXU_rate, bytes_touched / HBM_bw) — optionally *calibrated* by
timing the op's compiled XLA subgraph on the real chip (cost_model
measure=True), which replaces the reference's cudaEvent microbenchmarks.
XLA fuses ops, so isolated-op timing over-counts; the analytical model is
the default and measured times refine it (SURVEY.md §7 hard-part #3).
Comm time uses ICI/DCN bandwidths instead of the reference's constants.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax.numpy as jnp

from ..core.op import InputOp, Op
from ..parallel.pconfig import ParallelConfig
from ..utils.logging import log_sim

# Measured per-train-step dispatch floor on the tunneled v5e (round 5,
# 500-step pipelined windows; the additive share fitting all 12
# calibration points — see per_step_overhead_s below, which this pins).
# benchmarks/calibrate_sim.py re-measures the floor every sweep (the
# K→∞ intercept of the bench_superstep ms/step-vs-1/K line) and records
# the fresh value in benchmarks/dispatch_floor.json next to this
# constant, so future rounds can tell floor drift (the documented ~1.5×
# tunnel volatility, BENCHMARKS.md r5) from code regressions.
# RE-MEASURED round 6 after the fused interaction kernel shrank the
# dispatch body (fewer HLOs per step → less per-dispatch host work):
# the K→∞ intercept came back 0.52 ms, within the pinned value's noise
# band, so the pin stands (benchmarks/dispatch_floor.json records both).
MEASURED_DISPATCH_FLOOR_S = 5.5e-4

# fraction of a PIPELINED (ParallelConfig.overlap) row-shard exchange
# XLA's async collective scheduler actually hides under independent
# dense compute, when such a window exists. Measured by
# benchmarks/calibrate_sim.measure_overlap_window (ratio of the step
# speedup to the exchange time it could have hidden) and recorded in
# benchmarks/overlap_calibration.json, which overrides this default at
# load; 0.85 is the round-6 measured value on the tunneled v5e — the
# last ~15% is the rounds whose results feed the immediately-following
# gather and cannot move off the critical path.
OVERLAP_EFFICIENCY_DEFAULT = 0.85

_OVERLAP_CAL_CACHE = {"loaded": False, "data": None}


def load_overlap_calibration() -> Optional[dict]:
    """The committed overlap-window calibration artifact
    (benchmarks/overlap_calibration.json), or None when absent. Cached
    after the first read — the cost model consults it inside the MCMC
    hot loop."""
    if not _OVERLAP_CAL_CACHE["loaded"]:
        import json
        import os
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "benchmarks", "overlap_calibration.json")
        data = None
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = None
        _OVERLAP_CAL_CACHE["data"] = data
        _OVERLAP_CAL_CACHE["loaded"] = True
    return _OVERLAP_CAL_CACHE["data"]


@dataclass
class TPUSpec:
    """Per-chip hardware model. Defaults are TPU v5e (public numbers:
    197 bf16 TFLOP/s MXU, 819 GB/s HBM, 4 ICI links × ~50 GB/s per
    direction; DCN ~ 25 GB/s per host)."""

    name: str = "v5e"
    mxu_flops: float = 197e12         # bf16 FLOP/s
    mxu_flops_f32: float = 49e12      # fp32 FLOP/s
    hbm_bytes_per_s: float = 819e9
    ici_bytes_per_s: float = 45e9     # per link per direction
    ici_links: int = 4
    dcn_bytes_per_s: float = 25e9
    mxu_utilization: float = 0.55     # achievable fraction on real workloads
    hbm_utilization: float = 0.75
    kernel_launch_s: float = 2e-6     # per-HLO overhead (XLA fused ≈ small)
    hbm_capacity_bytes: float = 16e9  # v5e HBM per chip
    vmem_bytes: int = 128 * 1024 * 1024  # per-core VMEM (v4+ generations)
    # RANDOM HBM row-access model (embedding gather/scatter): fixed setup
    # plus per-row sustained cost. RE-PINNED round 5 (the round-2 numbers
    # were poisoned by the dynamic-roll bottleneck that sat in the same
    # measured path): in-graph XLA gathers of fresh random 512 B rows
    # from a 2 GB table measure 489 µs @ 2k rows, 847 µs @ 8k, 1.06 ms @
    # 32k, 1.58 ms @ 128k — a ~0.5 ms setup plus ~10 ns/row sustained
    # (HBM bank parallelism + deep DMA pipelining; the old 0.3 µs/row
    # figure was off 25x).
    # (the ~0.5 ms setup seen by an ISOLATED in-scan gather is mostly
    # loop artifact — in composed graphs gathers overlap surrounding
    # work, so the modeled fixed cost is far smaller)
    hbm_random_fixed_s: float = 0.5e-4
    hbm_random_row_s: float = 1.2e-8
    # random-row SCATTER (the touched-rows update): per-raw-lookup cost
    # of the whole update machinery — lane pack + dedup sort + the
    # 64-deep write-DMA scatter (_SCATTER_B) — measured r5 on kaggle
    # (26k lookups, 2.7 ms step) and dlrm_random; ~2x the pipelined
    # gather rate because the sort/pack passes ride along, not because
    # the writes themselves are slow
    hbm_scatter_row_s: float = 2.6e-8
    # irreducible per-TRAIN-STEP overhead (dispatch + epilogue) at steady
    # pipelined state: a one-dense-layer model's full train step floors
    # at ~820 µs on the tunneled v5e (500-step windows, round 5), but a
    # compute-heavier graph (mlp_heavy, real 794 µs total) shows device
    # work partially HIDES under the host-side floor — ~550 µs (0.55 ms,
    # BENCHMARKS.md r5) is the additive share that fits all 12
    # calibration points; without it every small-step model
    # under-predicts (the r4 measured-mode DLRM-family bias)
    per_step_overhead_s: float = MEASURED_DISPATCH_FLOOR_S
    # host-resident tables: PCIe host<->device link and host-DRAM random
    # row cost (the reference prices GPU<->DRAM at 16 MB/ms,
    # simulator.cu:27-29; v5e host link ~ PCIe gen3/4)
    pcie_bytes_per_s: float = 16e9
    # host DDR random row access is SLOWER than HBM random access (~60-100
    # ns DRAM latency, no HBM bank parallelism); pricing it cheaper would
    # make the simulator prefer host tables over HBM tables, inverting the
    # measured reality (benchmarks/bench_host_tables.py)
    host_random_row_s: float = 6.0e-7
    host_bytes_per_s: float = 50e9    # host DDR sequential stream
    # per-ROUND overhead of the pipelined (decomposed) row-shard
    # exchange: each ppermute ring hop / capacity chunk is its own
    # collective-start/-done pair, so decomposing a fused all-to-all
    # into k rounds pays k extra launches plus the scheduler's fence
    # bookkeeping. Measured round 6 alongside the overlap window
    # (benchmarks/overlap_calibration.json overrides); THE term that
    # makes overlap lose when there is no compute window to hide in —
    # without it the search would flip overlap on everywhere for free
    overlap_round_overhead_s: float = 8e-6
    # fixed OVERHEAD per serial scan iteration (lax.scan bookkeeping +
    # carry round-trip), on top of the cell's own FLOP/bandwidth cost.
    # PINNED by direct measurement (round 4): an NMT-sized cell (b64,
    # h1024, bf16) costs ~32 us/iteration marginal, of which ~27 us is
    # the cell's HBM weight re-stream (priced separately in
    # _roofline_time's scan term) — the residual loop overhead is ~5 us;
    # 10 us keeps a margin for smaller cells where bookkeeping dominates
    scan_iter_s: float = 1.0e-5

    def per_step_overhead_amortized(self, superstep: int = 1) -> float:
        """Dispatch floor per TRAINED step when K steps fuse into one
        dispatch (core/model.py _train_superstep: a lax.scan over K
        pre-staged batches inside one executable). One host→device
        dispatch then trains K steps, so the per-step share of the floor
        is ``per_step_overhead_s / K`` — the simulator must price this
        or it would call every floor-bound small-batch config K× slower
        than the fused runtime actually runs it."""
        return self.per_step_overhead_s / max(int(superstep), 1)

    @staticmethod
    def v4() -> "TPUSpec":
        return TPUSpec(name="v4", mxu_flops=275e12, mxu_flops_f32=69e12,
                       hbm_bytes_per_s=1228e9, ici_bytes_per_s=50e9,
                       ici_links=6, hbm_capacity_bytes=32e9)

    def apply_env_overrides(self) -> "TPUSpec":
        """Honor FF_ICI_GBPS / FF_DCN_GBPS (GB/s, per link / per host):
        pod-pricing knobs so a strategy search for a machine with a
        different interconnect needs no code edit. Strict parsing (the
        FLX401 contract): a malformed value raises naming the variable
        instead of silently running with defaults."""
        import os

        from ..utils.faults import _env_float
        for var, attr in (("FF_ICI_GBPS", "ici_bytes_per_s"),
                          ("FF_DCN_GBPS", "dcn_bytes_per_s")):
            raw = os.environ.get(var)
            if raw is not None and raw != "":
                val = _env_float(var, raw)
                if val <= 0:
                    raise ValueError(
                        f"{var} must be a positive bandwidth in GB/s, "
                        f"got {raw!r}")
                setattr(self, attr, val * 1e9)
        return self

    @staticmethod
    def detect() -> "TPUSpec":
        """Pick the spec matching the attached accelerator (falls back to
        the v5e defaults off-TPU), then apply FF_ICI_GBPS/FF_DCN_GBPS
        env overrides."""
        try:
            import jax
            kind = jax.devices()[0].device_kind.lower()
        except Exception:
            return TPUSpec().apply_env_overrides()
        if "v4" in kind:
            return TPUSpec.v4().apply_env_overrides()
        if "v5p" in kind or "v5 p" in kind:
            return TPUSpec(name="v5p", mxu_flops=459e12, mxu_flops_f32=115e12,
                           hbm_bytes_per_s=2765e9, ici_bytes_per_s=100e9,
                           ici_links=6, hbm_capacity_bytes=95e9
                           ).apply_env_overrides()
        if "v6" in kind:
            return TPUSpec(name="v6e", mxu_flops=918e12, mxu_flops_f32=230e12,
                           hbm_bytes_per_s=1640e9, ici_bytes_per_s=90e9,
                           ici_links=4, hbm_capacity_bytes=32e9
                           ).apply_env_overrides()
        return TPUSpec().apply_env_overrides()


class CostModel:
    """Per-op/per-config compute and comm times, memoized like the
    reference's hash-keyed measurements (simulator.cc:241-249)."""

    def __init__(self, spec: Optional[TPUSpec] = None,
                 compute_dtype=jnp.bfloat16, measure: bool = False):
        self.spec = spec or TPUSpec()
        self.compute_dtype = compute_dtype
        self.measure = measure
        self._cache: Dict[Tuple, float] = {}

    # ---- helpers --------------------------------------------------------
    def _flops_rate(self) -> float:
        rate = (self.spec.mxu_flops
                if jnp.dtype(self.compute_dtype) == jnp.dtype(jnp.bfloat16)
                else self.spec.mxu_flops_f32)
        return rate * self.spec.mxu_utilization

    def _hbm_rate(self) -> float:
        return self.spec.hbm_bytes_per_s * self.spec.hbm_utilization

    @staticmethod
    def _shard_elems(op: Op, pc: ParallelConfig) -> float:
        t = op.outputs[0]
        return math.prod(t.shape) / max(pc.num_parts, 1)

    # ---- per-op compute -------------------------------------------------
    def op_compute_time(self, op: Op, pc: ParallelConfig,
                        backward: bool = False) -> float:
        """Roofline time for one device's shard of `op` (seconds)."""
        # residency/device-type must key the cache: a ZCM config and an
        # HBM config with equal degrees have sharply different costs, and
        # MCMC rewrite proposals compare exactly such pairs (the PARAM-
        # axis row-shard degree — and its skew policies — likewise
        # change the update/comm shape)
        key = (op.name, pc.degrees, getattr(pc, "param_degree", 1),
               getattr(pc, "exchange", "dense"),
               getattr(pc, "hot_fraction", 0.0),
               getattr(pc, "overlap", False),
               pc.device_type, pc.memory_types, backward)
        if key in self._cache:
            return self._cache[key]

        if self.measure:
            # calibrated mode: time the op's compiled subgraph on the real
            # device (reference measures forward AND backward separately,
            # linear.cu:973-1049 / simulator.cc:235-273) — BLENDED with
            # the calibrated roofline: on a tunneled/shared chip a sub-ms
            # op's measurement can carry multiples of dispatch noise (or
            # run degenerately fast), so a raw reading that strays beyond
            # a 2x band around the roofline is evidence of measurement
            # failure, not of the op's true cost. Clamping to the band
            # keeps measured mode at-least-roofline-grade (validated on
            # benchmarks/sim_calibration.json; round-2's unclamped mode
            # was WORSE than the roofline it was meant to refine).
            t_raw = self.measure_op(op, pc, backward=backward)
            t_roof = self._roofline_time(op, pc, backward)
            # scanned ops keep a somewhat wider band: their roofline is
            # calibrated (r4: scan weight re-stream priced, scan_iter_s
            # pinned by measurement) but serial scans still measure
            # noisier than single kernels on a shared chip
            band = (3.0 if op.sequential_steps(pc, self.spec.vmem_bytes)
                    else 2.0)
            t = min(max(t_raw, t_roof / band), band * t_roof)
            if t != t_raw:
                log_sim.debug(
                    "measured %s %s bwd=%s: %.3es outside the roofline "
                    "band [%.3es, %.3es]; clamped",
                    op.name, pc.degrees, backward, t_raw,
                    t_roof / band, band * t_roof)
        else:
            t = self._roofline_time(op, pc, backward)
        self._cache[key] = t
        return t

    @staticmethod
    def _host_resident(op: Op, pc: ParallelConfig) -> bool:
        """True only for host-RESIDENT tables (ZCM memory). A bare CPU
        device_type without ZCM is compute-offload — its tables still
        live in HBM and MUST count against capacity."""
        if not hasattr(op, "host_lookup"):
            return False
        if op.name in getattr(op.model, "_host_resident_ops", set()):
            return True
        return "ZCM" in pc.memory_types

    def _roofline_time(self, op: Op, pc: ParallelConfig,
                       backward: bool = False) -> float:
        if self._host_resident(op, pc):
            # forward: host gather (DRAM random rows) + rows over PCIe
            # down; backward: cotangents staged host-ward over PCIe — the
            # touched-rows scatter itself is priced on the UPDATE task
            # (simulator._build_tasks), not here, so it isn't charged twice
            out_bytes = self.tensor_bytes(op.outputs[0])
            t = (self.spec.hbm_random_fixed_s
                 + out_bytes / self.spec.pcie_bytes_per_s)
            if not backward:
                t += (op.random_hbm_rows(False, raw=True)
                      * self.spec.host_random_row_s)
            return t
        batch = op.outputs[0].shape[0] if op.outputs[0].num_dims > 0 else 1
        flops = op.flops_per_sample() * batch / max(pc.num_parts, 1)
        # bytes: inputs read + outputs written (+ params read), sharded;
        # dtype-aware (activations stream at compute-dtype width)
        io_bytes = sum(self.tensor_bytes(t) for t in op.inputs)
        io_bytes += self.tensor_bytes(op.outputs[0])
        io_bytes /= max(pc.num_parts, 1)
        # params: bytes this shard actually streams per step (a sparse-
        # update embedding touches only its gathered rows, not the
        # multi-GB table)
        p_touch = op.param_bytes_touched_per_step(max(pc.num_parts, 1))
        io_bytes += p_touch
        steps = op.sequential_steps(pc, self.spec.vmem_bytes)
        if steps > 1 and not op.scan_weights_resident(
                pc, self.spec.vmem_bytes):
            # a serial scan re-streams its IN-LOOP weights from HBM on
            # EVERY iteration (measured round 4: the NMT LSTM cell's
            # marginal per-iteration wall time ≈ its bf16 weight-stream
            # time — XLA does not pin scan weights in VMEM at these
            # sizes; the pallas resident kernel does, and then skips
            # this). Only scan_param_stream_bytes counts — hoisted
            # input projections stream once. (steps - 1) extra passes
            # at compute-dtype width (the 4 B fp32 master read is
            # already counted once above)
            stream = op.scan_param_stream_bytes()
            itemsize = jnp.dtype(self.compute_dtype).itemsize
            io_bytes += (steps - 1) * stream * (itemsize / 4.0)
        io_bytes *= op.hbm_io_factor()
        if backward:
            # bwd ≈ 2x fwd flops (dX and dW gemms), grads written.
            # For scanned ops the dX chain re-streams weights like the
            # forward scan, but dW is ONE stacked gemm over all
            # timesteps (XLA's scan vjp stacks the residuals), so bwd
            # io ≈ 1.25x fwd, not 2x (measured r4: NMT bwd ≈ 1.15x fwd)
            flops *= 2.0
            io_bytes *= 1.25 if steps > 1 else 2.0
        rate = self._flops_rate() * op.mxu_utilization_factor()
        t = max(flops / rate, io_bytes / self._hbm_rate())
        # random-row HBM accesses (embedding gathers) are latency-bound,
        # not bandwidth-bound — the dominant term for sparse ops
        rand_rows = op.random_hbm_rows(backward) / max(pc.num_parts, 1)
        if (not backward and rand_rows > 0
                and getattr(pc, "param_degree", 1) > 1
                and hasattr(op, "_row_shard_geometry")
                and (getattr(pc, "exchange", "dense") == "dedup"
                     or getattr(pc, "hot_fraction", 0.0) > 0)):
            # skew-aware routed gather: owners gather one row per
            # DISTINCT routed id (dedup collapses duplicates before the
            # exchange; hot lookups hit the small replicated hot block,
            # which streams like the tiny tables above)
            from ..ops.embedding import (_lookup_count,
                                         expected_routed_lookups)
            n_dev = _lookup_count(op) / max(pc.num_parts, 1)
            rand_rows = min(rand_rows,
                            expected_routed_lookups(op, pc, n_dev))
        t = max(t, self.random_rows_time(rand_rows))
        # serial scan iterations floor at the per-iteration loop
        # overhead; the vjp of a scan runs its own reverse-order scan
        if steps:
            t = max(t, steps * self.spec.scan_iter_s)
        return t + self.spec.kernel_launch_s

    def host_update_time(self, op: Op, pc: ParallelConfig) -> float:
        """Update cost for a host-RESIDENT (ZCM) table. Pairs with the
        host branch of _roofline_time: the touched-rows scatter is priced
        HERE (on the update task) and nowhere else, so forward/backward
        must not charge it. Host DRAM is one shared resource — rows are
        not divided by num_parts."""
        if op.update_random_hbm_rows(pc) > 0:
            # sparse path: host RMW scatter = 2 accesses per looked-up
            # row (read + write; the 1.6x write-only discount is
            # structural to the Pallas lane-packed TPU path and does not
            # exist on the host), plus read+write per optimizer state
            # slab — mirrors the device path's _embedding_update_rows
            opt = getattr(op.model, "optimizer", None)
            nslabs = len(opt.sparse_slab_names()) if opt is not None else 0
            rows = (2.0 + 2.0 * nslabs) * op.random_hbm_rows(False,
                                                             raw=True)
            return (self.spec.hbm_random_fixed_s
                    + rows * self.spec.host_random_row_s)
        # dense fallback (momentum/Adam without sparse state): stream the
        # FULL table read+write+state through host DDR, at each param's
        # DECLARED dtype (a bf16 table streams half the fp32 bytes —
        # hardcoding 4 B over-billed it)
        full_bytes = sum(
            math.prod(d.shape) * jnp.dtype(d.dtype).itemsize
            for d in op.param_defs().values())
        return full_bytes * 3.0 / self.spec.host_bytes_per_s

    def dedup_overhead_time(self, op, ndev: int) -> float:
        """Sender-side cost of the dedup-before-exchange machinery
        (parallel/alltoall.py): two stable sorts + segment sums over
        the local lookup ids (~8 streaming passes of 4 B each) plus one
        gather/scatter of the returned rows through the inverse map.

        THE term that makes dedup lose on uniform ids: the exchange
        barely shrinks (every id is distinct) but the sort still runs
        every step — so the MCMC search only picks the dedup'd exchange
        when the observed histogram's duplicate mass pays for it
        (README troubleshooting: "dedup slower than dense on uniform
        ids")."""
        from ..ops.embedding import _lookup_count
        n_dev = _lookup_count(op) / max(ndev, 1)
        d = getattr(op, "out_dim", 0)
        isz = jnp.dtype(self.compute_dtype).itemsize
        bytes_ = 8.0 * n_dev * 4.0 + 2.0 * n_dev * d * isz
        return bytes_ / self._hbm_rate()

    def overlap_efficiency(self) -> float:
        """Fraction of a pipelined exchange the async scheduler hides
        under independent compute — the calibrated value
        (benchmarks/overlap_calibration.json, written by
        calibrate_sim.measure_overlap_window) or the pinned round-6
        default. Clamped to [0, 1): a measured value >= 1 would price
        overlapped exchanges as free and below-zero would price them
        slower than serial, both measurement artifacts."""
        cal = load_overlap_calibration()
        eff = OVERLAP_EFFICIENCY_DEFAULT
        if cal and isinstance(cal.get("overlap_efficiency"), (int, float)):
            eff = float(cal["overlap_efficiency"])
        return min(max(eff, 0.0), 0.99)

    def overlap_round_overhead(self, rounds: int) -> float:
        """Fixed cost of DECOMPOSING one fused exchange into `rounds`
        independent collectives (ppermute ring hops / capacity chunks):
        each round is its own collective-start/-done pair. Charged on
        the participating compute devices — it is host/scheduler work
        that does not hide."""
        cal = load_overlap_calibration()
        per = self.spec.overlap_round_overhead_s
        if cal and isinstance(cal.get("round_overhead_s"), (int, float)):
            per = float(cal["round_overhead_s"])
        return max(int(rounds), 0) * per

    def exposed_exchange_time(self, exchange_s: float,
                              window_s: float,
                              overlap: bool,
                              rounds: int = 0) -> float:
        """THE overlap term (ISSUE 19): the exchange time a step still
        PAYS given an exposed-compute window of `window_s` (compute with
        no data dependence on the exchange, which the async scheduler
        can run under it). Serial exchanges pay everything; pipelined
        ones hide `overlap_efficiency` of the window's worth and pay
        the decomposition overhead. shardcheck's FLX514 and the
        simulator's schedule both derive from this accounting."""
        if not overlap:
            return float(exchange_s)
        eff = self.overlap_efficiency()
        hidden = eff * min(float(window_s), float(exchange_s))
        return (float(exchange_s) - hidden
                + self.overlap_round_overhead(rounds))

    def random_rows_time(self, rows: float) -> float:
        if rows <= 0:
            return 0.0
        return (self.spec.hbm_random_fixed_s
                + rows * self.spec.hbm_random_row_s)

    def scatter_rows_time(self, rows: float) -> float:
        """Touched-rows UPDATE scatter: same fixed setup, slower per-row
        sustained rate (write DMAs drain every 64-tile block — the
        Pallas kernels' _SCATTER_B)."""
        if rows <= 0:
            return 0.0
        return (self.spec.hbm_random_fixed_s
                + rows * self.spec.hbm_scatter_row_s)

    def tensor_bytes(self, t) -> float:
        """Dtype-aware byte size: float activations flow in the model's
        compute dtype (bf16 halves comm/IO vs the old flat 4 B/elem);
        integer tensors (indices) keep their declared dtype."""
        dt = jnp.dtype(t.dtype)
        if jnp.issubdtype(dt, jnp.floating):
            dt = jnp.dtype(self.compute_dtype)
        return float(math.prod(t.shape)) * dt.itemsize

    # ---- comm -----------------------------------------------------------
    # The reference prices inter-GPU and inter-node transfers distinctly
    # (simulator.cu:27-29: 20 MB/ms NVLink, 12/numNodes MB/ms inter-node)
    # and gives each GPU its own comm devices (simulator.cu:21-76). The
    # TPU analog: per-MESH-AXIS channels — a collective over an "ici" axis
    # rides that axis's torus links at ring-allreduce bandwidth, a
    # collective over the "dcn" (multi-slice) axis rides the data-center
    # network. Collectives on different axes use disjoint links and run
    # concurrently; collectives on the same axis contend (the Simulator
    # serializes them on the axis's channel).

    def axis_bw(self, kind: str) -> float:
        if kind == "dcn":
            return self.spec.dcn_bytes_per_s
        # bidirectional ring over ICI: effective algorithm bandwidth
        return self.spec.ici_bytes_per_s * self.spec.ici_links

    def allreduce_time_axes(self, bytes_per_dev: float, axes) -> float:
        """Hierarchical ring all-reduce over `axes` = [(kind, size), ...]:
        phase i moves 2·B·(n−1)/n at its axis's bandwidth, with B shrinking
        by each completed phase's factor (reduce-scatter hierarchy)."""
        t, b = 0.0, float(bytes_per_dev)
        for kind, size in axes:
            if size <= 1:
                continue
            t += 2.0 * b * (size - 1) / size / self.axis_bw(kind)
            b /= size
        return t

    def _ici_allreduce_bw(self) -> float:
        return self.axis_bw("ici")

    def alltoall_time_axes(self, bytes_per_dev: float, axes) -> float:
        """All-to-all over `axes` = [(kind, size), ...]: each device
        exchanges (size−1)/size of its `bytes_per_dev` payload with its
        peers along that axis at the axis's bandwidth — the lookup/row
        exchange of row-sharded embedding tables. Hierarchical like
        allreduce_time_axes: a multi-axis shard group pays each axis's
        phase on that axis's channel."""
        t, b = 0.0, float(bytes_per_dev)
        for kind, size in axes:
            if size <= 1:
                continue
            t += b * (size - 1) / size / self.axis_bw(kind)
        return t

    def resharding_time(self, tensor_bytes: float, src_pc: ParallelConfig,
                        dst_pc: ParallelConfig,
                        kind: str = "ici") -> float:
        """Cost of moving a tensor from the producer's sharding to the
        consumer's (the reference gets this implicitly from Legion region
        intersections, simulator.cc:279-326; GSPMD emits collectives).
        `kind` picks the channel the move rides ("dcn" when the redistri-
        bution crosses the slice axis). PARAM-axis (row-shard) degrees
        count as parts too: resharding a row-sharded table (elastic
        recovery) is an all-to-all of the row blocks."""
        pd_s = max(getattr(src_pc, "param_degree", 1), 1)
        pd_d = max(getattr(dst_pc, "param_degree", 1), 1)
        if src_pc.degrees == dst_pc.degrees and pd_s == pd_d:
            return 0.0
        # approximate: every device re-reads its destination shard from
        # peers — an all-to-all of the full tensor over the channel
        moved = tensor_bytes * (1.0 - 1.0 / max(src_pc.num_parts * pd_s,
                                                dst_pc.num_parts * pd_d,
                                                1))
        return moved / self.axis_bw(kind)

    def grad_sync_time(self, param_bytes: float, replicas: int,
                       kind: str = "ici") -> float:
        """All-reduce of a parameter's gradient across `replicas`
        data-parallel parts (reference: replica regions gathered into the
        optimizer task, optimizer_kernel.cu:98-104; here a psum ring)."""
        if replicas <= 1:
            return 0.0
        moved = 2.0 * param_bytes * (replicas - 1) / replicas
        return moved / self.axis_bw(kind)

    # ---- measured calibration ------------------------------------------
    # in-graph repetitions per measurement: on a tunneled PJRT device the
    # residual dispatch jitter is ~ms, so per-op resolution needs a long
    # in-graph loop to amortize below the op times being measured
    _REPEATS = 128

    def _dispatch_overhead(self) -> float:
        """One-time estimate of per-dispatch wall overhead (a tunneled /
        remote PJRT device costs milliseconds per execute call — that is
        harness overhead, not kernel time, and must be subtracted)."""
        key = ("dispatch_overhead",)
        if key in self._cache:
            return self._cache[key]
        import time

        import jax
        f = jax.jit(lambda x: x + 1.0)
        x = jnp.zeros((8,), jnp.float32)
        float(f(x)[0])
        # SAME pattern as _time_fn's timed runs — one dispatch + dependent
        # readback per sample — so the full round-trip latency (which on a
        # tunneled device is ~ms of RPC, not just enqueue cost) is what
        # gets subtracted
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(f(x)[0])
            times.append(time.perf_counter() - t0)
        dt = sorted(times)[2]
        self._cache[key] = dt
        return dt

    def _time_fn(self, make_out, params, xs, int_rows: int = 0) -> float:
        """Median-of-3 wall time of ONE application of `make_out`, measured
        as an in-graph lax.scan of N applications inside a single dispatch
        (the XLA analog of the reference's warmup-5/repeat-10 raw kernel
        loops, simulator.cu:25). The scan body perturbs a float input with
        the carry so XLA cannot hoist the op out of the loop. N adapts so
        the loop wall time dwarfs the per-dispatch overhead — on a
        tunneled PJRT device that overhead is milliseconds of RPC jitter,
        which would otherwise swamp sub-ms ops.

        `int_rows` > 0 rotates every integer input over [0, int_rows) by a
        per-iteration multiplicative hash: a sparse op re-gathering the
        SAME index set N times sees warm HBM row locality and measures
        well below its fresh-random-rows cost — the round-4 artifact's
        systematic −20…−32% DLRM-family under-prediction. Real steps see
        fresh indices every batch, so the measurement must too."""
        import math as _math
        import time

        import jax

        def loop_fn(n):
            def loop(p, xs_):
                def body(acc, it):
                    # a data dependence the compiler cannot remove, at
                    # negligible cost: float operands get +tiny·acc; int
                    # operands (embedding indices) rotate per-iteration
                    # (or get a data-dependent zero) — NEVER perturb
                    # params (adding eps to a multi-GB table would stream
                    # it every iteration and swamp the op being measured)
                    eps = (acc * 1e-38).astype(jnp.float32)
                    izero = jnp.where(acc > 3e38, 1, 0).astype(jnp.int32)
                    pxs, bumped = [], False
                    for x in xs_:
                        if int_rows > 0 and jnp.issubdtype(x.dtype,
                                                           jnp.integer):
                            # Knuth multiplicative rotation: uniform-ish
                            # fresh rows every iteration, same range
                            x = ((x.astype(jnp.uint32)
                                  + it.astype(jnp.uint32)
                                  * jnp.uint32(2654435761))
                                 % jnp.uint32(int_rows)).astype(x.dtype)
                            bumped = True
                            pxs.append(x)
                            continue
                        if not bumped and jnp.issubdtype(x.dtype,
                                                         jnp.floating):
                            x = x + eps.astype(x.dtype)
                            bumped = True
                        elif not bumped and jnp.issubdtype(x.dtype,
                                                           jnp.integer):
                            x = x + izero.astype(x.dtype)
                            bumped = True
                        pxs.append(x)
                    pp = p
                    if not bumped and p:
                        pp = dict(p)
                        k0 = next(iter(pp))
                        pp[k0] = pp[k0] + eps.astype(pp[k0].dtype)
                    out = make_out(pp, pxs)
                    # consume EVERY output leaf FULLY: reading one element
                    # would let XLA slice the computation down to just
                    # that element (conv/dot shrink to a sliver) and, for
                    # vjp outputs, drop whole cotangents — the op being
                    # measured must fully materialize
                    tot = jnp.zeros((), jnp.float32)
                    for leaf in jax.tree.leaves(out):
                        tot = tot + jnp.sum(leaf).astype(jnp.float32)
                    return acc + tot, None

                acc, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                      jnp.arange(n, dtype=jnp.int32))
                return acc
            return jax.jit(loop)

        def run(f):
            times = []
            for _ in range(3):
                t0 = time.perf_counter()
                float(f(params, xs))
                times.append(time.perf_counter() - t0)
            return sorted(times)[1]

        ovh = self._dispatch_overhead()
        n = self._REPEATS
        f = loop_fn(n)
        float(f(params, xs))  # compile + warmup
        dt = run(f)
        # grow the loop until it costs >= 20x the dispatch overhead (one
        # extra compile at most; scan length doesn't affect compile time)
        target = max(20.0 * ovh, 0.2)
        if dt < target:
            n2 = min(int(n * _math.ceil(target / max(dt, 1e-4))), 8192)
            if n2 > n:
                f = loop_fn(n2)
                float(f(params, xs))
                dt, n = run(f), n2
        return max((dt - ovh) / n, 1e-9)

    def measure_op(self, op: Op, pc: ParallelConfig,
                   backward: bool = False) -> float:
        """Time the op's compiled XLA computation for its shard shape on
        the real device (reference Op::measure_compute_time, e.g.
        linear.cu:973-1049: warmup 5 / repeat 10 — forward and backward
        are measured SEPARATELY there too). Backward is measured as
        (fwd+vjp) − fwd on the op subgraph. Memoized."""
        import jax

        key = ("measured", op.name, pc.degrees, pc.device_type,
               pc.memory_types, backward)
        if key in self._cache:
            return self._cache[key]
        # inputs and params are built at the per-device shapes the op
        # declares for this config (the two hooks stay mutually consistent
        # so apply() traces at the sharded shapes)
        shard_shapes = op.input_shard_shapes(pc)
        params = ({n: jnp.zeros(s, jnp.float32)
                   for n, s in op.param_shard_shapes(pc).items()}
                  if op.param_defs() else {})
        # mirror _forward_env: NHWC-opted-in ops see the producer's NHWC
        # physical form; everything else gets logical NCHW
        accepts_nhwc = getattr(op, "_accepts_nhwc_inputs", False)

        def _phys(s, t):
            if (accepts_nhwc and len(s) == 4
                    and getattr(t, "physical", None) == "nhwc"):
                return (s[0], s[2], s[3], s[1])
            return s
        # integer inputs are lookup indices: zeros would hit row 0 every
        # iteration and hide the random-HBM-row latency that dominates
        # sparse ops — fill them with seeded uniform rows over the table
        # range instead (reference measures with the app's real batches)
        import numpy as _np
        rows = int(getattr(op, "num_entries", 0))
        rng = _np.random.RandomState(0)

        def _fill(s, t):
            if rows > 0 and jnp.issubdtype(jnp.dtype(t.dtype), jnp.integer):
                return jnp.asarray(rng.randint(0, rows, size=s),
                                   dtype=t.dtype)
            return jnp.zeros(_phys(s, t), t.dtype)
        xs = [_fill(s, t) for s, t in zip(shard_shapes, op.inputs)]
        try:
            t_fwd = self._time_fn(
                lambda p, xs_: op.apply(p, xs_, training=False), params, xs,
                int_rows=rows)
            if not backward:
                dt = t_fwd
            else:
                def fwdbwd(p, xs_):
                    y, vjp = jax.vjp(
                        lambda p2, x2: op.apply(p2, x2, training=True),
                        p, xs_)
                    return vjp(jax.tree.map(jnp.ones_like, y))
                t_both = self._time_fn(fwdbwd, params, xs, int_rows=rows)
                # floor at the analytical fwd/bwd ratio's spirit: vjp can't
                # be cheaper than re-running forward
                dt = max(t_both - t_fwd, 0.5 * t_fwd)
        except Exception as e:
            # degrade loudly: a silent fallback would let --measure-ops
            # quietly become the roofline it was meant to replace
            dt = self._roofline_time(op, pc, backward)
            log_sim.warning(
                "measure_op(%s, %s, backward=%s) failed (%r); "
                "using roofline %.3es",
                op.name, pc.degrees, backward, e, dt)
        self._cache[key] = dt
        return dt
