"""TPU cost model for the strategy-search simulator.

Parity with the reference device model (reference: include/simulator.h:29-129,
src/runtime/simulator.cu:21-76 — per-GPU compute devices plus comm devices
with fixed bandwidths: inter-GPU 20 MB/ms, inter-node 12/numNodes, GPU⇄DRAM
16, simulator.cu:27-29; per-op times measured by running the real kernels,
memoized by (op, config) hash, simulator.cc:235-273).

TPU redesign: per-op compute time is a roofline estimate —
max(FLOPs / MXU_rate, bytes_touched / HBM_bw) — optionally *calibrated* by
timing the op's compiled XLA subgraph on the real chip (cost_model
measure=True), which replaces the reference's cudaEvent microbenchmarks.
XLA fuses ops, so isolated-op timing over-counts; the analytical model is
the default and measured times refine it (SURVEY.md §7 hard-part #3).
Comm time uses ICI/DCN bandwidths instead of the reference's constants.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax.numpy as jnp

from ..core.op import InputOp, Op
from ..parallel.pconfig import ParallelConfig
from ..utils.logging import log_sim


@dataclass
class TPUSpec:
    """Per-chip hardware model. Defaults are TPU v5e (public numbers:
    197 bf16 TFLOP/s MXU, 819 GB/s HBM, 4 ICI links × ~50 GB/s per
    direction; DCN ~ 25 GB/s per host)."""

    name: str = "v5e"
    mxu_flops: float = 197e12         # bf16 FLOP/s
    mxu_flops_f32: float = 49e12      # fp32 FLOP/s
    hbm_bytes_per_s: float = 819e9
    ici_bytes_per_s: float = 45e9     # per link per direction
    ici_links: int = 4
    dcn_bytes_per_s: float = 25e9
    mxu_utilization: float = 0.55     # achievable fraction on real workloads
    hbm_utilization: float = 0.75
    kernel_launch_s: float = 2e-6     # per-HLO overhead (XLA fused ≈ small)
    hbm_capacity_bytes: float = 16e9  # v5e HBM per chip

    @staticmethod
    def v4() -> "TPUSpec":
        return TPUSpec(name="v4", mxu_flops=275e12, mxu_flops_f32=69e12,
                       hbm_bytes_per_s=1228e9, ici_bytes_per_s=50e9,
                       ici_links=6, hbm_capacity_bytes=32e9)

    @staticmethod
    def detect() -> "TPUSpec":
        """Pick the spec matching the attached accelerator (falls back to
        the v5e defaults off-TPU)."""
        try:
            import jax
            kind = jax.devices()[0].device_kind.lower()
        except Exception:
            return TPUSpec()
        if "v4" in kind:
            return TPUSpec.v4()
        if "v5p" in kind or "v5 p" in kind:
            return TPUSpec(name="v5p", mxu_flops=459e12, mxu_flops_f32=115e12,
                           hbm_bytes_per_s=2765e9, ici_bytes_per_s=100e9,
                           ici_links=6, hbm_capacity_bytes=95e9)
        if "v6" in kind:
            return TPUSpec(name="v6e", mxu_flops=918e12, mxu_flops_f32=230e12,
                           hbm_bytes_per_s=1640e9, ici_bytes_per_s=90e9,
                           ici_links=4, hbm_capacity_bytes=32e9)
        return TPUSpec()


class CostModel:
    """Per-op/per-config compute and comm times, memoized like the
    reference's hash-keyed measurements (simulator.cc:241-249)."""

    def __init__(self, spec: Optional[TPUSpec] = None,
                 compute_dtype=jnp.bfloat16, measure: bool = False):
        self.spec = spec or TPUSpec()
        self.compute_dtype = compute_dtype
        self.measure = measure
        self._cache: Dict[Tuple, float] = {}

    # ---- helpers --------------------------------------------------------
    def _flops_rate(self) -> float:
        rate = (self.spec.mxu_flops
                if jnp.dtype(self.compute_dtype) == jnp.dtype(jnp.bfloat16)
                else self.spec.mxu_flops_f32)
        return rate * self.spec.mxu_utilization

    def _hbm_rate(self) -> float:
        return self.spec.hbm_bytes_per_s * self.spec.hbm_utilization

    @staticmethod
    def _shard_elems(op: Op, pc: ParallelConfig) -> float:
        t = op.outputs[0]
        return math.prod(t.shape) / max(pc.num_parts, 1)

    # ---- per-op compute -------------------------------------------------
    def op_compute_time(self, op: Op, pc: ParallelConfig,
                        backward: bool = False) -> float:
        """Roofline time for one device's shard of `op` (seconds)."""
        key = (op.name, pc.degrees, backward)
        if key in self._cache:
            return self._cache[key]

        if self.measure:
            # calibrated mode: time the op's compiled subgraph on the real
            # device (reference Op::measure_compute_time); backward ≈ 2×
            # forward, the same ratio the analytical model assumes
            t = self.measure_op(op, pc) * (2.0 if backward else 1.0)
        else:
            t = self._roofline_time(op, pc, backward)
        self._cache[key] = t
        return t

    def _roofline_time(self, op: Op, pc: ParallelConfig,
                       backward: bool = False) -> float:
        batch = op.outputs[0].shape[0] if op.outputs[0].num_dims > 0 else 1
        flops = op.flops_per_sample() * batch / max(pc.num_parts, 1)
        # bytes: inputs read + outputs written (+ params read), sharded
        io_elems = sum(math.prod(t.shape) for t in op.inputs)
        io_elems += math.prod(op.outputs[0].shape)
        io_bytes = 4.0 * io_elems / max(pc.num_parts, 1)
        # params: bytes this shard actually streams per step (a sparse-
        # update embedding touches only its gathered rows, not the
        # multi-GB table)
        io_bytes += op.param_bytes_touched_per_step(max(pc.num_parts, 1))
        if backward:
            # bwd ≈ 2x fwd flops (dX and dW gemms), grads written
            flops *= 2.0
            io_bytes *= 2.0
        t = max(flops / self._flops_rate(), io_bytes / self._hbm_rate())
        return t + self.spec.kernel_launch_s

    # ---- comm -----------------------------------------------------------
    def _ici_allreduce_bw(self) -> float:
        # bidirectional ring over ICI: effective algorithm bandwidth
        return self.spec.ici_bytes_per_s * self.spec.ici_links

    def resharding_time(self, tensor_bytes: float, src_pc: ParallelConfig,
                        dst_pc: ParallelConfig) -> float:
        """Cost of moving a tensor from the producer's sharding to the
        consumer's (the reference gets this implicitly from Legion region
        intersections, simulator.cc:279-326; GSPMD emits collectives)."""
        if src_pc.degrees == dst_pc.degrees:
            return 0.0
        # approximate: every device re-reads its destination shard from
        # peers — an all-to-all of the full tensor over ICI
        moved = tensor_bytes * (1.0 - 1.0 / max(src_pc.num_parts,
                                                dst_pc.num_parts, 1))
        return moved / self._ici_allreduce_bw()

    def grad_sync_time(self, param_bytes: float, replicas: int) -> float:
        """All-reduce of a parameter's gradient across `replicas`
        data-parallel parts (reference: replica regions gathered into the
        optimizer task, optimizer_kernel.cu:98-104; here a psum ring)."""
        if replicas <= 1:
            return 0.0
        moved = 2.0 * param_bytes * (replicas - 1) / replicas
        return moved / self._ici_allreduce_bw()

    # ---- measured calibration ------------------------------------------
    def measure_op(self, op: Op, pc: ParallelConfig) -> float:
        """Time the op's compiled XLA computation for its shard shape on
        the real device (reference Op::measure_compute_time, e.g.
        linear.cu:973-1049: warmup 5 / repeat 10). Memoized."""
        import time

        import jax

        key = ("measured", op.name, pc.degrees)
        if key in self._cache:
            return self._cache[key]
        # inputs and params are built at the per-device shapes the op
        # declares for this config (the two hooks stay mutually consistent
        # so apply() traces at the sharded shapes)
        shard_shapes = op.input_shard_shapes(pc)
        params = ({n: jnp.zeros(s, jnp.float32)
                   for n, s in op.param_shard_shapes(pc).items()}
                  if op.param_defs() else {})
        xs = [jnp.zeros(s, t.dtype) for s, t in zip(shard_shapes, op.inputs)]
        fn = jax.jit(lambda p, xs_: op.apply(p, xs_, training=False))
        try:
            fn(params, xs)  # compile+warmup
            for _ in range(4):
                fn(params, xs)
            jax.block_until_ready(fn(params, xs))
            t0 = time.perf_counter()
            for _ in range(10):
                out = fn(params, xs)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / 10
        except Exception as e:
            # degrade loudly: a silent fallback would let --measure-ops
            # quietly become the roofline it was meant to replace
            dt = self._roofline_time(op, pc)
            log_sim.warning(
                "measure_op(%s, %s) failed (%r); using roofline %.3es",
                op.name, pc.degrees, e, dt)
        self._cache[key] = dt
        return dt
