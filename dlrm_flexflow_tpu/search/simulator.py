"""Event-driven execution simulator for strategy search.

Port of the reference simulation algorithm (reference:
src/runtime/simulator.cc:275-448 — build a task graph of fwd/bwd/comm/
update/barrier SimTasks, then event-driven priority-queue simulation over
compute and comm devices; weight sync modeled either overlapped with
compute or bulk-synchronous behind a barrier, simulator.cc:327-408).

The algorithm is pure logic (no CUDA) and ports directly; what changes is
the device graph. The reference gives each GPU its own comm devices and
prices inter-node hops separately (simulator.cu:21-76, 27-29:
GPU→DRAM→DRAM→GPU at 12/numNodes MB/ms). The TPU analog here:

- one SPMD compute stream per mesh device, and
- one comm channel PER MESH AXIS: a collective over an "ici" axis rides
  that torus dimension's links, a collective over the "dcn" (multi-slice)
  axis rides the data-center network at TPUSpec.dcn_bytes_per_s.
  Collectives on different axes use disjoint links and run concurrently;
  collectives contending for the same axis serialize on its channel —
  replacing round 1's single shared COMM_DEVICE, which serialized
  everything and priced DCN at ICI rates.

Degrees map to axes exactly as parallel.sharding.AxisAssigner does at
compile time (consume consecutive axes in order), so the simulator prices
the same collectives GSPMD will emit. Costs come from search/cost_model.py.
"""

from __future__ import annotations

import heapq
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..core.op import InputOp, Op
from ..parallel.pconfig import ParallelConfig, StrategyMap
from .cost_model import CostModel

COMM_DEVICE = -1  # flat-topology fallback channel (axis 0)
HOST_DEVICE = -1000  # host CPU/DRAM: ONE shared resource for all ZCM ops


def hbm_footprint_report(model, cost: CostModel, strategies: StrategyMap,
                         ndev: int) -> Dict[str, float]:
    """Per-op PEAK per-device HBM residency (bytes) a strategy implies:
    parameters at each op's sharded shapes, optimizer state slabs, dense
    gradients, and LIVE ACTIVATIONS (under reverse-mode autodiff every
    op output is live from its forward until its backward, at its
    sharded shape in compute dtype), plus model inputs under the
    "inputs" key. Host-resident tables (CPU/ZCM strategies) live in host
    RAM and don't count — the capability that lets DLRM-Terabyte run on
    few chips (reference dlrm_strategy_hetero.cc:28-49).

    Shared accounting: Simulator.fits_memory sums it for search
    feasibility; the static plan verifier (analysis/shardcheck.py)
    reports it per-op against an ``--hbm-gb`` cap."""
    opt = getattr(model, "optimizer", None)
    nslabs = len(opt.sparse_slab_names()) if opt is not None else 0
    report: Dict[str, float] = {}
    for op in model.ops:
        pc = strategies.get(op.name)
        if isinstance(op, InputOp):
            # batch inputs are device-resident for the whole step;
            # sharded along the sample dim under DP
            report["inputs"] = (report.get("inputs", 0.0)
                                + cost.tensor_bytes(op.outputs[0])
                                / max(ndev, 1))
            continue
        if pc is None:
            continue
        parts = max(pc.num_parts, 1)
        total = cost.tensor_bytes(op.outputs[0]) / parts
        if op.param_defs() and not cost._host_resident(op, pc):
            shapes = op.param_shard_shapes(pc, ndev)
            # stored params at their EFFECTIVE storage bytes: embedding
            # tables under an int8/fp8 policy hold quantized rows + one
            # fp32 scale per row (quant/policy.py — the ~4x HBM lever);
            # the master_weight fp32 master lives host-side beside the
            # optimizer state, not in HBM. Non-table params price at
            # their declared dtype (bf16 tables stop being billed 4 B)
            from ..quant.policy import param_storage_bytes
            param_bytes = param_storage_bytes(op, pc, shapes)
            # momentum/Adam keep param-shaped fp32 state slabs (lazy
            # sparse state is table-shaped too); a dense-updated param
            # also materializes a param-shaped fp32 gradient before its
            # update, while a touched-rows update's gradient is
            # negligible next to the table
            fp32_bytes = sum(math.prod(shape) * 4.0
                             for shape in shapes.values())
            dense_grad = (op.param_bytes_touched_per_step(parts)
                          >= op.param_bytes())
            total += param_bytes + fp32_bytes * (nslabs + (1.0 if
                                                 dense_grad else 0.0))
        report[op.name] = total
    return report


def _axis_kind(name: str) -> str:
    return "dcn" if str(name).startswith("dcn") else "ici"


@dataclass
class SimTask:
    """reference: SimTask in include/simulator.h:29-60."""

    run_time: float
    device: int
    name: str = ""
    ready_time: float = 0.0
    counter: int = 0                  # unresolved dependencies
    next_tasks: List["SimTask"] = field(default_factory=list)

    def add_next(self, t: "SimTask"):
        self.next_tasks.append(t)
        t.counter += 1


class Simulator:
    """Builds the per-iteration task graph for a model + strategy and
    simulates its makespan (reference Simulator::simulate_runtime).

    `topology` describes the simulated machine as [(axis_name, size), ...]
    in AxisAssigner order; axis names starting with "dcn" are priced at
    DCN bandwidth. Default: the model's mesh axes when the mesh matches
    the simulated device count, else one flat ICI axis.
    """

    def __init__(self, model, cost_model: Optional[CostModel] = None,
                 overlap_weight_sync: bool = True,
                 topology: Optional[Sequence[Tuple[str, int]]] = None):
        self.model = model
        self.cost = cost_model or CostModel(
            compute_dtype=model.config.jnp_compute_dtype)
        self.overlap_weight_sync = overlap_weight_sync
        self.topology = list(topology) if topology is not None else None

    def _effective_superstep(self) -> int:
        """The superstep K this model's fit() would actually run
        (FFModel.resolve_superstep handles "auto" and the host-resident-
        table K=1 fallback), so the simulated dispatch floor amortizes
        exactly like the runtime's. Models without the resolver (config
        stubs in older tests) price the legacy K=1 floor."""
        resolve = getattr(self.model, "resolve_superstep", None)
        if resolve is None:
            return 1
        try:
            return max(int(resolve()), 1)
        except Exception:
            return 1

    # ---- topology ----------------------------------------------------
    def _topo(self, ndev: int) -> List[Tuple[str, int]]:
        if self.topology is not None:
            return self.topology
        mesh = self.model.mesh
        if mesh is not None and mesh.size == ndev:
            return [(a, int(mesh.shape[a])) for a in mesh.axis_names]
        # offline target: the factorization make_mesh would build for
        # ndev, so per-dim axis assignment (and thus collective pricing)
        # matches what compile() on the target will do
        from ..parallel.mesh import structural_axis_sizes
        return [(f"f{i}", s)
                for i, s in enumerate(structural_axis_sizes(ndev))]

    @staticmethod
    def _assign(degrees: Sequence[int],
                topo: Sequence[Tuple[str, int]]
                ) -> Optional[List[Tuple[int, ...]]]:
        """Per-dim axis-index assignment — the SAME algorithm compile-time
        sharding uses (parallel.sharding.assign_indices), so the simulator
        prices exactly the collectives GSPMD will emit."""
        from ..parallel.sharding import assign_indices
        return assign_indices(degrees, [s for _, s in topo])

    @staticmethod
    def _channel(axis_idx: int) -> int:
        """Comm pseudo-device id for a mesh axis (compute devices are >=0)."""
        return -(axis_idx + 1)

    def _reshard_spec(self, src_pc: ParallelConfig, dst_pc: ParallelConfig,
                      topo) -> Optional[Tuple[str, int]]:
        """(kind, channel) the src→dst redistribution rides: the slowest
        axis whose per-dim assignment changes. None = layouts agree.
        Configs that differ on the PARAM (row-shard) axis ride the axes
        the larger row-shard degree occupies — an all-to-all of row
        blocks, NOT the flat-ICI COMM_DEVICE fallback."""
        pd_s = max(getattr(src_pc, "param_degree", 1), 1)
        pd_d = max(getattr(dst_pc, "param_degree", 1), 1)
        if src_pc.degrees == dst_pc.degrees and pd_s == pd_d:
            return None
        sa = self._assign(src_pc.degrees, topo)
        da = self._assign(dst_pc.degrees, topo)
        if sa is None or da is None:
            return ("ici", COMM_DEVICE)
        nd = max(len(sa), len(da))
        sa += [()] * (nd - len(sa))
        da += [()] * (nd - len(da))
        involved = set()
        for s, d in zip(sa, da):
            involved |= set(s) ^ set(d)
        if pd_s != pd_d:
            from ..parallel.sharding import param_axis_indices
            pidx = param_axis_indices(max(pd_s, pd_d),
                                      [s for _, s in topo])
            involved |= set(pidx or ())
        if not involved:
            return None
        dcn = [i for i in involved if _axis_kind(topo[i][0]) == "dcn"]
        idx = dcn[0] if dcn else min(involved)
        return (_axis_kind(topo[idx][0]), self._channel(idx))

    def build_task_graph(self, strategies: StrategyMap, ndev: int):
        topo = self._topo(ndev)
        ops = [op for op in self.model.ops if not isinstance(op, InputOp)]
        tasks: List[SimTask] = []
        fwd_of: Dict[str, List[SimTask]] = {}
        bwd_of: Dict[str, List[SimTask]] = {}

        def new_task(rt, dev, name):
            t = SimTask(run_time=rt, device=dev, name=name)
            tasks.append(t)
            return t

        def reshard_task(tensor, src_pc, dst_pc, name):
            spec = self._reshard_spec(src_pc, dst_pc, topo)
            if spec is None:
                return None
            kind, chan = spec
            bytes_ = self.cost.tensor_bytes(tensor)
            comm_t = self.cost.resharding_time(bytes_, src_pc, dst_pc,
                                               kind=kind)
            if comm_t <= 0:
                return None
            return new_task(comm_t, chan, name)

        def _a2a_axes(pd):
            """[(axis_idx, kind, size)] the pd-way row shards occupy."""
            from ..parallel.sharding import param_axis_indices
            pidx = param_axis_indices(pd, [s for _, s in topo])
            return [(i, _axis_kind(topo[i][0]), topo[i][1])
                    for i in (pidx or ())]

        def _a2a_chain(parents, bytes_per_dev, pd, label, pc=None,
                       op=None, hide_under=None, tail=False):
            """Chain one exchange task per row axis after `parents`;
            returns the new frontier. The schedule shape depends on the
            strategy's overlap flag — THE semantics that let the MCMC
            walk discover pipelined plans unforced:

            - overlap OFF (the fused `jax.lax.all_to_all`): a blocking
              collective — every participating device sits in it, so
              the exchange occupies the COMPUTE stream and independent
              ops cannot run under it (one task per device, the
              serialized-exchange reality FLX514 flags);
            - overlap ON (the decomposed ppermute/chunked rounds): the
              bytes ride the axis CHANNEL. The rounds interleave with
              the op's OWN chunked compute — round r's ppermute DMA
              flies while round r+1's local gather runs — so the
              channel task starts with `hide_under` (the frontier the
              compute itself starts from) rather than after it, and
              downstream waits on max(compute, exchange). With `tail`
              (the gradient direction) the consumer is the per-chunk
              scatter update, which drains arrivals round by round: the
              channel task gates the makespan (every task end does) but
              not the update's start. The residual (1-efficiency)
              fraction plus the per-round decomposition overhead still
              blocks the compute stream (rounds cannot all leave the
              critical path, and the extra collective launches are
              real)."""
            from ..parallel.alltoall import _OVERLAP_CHUNKS
            overlap = bool(getattr(pc, "overlap", False)) \
                if pc is not None else False
            axes = _a2a_axes(pd)
            devs = (self._participants(pc, ndev, op)
                    if pc is not None else list(range(ndev)))
            for i, kind, size in axes:
                t_ax = self.cost.alltoall_time_axes(bytes_per_dev,
                                                    [(kind, size)])
                if t_ax <= 0:
                    continue
                if not overlap:
                    step = [new_task(t_ax, d, f"{label}[{topo[i][0]}]")
                            for d in devs]
                    for p in parents:
                        for s in step:
                            p.add_next(s)
                    parents = step
                    continue
                rounds = (size - 1) if len(axes) == 1 \
                    else _OVERLAP_CHUNKS
                resid = ((1.0 - self.cost.overlap_efficiency()) * t_ax
                         + self.cost.overlap_round_overhead(rounds))
                c = new_task(t_ax, self._channel(i),
                             f"{label}[{topo[i][0]}]")
                for p in (hide_under if hide_under is not None
                          else parents):
                    p.add_next(c)
                # downstream waits on the compute frontier AND (unless
                # the consumer drains per-round) the channel
                frontier = list(parents) if hide_under is not None \
                    else []
                if not tail:
                    frontier.append(c)
                elif hide_under is None:
                    frontier += list(parents)
                if resid > 0:
                    step = [new_task(resid, d,
                                     f"{label}_resid[{topo[i][0]}]")
                            for d in devs]
                    for p in parents:
                        for s in step:
                            p.add_next(s)
                    frontier += step
                parents = frontier or [c]
                hide_under = None
            return parents

        # forward tasks per op per participating device
        itemsize = jnp.dtype(self.cost.compute_dtype).itemsize
        for op in ops:
            pc = strategies[op.name]
            ct = self.cost.op_compute_time(op, pc, backward=False)
            fwd_of[op.name] = [new_task(ct, d, f"fwd:{op.name}")
                               for d in self._participants(pc, ndev, op)]
            # row-sharded embedding lookups: explicit all-to-alls ride
            # the row axes' channels — request ids to the owning shards
            # before the local gather, embedded rows back after it. The
            # skew-aware policies shrink the routed bytes (dedup /
            # hot/cold hybrid — _a2a_payload_bytes prices the expected
            # routed count from the observed id histogram); dedup also
            # pays its sort/unique machinery as a compute task, which
            # is what makes it LOSE on uniform ids.
            pd = max(getattr(pc, "param_degree", 1), 1)
            if pd > 1 and hasattr(op, "alltoall_payload_bytes"):
                req_b, rows_b, _ = op.alltoall_payload_bytes(
                    ndev, itemsize, pc=pc)
                pre: List[SimTask] = []
                if getattr(pc, "exchange", "dense") == "dedup":
                    t_sort = self.cost.dedup_overhead_time(op, ndev)
                    if t_sort > 0:
                        pre = [new_task(t_sort, d, f"dedup:{op.name}")
                               for d in self._participants(pc, ndev,
                                                           op)]
                req = _a2a_chain(pre, req_b, pd, f"a2a_idx:{op.name}",
                                 pc=pc, op=op)
                for r in req:
                    for ft in fwd_of[op.name]:
                        r.add_next(ft)
                # pipelined plans ship the first rounds' rows while the
                # later rounds still gather: the rows exchange starts
                # where the gather starts (the routed-ids frontier)
                fwd_of[op.name] = _a2a_chain(fwd_of[op.name], rows_b,
                                             pd, f"a2a_rows:{op.name}",
                                             pc=pc, op=op,
                                             hide_under=req)
            # dependency + resharding comm from producers
            for src in op.inputs:
                if src.owner_op is None or isinstance(src.owner_op, InputOp):
                    continue
                src_pc = strategies[src.owner_op.name]
                c = reshard_task(src, src_pc, pc,
                                 f"reshard:{src.owner_op.name}->{op.name}")
                if c is not None:
                    for ft in fwd_of[src.owner_op.name]:
                        ft.add_next(c)
                    for ft in fwd_of[op.name]:
                        c.add_next(ft)
                else:
                    for sft in fwd_of[src.owner_op.name]:
                        for ft in fwd_of[op.name]:
                            sft.add_next(ft)

        # backward tasks (reverse order), mirroring fwd deps
        for op in reversed(ops):
            pc = strategies[op.name]
            ct = self.cost.op_compute_time(op, pc, backward=True)
            bwd_of[op.name] = [new_task(ct, d, f"bwd:{op.name}")
                               for d in self._participants(pc, ndev, op)]
            # bwd of op depends on bwd of its consumers (grad flow) and on
            # its own fwd
            for ft in fwd_of[op.name]:
                for bt in bwd_of[op.name]:
                    ft.add_next(bt)
        consumers: Dict[str, List[Op]] = {}
        for op in ops:
            for src in op.inputs:
                if src.owner_op and not isinstance(src.owner_op, InputOp):
                    consumers.setdefault(src.owner_op.name, []).append(op)
        for op in ops:
            for cons in consumers.get(op.name, []):
                c = reshard_task(op.outputs[0], strategies[cons.name],
                                 strategies[op.name],
                                 f"reshard_grad:{cons.name}->{op.name}")
                if c is not None:
                    for bt in bwd_of[cons.name]:
                        bt.add_next(c)
                    for bt in bwd_of[op.name]:
                        c.add_next(bt)
                else:
                    for cbt in bwd_of[cons.name]:
                        for bt in bwd_of[op.name]:
                            cbt.add_next(bt)

        # weight sync + update per parameter (reference simulator.cc:327-408)
        for op in ops:
            if not op.param_defs():
                continue
            pc = strategies[op.name]
            replicas = pc.degrees[0] if pc.degrees else 1
            # per-device parameter traffic: the op-declared shard shapes
            # (every TP-capable op overrides param_shard_shapes; a config
            # that replicates params — e.g. conv spatial splits — keeps
            # full shapes) or touched-rows sparse updates, whichever is
            # tighter. Params/grads sync in fp32.
            shard_bytes = sum(
                math.prod(shape) * 4.0
                for shape in op.param_shard_shapes(pc, ndev).values())
            touched = op.param_bytes_touched_per_step(max(pc.num_parts, 1))
            dev_bytes = min(shard_bytes, touched)
            # the DP all-reduce rides the axes assigned to the sample dim —
            # a hierarchical chain, one task per axis on that axis's
            # channel (phases over different axes of different ops overlap)
            asn = self._assign(pc.degrees, topo)
            parents: List[SimTask] = list(bwd_of[op.name])
            pd = max(getattr(pc, "param_degree", 1), 1)
            if pd > 1 and hasattr(op, "alltoall_payload_bytes"):
                # row-sharded table: gradient rows route to their owning
                # shard (all-to-all over the row axes) instead of a DP
                # all-reduce — optimizer state stays shard-local
                _, _, grad_b = op.alltoall_payload_bytes(ndev, itemsize,
                                                         pc=pc)
                # pipelined plans scatter each arriving round while the
                # next is in flight: the update drains the exchange
                # per-round instead of waiting for the full buffer
                parents = _a2a_chain(parents, grad_b, pd,
                                     f"a2a_grad:{op.name}", pc=pc,
                                     op=op, tail=True)
                # hybrid placement: the replicated hot head applies its
                # (small) update stream in lockstep from an all-gather —
                # the allreduce-style cost the simulator already prices
                # for replicated tables, but only over the hot hits
                hot_b = 0.0
                if (getattr(pc, "hot_fraction", 0.0) > 0
                        and hasattr(op, "_row_shard_geometry")):
                    from ..ops.embedding import hot_update_bytes
                    hot_b = hot_update_bytes(op, pc, ndev)
                if hot_b > 0:
                    for ax_i, (ax_name, size) in enumerate(topo):
                        if size <= 1:
                            continue
                        ph = self.cost.allreduce_time_axes(
                            float(hot_b), [(_axis_kind(ax_name), size)])
                        if ph <= 0:
                            continue
                        s = new_task(
                            ph, self._channel(ax_i),
                            f"hot_allgather[{ax_name}]:{op.name}")
                        for p in parents:
                            p.add_next(s)
                        parents = [s]
            elif replicas > 1:
                if asn is not None and asn[0]:
                    b = float(dev_bytes)
                    for ax in asn[0]:
                        kind, size = _axis_kind(topo[ax][0]), topo[ax][1]
                        ph = self.cost.allreduce_time_axes(b, [(kind, size)])
                        if ph <= 0:
                            continue
                        s = new_task(ph, self._channel(ax),
                                     f"allreduce[{topo[ax][0]}]:{op.name}")
                        for p in parents:
                            p.add_next(s)
                        parents = [s]
                        b /= size
                else:
                    sync_t = self.cost.grad_sync_time(dev_bytes, replicas)
                    if sync_t > 0:
                        s = new_task(sync_t, COMM_DEVICE,
                                     f"allreduce:{op.name}")
                        for p in parents:
                            p.add_next(s)
                        parents = [s]
            if self.cost._host_resident(op, pc):
                upd_compute = self.cost.host_update_time(op, pc)
            else:
                # the sparse scatter divides by how many shards the
                # TABLE actually splits into (param_shard_shapes:
                # row/table/width sharding), not by the output parts —
                # a REPLICATED table applies the full update set on
                # every replica (GSPMD gathers the updates), which is
                # what makes pure DP lose to row sharding at scale
                full_bytes = sum(
                    math.prod(d.shape) * 4.0
                    for d in op.param_defs().values())
                tshards = max(full_bytes / max(shard_bytes, 1.0), 1.0)
                upd_rows = op.update_random_hbm_rows(pc)
                hot_rows_dev = 0.0
                if (pd > 1 and upd_rows > 0
                        and hasattr(op, "_row_shard_geometry")
                        and (getattr(pc, "exchange", "dense") == "dedup"
                             or getattr(pc, "hot_fraction", 0.0) > 0)):
                    # skew-aware scatter: the routed update stream is
                    # pre-combined per (row, device), so each shard
                    # scatters its share of the ROUTED entries, not the
                    # raw lookups; every replica also applies the hot
                    # partials locally
                    from ..ops.embedding import (_lookup_count,
                                                 expected_hot_distinct,
                                                 expected_routed_lookups)
                    lookups = max(_lookup_count(op), 1.0)
                    acc = upd_rows / lookups
                    n_dev = lookups / max(ndev, 1)
                    upd_rows = acc * ndev * expected_routed_lookups(
                        op, pc, n_dev)
                    hot_rows_dev = acc * expected_hot_distinct(op, pc,
                                                               n_dev)
                upd_compute = max(
                    dev_bytes / self.cost._hbm_rate() * 3.0,  # r/w+momentum
                    # sparse touched-rows scatter is random-access
                    # latency bound (write-pipeline rate, slower than
                    # the gather's)
                    self.cost.scatter_rows_time(
                        upd_rows / tshards + hot_rows_dev))
            for d in self._participants(pc, ndev, op):
                u = new_task(upd_compute, d, f"update:{op.name}")
                for p in parents:
                    p.add_next(u)
        return tasks

    # ------------------------------------------------------------------
    def _participants(self, pc: ParallelConfig, ndev: int,
                      op: Optional[Op] = None) -> List[int]:
        """Devices an op's point tasks run on. The strategy's explicit
        `device_ids` are honored when present (reference builds each op's
        SimTasks on the devices its strategy names,
        simulator.cc:279-326 — what lets operator-placement strategies
        price correctly: ops on disjoint devices overlap). Fallback:
        devices 0..k-1. Host-RESIDENT ops run on the single shared host
        channel instead — host DRAM does not parallelize across tables
        (see CostModel.host_update_time)."""
        if op is not None and self.cost._host_resident(op, pc):
            return [HOST_DEVICE]
        k = min(pc.num_parts, ndev)
        ids = pc.device_ids
        if ids and len(ids) >= k:
            return [int(i) % ndev for i in ids[:k]]
        return list(range(k))

    def _clamp_strategies(self, strategies: StrategyMap,
                          ndev: int) -> StrategyMap:
        """Price what would actually EXECUTE: clamp each op's degrees to
        divide its output dims AND to the target mesh's factorizable
        degrees (the simulator twin of FFModel._effective_pc — both
        checks, or the search selects wins from degrees that silently
        execute as different ones). Without this, 8-way data parallelism
        over a batch of 4 simulates as an impossible 8x speedup. Ops with
        raw_degree_semantics (concatenated-rows embeddings) keep their
        raw degrees — their table dim is intent, not an output
        partitioning."""
        from ..parallel.mesh import structural_axis_sizes
        from ..parallel.sharding import (clamp_param_degree,
                                         feasible_degrees_for)
        if self.model.mesh is not None and self.model.mesh.size == ndev:
            from ..parallel.sharding import AxisAssigner
            asn = AxisAssigner(self.model.mesh)
            feas, axis_sizes = asn.feasible_degrees(), asn.axis_sizes
        else:
            axis_sizes = structural_axis_sizes(ndev)
            feas = feasible_degrees_for(axis_sizes)
        out = {}
        by_name = {op.name: op for op in self.model.ops}

        def _skew(pc, pd):
            """Skew/pipelining policies survive a clamp only while the
            exchange itself does (pd > 1) — a fully-replicated table
            has nothing to dedup, no cold tail to split, and no
            exchange to overlap."""
            if pd > 1:
                return (getattr(pc, "exchange", "dense"),
                        getattr(pc, "hot_fraction", 0.0),
                        bool(getattr(pc, "overlap", False)))
            return "dense", 0.0, False

        for name, pc in strategies.items():
            op = by_name.get(name)
            pd = clamp_param_degree(getattr(pc, "param_degree", 1),
                                    axis_sizes)
            exch, frac, ovl = _skew(pc, pd)
            if (op is None or not op.outputs
                    or getattr(op, "raw_degree_semantics", False)):
                if (pd != getattr(pc, "param_degree", 1)
                        or exch != getattr(pc, "exchange", "dense")
                        or frac != getattr(pc, "hot_fraction", 0.0)
                        or ovl != bool(getattr(pc, "overlap", False))):
                    pc = ParallelConfig(
                        pc.degrees, pc.device_type,
                        pc.device_ids, pc.memory_types,
                        param_degree=pd, exchange=exch,
                        hot_fraction=frac,
                        quant_dtype=getattr(pc, "quant_dtype", ""),
                        quant_update=getattr(pc, "quant_update", ""),
                        overlap=ovl)
                out[name] = pc
                continue
            shape = op.outputs[0].shape
            degs = list(pc.degrees)[:len(shape)]
            degs += [1] * (len(shape) - len(degs))
            changed = (pd != getattr(pc, "param_degree", 1)
                       or exch != getattr(pc, "exchange", "dense")
                       or frac != getattr(pc, "hot_fraction", 0.0)
                       or ovl != bool(getattr(pc, "overlap", False)))
            for i, d in enumerate(degs):
                d = min(d, shape[i])
                while d > 1 and (shape[i] % d != 0 or d not in feas):
                    d -= 1
                if d != degs[i]:
                    changed = True
                degs[i] = max(d, 1)
            out[name] = (ParallelConfig(
                             tuple(degs), pc.device_type,
                             pc.device_ids, pc.memory_types,
                             param_degree=pd, exchange=exch,
                             hot_fraction=frac,
                             quant_dtype=getattr(pc, "quant_dtype", ""),
                             quant_update=getattr(pc, "quant_update", ""),
                             overlap=ovl)
                         if changed else pc)
        return out

    def fits_memory(self, strategies: StrategyMap, ndev: int) -> bool:
        """Per-device residency must fit the chip's HBM with 10%
        headroom for temps and fragmentation. The reference allocates
        real FB scratch on-device and fails oversized configs
        (reference simulator.cu:84-90); the round-3 flat 25% headroom
        ignored activations entirely, so a b256 conv strategy whose
        forward residuals alone exceed HBM could be blessed by the
        search and OOM on the real chip. The accounting itself lives in
        :func:`hbm_footprint_report`, shared with the static plan
        verifier (analysis/shardcheck.py FLX503)."""
        total = sum(hbm_footprint_report(self.model, self.cost,
                                         strategies, ndev).values())
        return total <= 0.9 * self.cost.spec.hbm_capacity_bytes

    def simulate(self, strategies: StrategyMap,
                 ndev: Optional[int] = None,
                 use_native: bool = True) -> float:
        """Event-driven makespan (reference simulator.cc:410-447): pop the
        earliest-ready task whose device is free, run it, release deps.

        The event loop itself runs in the native C++ engine
        (native/ffsim.cc) when available — it sits inside the MCMC search
        hot loop, which is why the reference keeps it native too. The
        Python loop below is the reference semantics and the fallback.
        """
        if ndev is None:
            ndev = int(math.prod(
                [self.model.mesh.shape[a] for a in self.model.mesh.axis_names])
            ) if self.model.mesh else 1
        strategies = self._clamp_strategies(strategies, ndev)
        if not self.fits_memory(strategies, ndev):
            # infeasible placement: params exceed per-chip HBM (pure DP on
            # DLRM-Terabyte replicates ~96 GB of tables, ~6x its HBM); an
            # infinite makespan makes the MCMC reject it like the reference
            # rejects illegal configs
            return float("inf")
        tasks = self.build_task_graph(strategies, ndev)
        # per-step dispatch/epilogue floor (TPUSpec.per_step_overhead_s):
        # constant across strategies, so it never changes WHICH strategy
        # wins, but calibration against real step times needs it. Fused
        # supersteps (FFConfig.superstep) amortize the floor — K steps
        # share ONE dispatch — so the per-step price is overhead / K or
        # the simulator would stay wrong about every floor-bound
        # small-batch config the fusion exists for.
        overhead = self.cost.spec.per_step_overhead_amortized(
            self._effective_superstep())
        if use_native:
            ms = self._simulate_native(tasks)
            if ms is not None:
                return ms + overhead
        device_free: Dict[int, float] = {}
        ready: List = []
        seq = 0
        for t in tasks:
            if t.counter == 0:
                heapq.heappush(ready, (t.ready_time, seq, t))
                seq += 1
        makespan = 0.0
        done = 0
        while ready:
            rt, _, task = heapq.heappop(ready)
            start = max(rt, device_free.get(task.device, 0.0))
            end = start + task.run_time
            device_free[task.device] = end
            makespan = max(makespan, end)
            done += 1
            for nxt in task.next_tasks:
                nxt.counter -= 1
                nxt.ready_time = max(nxt.ready_time, end)
                if nxt.counter == 0:
                    heapq.heappush(ready, (nxt.ready_time, seq, nxt))
                    seq += 1
        if done != len(tasks):
            raise RuntimeError(
                f"simulation deadlock: {done}/{len(tasks)} tasks ran")
        return makespan + overhead

    def _simulate_native(self, tasks: List[SimTask]) -> Optional[float]:
        """Run the event loop in native/ffsim.cc. Returns None when the
        native library is unavailable (caller falls back to Python)."""
        from ..native import get_lib
        lib = get_lib()
        if lib is None:
            return None
        import ctypes

        import numpy as np
        n = len(tasks)
        index = {id(t): i for i, t in enumerate(tasks)}
        run_time = np.empty(n, dtype=np.float64)
        device = np.empty(n, dtype=np.int32)
        src_list: List[int] = []
        dst_list: List[int] = []
        for i, t in enumerate(tasks):
            run_time[i] = t.run_time
            device[i] = t.device
            for nxt in t.next_tasks:
                src_list.append(i)
                dst_list.append(index[id(nxt)])
        edge_src = np.asarray(src_list, dtype=np.int64)
        edge_dst = np.asarray(dst_list, dtype=np.int64)
        ms = lib.ffsim_makespan(
            n, run_time.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            device.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(edge_src),
            edge_src.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            edge_dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if ms < 0:
            raise RuntimeError("simulation deadlock (native engine)")
        return float(ms)
