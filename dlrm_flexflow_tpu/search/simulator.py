"""Event-driven execution simulator for strategy search.

Port of the reference simulation algorithm (reference:
src/runtime/simulator.cc:275-448 — build a task graph of fwd/bwd/comm/
update/barrier SimTasks, then event-driven priority-queue simulation over
compute and comm devices; weight sync modeled either overlapped with
compute or bulk-synchronous behind a barrier, simulator.cc:327-408).

The algorithm is pure logic (no CUDA) and ports directly; what changes is
the device graph: instead of per-GPU compute devices + DRAM hops, the
devices are (a) one SPMD compute stream per mesh device and (b) one shared
ICI collective channel (XLA overlaps async collectives with compute, which
the event-driven queue models naturally by putting comm tasks on the
channel device). Costs come from search/cost_model.py.
"""

from __future__ import annotations

import heapq
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..core.op import InputOp, Op
from ..parallel.pconfig import ParallelConfig, StrategyMap
from .cost_model import CostModel

COMM_DEVICE = -1  # the ICI channel pseudo-device


@dataclass
class SimTask:
    """reference: SimTask in include/simulator.h:29-60."""

    run_time: float
    device: int
    name: str = ""
    ready_time: float = 0.0
    counter: int = 0                  # unresolved dependencies
    next_tasks: List["SimTask"] = field(default_factory=list)

    def add_next(self, t: "SimTask"):
        self.next_tasks.append(t)
        t.counter += 1


class Simulator:
    """Builds the per-iteration task graph for a model + strategy and
    simulates its makespan (reference Simulator::simulate_runtime)."""

    def __init__(self, model, cost_model: Optional[CostModel] = None,
                 overlap_weight_sync: bool = True):
        self.model = model
        self.cost = cost_model or CostModel(
            compute_dtype=model.config.jnp_compute_dtype)
        self.overlap_weight_sync = overlap_weight_sync

    # ------------------------------------------------------------------
    def _participants(self, pc: ParallelConfig, ndev: int) -> List[int]:
        """SPMD: every op runs on all devices, but an op whose config uses
        fewer parts than devices leaves the rest idle for its duration —
        modeled by placing tasks only on the participating devices."""
        return list(range(min(pc.num_parts, ndev)))

    def build_task_graph(self, strategies: StrategyMap, ndev: int):
        ops = [op for op in self.model.ops if not isinstance(op, InputOp)]
        tasks: List[SimTask] = []
        fwd_of: Dict[str, List[SimTask]] = {}
        bwd_of: Dict[str, List[SimTask]] = {}

        def new_task(rt, dev, name):
            t = SimTask(run_time=rt, device=dev, name=name)
            tasks.append(t)
            return t

        # forward tasks per op per participating device
        for op in ops:
            pc = strategies[op.name]
            ct = self.cost.op_compute_time(op, pc, backward=False)
            fwd_of[op.name] = [new_task(ct, d, f"fwd:{op.name}")
                               for d in self._participants(pc, ndev)]
            # dependency + resharding comm from producers
            for src in op.inputs:
                if src.owner_op is None or isinstance(src.owner_op, InputOp):
                    continue
                src_pc = strategies[src.owner_op.name]
                bytes_ = math.prod(src.shape) * 4.0
                comm_t = self.cost.resharding_time(bytes_, src_pc, pc)
                if comm_t > 0:
                    c = new_task(comm_t, COMM_DEVICE,
                                 f"reshard:{src.owner_op.name}->{op.name}")
                    for ft in fwd_of[src.owner_op.name]:
                        ft.add_next(c)
                    for ft in fwd_of[op.name]:
                        c.add_next(ft)
                else:
                    for sft in fwd_of[src.owner_op.name]:
                        for ft in fwd_of[op.name]:
                            sft.add_next(ft)

        # backward tasks (reverse order), mirroring fwd deps
        for op in reversed(ops):
            pc = strategies[op.name]
            ct = self.cost.op_compute_time(op, pc, backward=True)
            bwd_of[op.name] = [new_task(ct, d, f"bwd:{op.name}")
                               for d in self._participants(pc, ndev)]
            # bwd of op depends on bwd of its consumers (grad flow) and on
            # its own fwd
            for ft in fwd_of[op.name]:
                for bt in bwd_of[op.name]:
                    ft.add_next(bt)
        consumers: Dict[str, List[Op]] = {}
        for op in ops:
            for src in op.inputs:
                if src.owner_op and not isinstance(src.owner_op, InputOp):
                    consumers.setdefault(src.owner_op.name, []).append(op)
        for op in ops:
            for cons in consumers.get(op.name, []):
                src_pc = strategies[cons.name]
                dst_pc = strategies[op.name]
                bytes_ = math.prod(op.outputs[0].shape) * 4.0
                comm_t = self.cost.resharding_time(bytes_, src_pc, dst_pc)
                if comm_t > 0:
                    c = SimTask(run_time=comm_t, device=COMM_DEVICE,
                                name=f"reshard_grad:{cons.name}->{op.name}")
                    tasks.append(c)
                    for bt in bwd_of[cons.name]:
                        bt.add_next(c)
                    for bt in bwd_of[op.name]:
                        c.add_next(bt)
                else:
                    for cbt in bwd_of[cons.name]:
                        for bt in bwd_of[op.name]:
                            cbt.add_next(bt)

        # weight sync + update per parameter (reference simulator.cc:327-408)
        for op in ops:
            if not op.param_defs():
                continue
            pc = strategies[op.name]
            replicas = pc.degrees[0] if pc.degrees else 1
            # per-device bytes: dense params are sharded over the
            # non-sample degrees; sparse-update embeddings stream only
            # their touched rows (min() picks whichever applies)
            # per-device parameter traffic: the op-declared shard shapes
            # (every TP-capable op overrides param_shard_shapes; a config
            # that replicates params — e.g. conv spatial splits — keeps
            # full shapes) or touched-rows sparse updates, whichever is
            # tighter
            shard_bytes = sum(
                math.prod(shape) * 4.0
                for shape in op.param_shard_shapes(pc, ndev).values())
            touched = op.param_bytes_touched_per_step(max(pc.num_parts, 1))
            dev_bytes = min(shard_bytes, touched)
            sync_t = self.cost.grad_sync_time(dev_bytes, replicas)
            upd_compute = dev_bytes / self.cost._hbm_rate() * 3.0  # r/w+mom
            if sync_t > 0:
                s = SimTask(run_time=sync_t, device=COMM_DEVICE,
                            name=f"allreduce:{op.name}")
                tasks.append(s)
                for bt in bwd_of[op.name]:
                    bt.add_next(s)
                parents = [s]
            else:
                parents = bwd_of[op.name]
            for d in self._participants(pc, ndev):
                u = SimTask(run_time=upd_compute, device=d,
                            name=f"update:{op.name}")
                tasks.append(u)
                for p in parents:
                    p.add_next(u)
        return tasks

    # ------------------------------------------------------------------
    def fits_memory(self, strategies: StrategyMap, ndev: int) -> bool:
        """Per-device parameter bytes (at each op's sharded shapes) must
        fit the chip's HBM, with 25% headroom for activations/temps."""
        total = 0.0
        for op in self.model.ops:
            if isinstance(op, InputOp) or not op.param_defs():
                continue
            pc = strategies.get(op.name)
            if pc is None:
                continue
            for shape in op.param_shard_shapes(pc, ndev).values():
                total += math.prod(shape) * 4.0
        return total <= 0.75 * self.cost.spec.hbm_capacity_bytes

    def simulate(self, strategies: StrategyMap,
                 ndev: Optional[int] = None,
                 use_native: bool = True) -> float:
        """Event-driven makespan (reference simulator.cc:410-447): pop the
        earliest-ready task whose device is free, run it, release deps.

        The event loop itself runs in the native C++ engine
        (native/ffsim.cc) when available — it sits inside the MCMC search
        hot loop, which is why the reference keeps it native too. The
        Python loop below is the reference semantics and the fallback.
        """
        if ndev is None:
            import numpy as np
            ndev = int(math.prod(
                [self.model.mesh.shape[a] for a in self.model.mesh.axis_names])
            ) if self.model.mesh else 1
        if not self.fits_memory(strategies, ndev):
            # infeasible placement: params exceed per-chip HBM (pure DP on
            # DLRM-Terabyte replicates ~96 GB of tables, ~6x its HBM); an
            # infinite makespan makes the MCMC reject it like the reference
            # rejects illegal configs
            return float("inf")
        tasks = self.build_task_graph(strategies, ndev)
        if use_native:
            ms = self._simulate_native(tasks)
            if ms is not None:
                return ms
        device_free: Dict[int, float] = {}
        ready: List = []
        seq = 0
        for t in tasks:
            if t.counter == 0:
                heapq.heappush(ready, (t.ready_time, seq, t))
                seq += 1
        makespan = 0.0
        done = 0
        while ready:
            rt, _, task = heapq.heappop(ready)
            start = max(rt, device_free.get(task.device, 0.0))
            end = start + task.run_time
            device_free[task.device] = end
            makespan = max(makespan, end)
            done += 1
            for nxt in task.next_tasks:
                nxt.counter -= 1
                nxt.ready_time = max(nxt.ready_time, end)
                if nxt.counter == 0:
                    heapq.heappush(ready, (nxt.ready_time, seq, nxt))
                    seq += 1
        if done != len(tasks):
            raise RuntimeError(
                f"simulation deadlock: {done}/{len(tasks)} tasks ran")
        return makespan

    def _simulate_native(self, tasks: List[SimTask]) -> Optional[float]:
        """Run the event loop in native/ffsim.cc. Returns None when the
        native library is unavailable (caller falls back to Python)."""
        from ..native import get_lib
        lib = get_lib()
        if lib is None:
            return None
        import ctypes

        import numpy as np
        n = len(tasks)
        index = {id(t): i for i, t in enumerate(tasks)}
        run_time = np.empty(n, dtype=np.float64)
        device = np.empty(n, dtype=np.int32)
        src_list: List[int] = []
        dst_list: List[int] = []
        for i, t in enumerate(tasks):
            run_time[i] = t.run_time
            device[i] = t.device
            for nxt in t.next_tasks:
                src_list.append(i)
                dst_list.append(index[id(nxt)])
        edge_src = np.asarray(src_list, dtype=np.int64)
        edge_dst = np.asarray(dst_list, dtype=np.int64)
        ms = lib.ffsim_makespan(
            n, run_time.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            device.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(edge_src),
            edge_src.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            edge_dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if ms < 0:
            raise RuntimeError("simulation deadlock (native engine)")
        return float(ms)
