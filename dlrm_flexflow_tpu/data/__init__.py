from .dataloader import (FFBinDataLoader, SingleDataLoader, load_dlrm_hdf5,
                         write_ffbin)

__all__ = ["SingleDataLoader", "FFBinDataLoader", "write_ffbin",
           "load_dlrm_hdf5"]
