from .dataloader import (FFBinDataLoader, ImgDataLoader2D, ImgDataLoader4D,
                         SingleDataLoader, coalesce_batches, load_dlrm_hdf5,
                         pad_batch_rows, write_ffbin, write_img_ffbin)
from .prefetch import PrefetchPipeline
from .stream import ArrayStream

__all__ = ["SingleDataLoader", "FFBinDataLoader", "write_ffbin",
           "ImgDataLoader4D", "ImgDataLoader2D", "write_img_ffbin",
           "load_dlrm_hdf5", "PrefetchPipeline", "coalesce_batches",
           "pad_batch_rows", "ArrayStream"]
