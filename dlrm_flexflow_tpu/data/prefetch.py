"""Pipelined input staging: a depth-K prefetch ring fed by a background
staging thread.

The reference overlaps its data pipeline with device compute — Legion
DataLoader tasks stage batch N+1 into each GPU's framebuffer while the
device trains batch N (reference: examples/cpp/DLRM/dlrm.cc:486-589,
python/flexflow_dataloader.cc keeps the dataset zero-copy resident and
launches the per-batch scatters asynchronously). The TPU analog lives
here: a staging thread runs ``produce(i)`` for future step indices —
typically slice → ``jax.device_put`` against the model's input shardings
→ host-table gather — and parks the results in a bounded ring while the
consumer trains the current step.

Contracts (tests/test_prefetch.py pins all three):

- **Order**: items are delivered strictly in produce order
  (i = 0, 1, 2, ...), so a deterministic ``produce`` makes prefetched
  training bit-identical to calling it inline.
- **Errors**: transient ``IOError``/``OSError`` from ``produce`` are first
  absorbed by the shared :func:`~.dataloader.read_with_retries`
  backoff (same discipline as the ``.ffbin`` reader); anything that
  survives is re-raised at the consumer's next :meth:`get` — the step
  boundary — exactly like ``FFModel._host_drain`` surfaces async
  host-scatter failures. The error is sticky: the producer is dead, and
  the pipeline must be rebuilt.
- **Drain**: :meth:`close` stops the producer, discards staged items and
  joins the thread. Call it before anything that invalidates staged work
  (checkpoint restore, rollback, a reshuffle, loader state capture) and
  rebuild afterwards — re-producing dropped items is exact because
  ``produce`` is deterministic.

:meth:`stats` reports how much staging time was hidden under compute
(``overlap_fraction``), which benchmarks/bench_pipeline.py turns into the
gather/H2D overlap metric.

Fused supersteps (``FFConfig.superstep``) ride the same ring: ``fit()``'s
schedule emits one entry per K-step *megabatch* and ``produce`` stages the
K host batches as ONE stacked ``[K, batch, ...]`` device_put
(``FFModel._stage_superstep``), so a single ring slot — and a single H2D
transfer, extending the PR-2 single-put win — feeds K fused training
steps. :func:`stack_batches` is the host-side stacking helper for
non-contiguous batch lists (contiguous dataset slices reshape for free).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Callable, Optional

from ..obs import metrics as obsm
from ..obs import trace as obstrace
from ..utils.watchdog import StallReport, WorkerStalled

# global ordinal for thread naming: every staging thread in the process
# is distinguishable in a stack dump / stall report (ff-prefetch-0, ...)
_PIPE_SEQ = itertools.count()


def stack_batches(batches):
    """Stack a list of same-keyed host batches into one ``[K, ...]``
    megabatch dict (the input to ``FFModel._stage_superstep``). All
    batches must share keys, shapes, and dtypes — a ragged list cannot
    fuse into one scan and raises here rather than at trace time."""
    import numpy as np
    if not batches:
        raise ValueError("stack_batches needs at least one batch")
    keys = set(batches[0])
    for i, b in enumerate(batches[1:], 1):
        if set(b) != keys:
            raise ValueError(
                f"batch {i} keys {sorted(b)} differ from batch 0 keys "
                f"{sorted(keys)}; superstep batches must be homogeneous")
    out = {}
    for k in batches[0]:
        arrs = [np.asarray(b[k]) for b in batches]
        if any(a.shape != arrs[0].shape or a.dtype != arrs[0].dtype
               for a in arrs[1:]):
            raise ValueError(
                f"input {k!r} has ragged shapes/dtypes across batches; "
                f"superstep batches must be homogeneous")
        out[k] = np.stack(arrs)
    return out


class PrefetchPipeline:
    """Depth-K ring buffer fed by one background staging thread.

    produce    : callable(i) -> item, for i = 0, 1, 2, ...; runs on the
                 staging thread, so it must only do thread-safe work
                 (numpy slicing and jax device_puts are).
    depth      : ring capacity = how many items may be staged ahead.
    num_items  : total items to produce (None = unbounded); `get()` past
                 the end raises IndexError.
    io_site    : fault-injection/retry site name for the transient-error
                 backoff wrapped around every produce call.
    deadline_s : liveness deadline for the staging thread: `get()` that
                 waits longer than this raises
                 :class:`~..utils.watchdog.WorkerStalled` with a
                 structured stall report instead of hanging (0/None =
                 wait forever, the pre-watchdog behavior).
    """

    def __init__(self, produce: Callable[[int], object], depth: int = 2,
                 num_items: Optional[int] = None, name: str = "prefetch",
                 io_site: str = "prefetch", io_retries: int = 3,
                 io_backoff_s: float = 0.05,
                 deadline_s: Optional[float] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._produce = produce
        self._depth = int(depth)
        self._num = num_items
        self._io_site = io_site
        self._io_retries = io_retries
        self._io_backoff_s = io_backoff_s
        self._deadline_s = deadline_s if deadline_s else None
        self._buf: deque = deque()
        self._cond = threading.Condition()
        self._stopped = False
        self._exc: Optional[BaseException] = None
        self._produced = 0
        self._consumed = 0
        # staging-time accounting for the overlap metric
        self._produce_s = 0.0
        self._wait_s = 0.0
        self.name = name
        obsm.register_collector(self._obs_collect)
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"ff-prefetch-{next(_PIPE_SEQ)}")
        self._thread.start()

    def _obs_collect(self):
        """Registry collector: the ring's staging accounting as
        scrapeable samples (same numbers stats() reports)."""
        s = self.stats()
        lab = {"pipeline": self.name}
        yield "ff_prefetch_items_total", lab, s["items"]
        yield "ff_prefetch_produce_seconds_total", lab, s["produce_s"]
        yield "ff_prefetch_wait_seconds_total", lab, s["wait_s"]
        yield "ff_prefetch_overlap_fraction", lab, s["overlap_fraction"]
        yield "ff_prefetch_ring_depth", lab, len(self._buf)

    # --- producer side -------------------------------------------------
    def _run(self):
        from .dataloader import read_with_retries
        from ..utils import faults
        i = 0
        while True:
            with self._cond:
                while len(self._buf) >= self._depth and not self._stopped:
                    self._cond.wait()
                if self._stopped or (self._num is not None
                                     and i >= self._num):
                    return
            t0 = time.perf_counter()
            try:
                faults.maybe_stall("prefetch")   # simulated wedged stager
                # span lands on THIS (ff-prefetch-N) thread: staging
                # time shows as its own trace lane under the consumer's
                # train/step spans
                with obstrace.span("prefetch/produce",
                                   pipeline=self.name, item=i):
                    item = read_with_retries(lambda: self._produce(i),
                                             self._io_site,
                                             retries=self._io_retries,
                                             backoff_s=self._io_backoff_s)
            except BaseException as e:
                with self._cond:
                    self._exc = e
                    self._cond.notify_all()
                return
            dt = time.perf_counter() - t0
            with self._cond:
                if self._stopped:
                    return
                self._buf.append(item)
                self._produced += 1
                self._produce_s += dt
                self._cond.notify_all()
            i += 1

    # --- consumer side -------------------------------------------------
    def get(self):
        """Next staged item, in produce order. Blocks until staged.

        Raises the staging thread's error (sticky — rebuild the pipeline
        after), IndexError past `num_items`, or — when `deadline_s` is
        set — :class:`WorkerStalled` if the staging thread misses its
        liveness deadline (wedged device_put, stuck IO): the structured
        stall report names the thread and what was awaited, and the
        elastic layer recovers instead of the job hanging."""
        t0 = time.perf_counter()
        with self._cond:
            while not self._buf:
                if self._exc is not None:
                    raise self._exc
                if self._stopped:
                    raise RuntimeError("prefetch pipeline is closed")
                if self._num is not None and self._consumed >= self._num:
                    raise IndexError(
                        f"prefetch pipeline exhausted after {self._num} "
                        f"items")
                waited = time.perf_counter() - t0
                if (self._deadline_s is not None
                        and waited >= self._deadline_s):
                    raise WorkerStalled(StallReport(
                        worker=self._thread.name,
                        waiting_for=f"staged item {self._consumed}",
                        waited_s=waited, deadline_s=self._deadline_s,
                        detail=(f"pipeline {self.name!r}: produced "
                                f"{self._produced}, consumed "
                                f"{self._consumed}, depth {self._depth}"),
                        alive=self._thread.is_alive()))
                timeout = (None if self._deadline_s is None
                           else self._deadline_s - waited)
                self._cond.wait(timeout)
            item = self._buf.popleft()
            self._consumed += 1
            self._wait_s += time.perf_counter() - t0
            self._cond.notify_all()
        return item

    def close(self, join_timeout_s: float = 10.0):
        """Stop the producer, discard staged items, join the thread.
        Never raises — pending staging errors die with the pipeline
        (a caller closing is abandoning the staged stream anyway). The
        join is BOUNDED: a wedged staging thread is abandoned (it is a
        daemon, so interpreter shutdown and test teardown never hang on
        it) rather than waited on forever."""
        obsm.unregister_collector(self._obs_collect)
        with self._cond:
            self._stopped = True
            self._buf.clear()
            self._cond.notify_all()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=join_timeout_s)
            if self._thread.is_alive():
                from ..utils.logging import get_logger
                get_logger("prefetch").warning(
                    "staging thread %s did not exit within %.3gs of "
                    "close(); abandoning it (daemon)",
                    self._thread.name, join_timeout_s)

    @property
    def closed(self) -> bool:
        return self._stopped

    def stats(self) -> dict:
        """Staging accounting: `overlap_fraction` is the share of total
        staging time hidden under the consumer's compute (1.0 = the
        consumer never waited on the ring)."""
        with self._cond:
            ps, ws = self._produce_s, self._wait_s
            items = self._consumed
        hidden = max(ps - min(ws, ps), 0.0)
        return {"items": items,
                "produce_s": ps,
                "wait_s": ws,
                "overlap_fraction": (hidden / ps) if ps > 0 else 1.0}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
