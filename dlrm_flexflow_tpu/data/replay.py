"""Trace-driven replay: realistic traffic for the closed serve->train loop.

Production recsys traffic is not one zipf(alpha) forever — it has diurnal
QPS cycles, flash crowds, a hot set that churns, and a skew exponent that
drifts (the FAE/Monolith observation: the distribution you searched your
placement with is not the one you serve an hour later). This module
extends the ``zipf_indices`` machinery into a deterministic open-loop
load generator plus the feedback half of the loop:

- :class:`ReplaySpec` / :func:`scenario_spec` — a named, seeded traffic
  shape: base QPS, diurnal amplitude/period, a flash-crowd window
  (multiplies QPS), a time-varying zipf alpha (drifting skew), and a
  hot-set churn point (an id-space rotation: the same zipf head lands on
  DIFFERENT rows, which is exactly what invalidates a searched hot/cold
  placement without changing the marginal skew).
- :class:`TraceReplay` — ``request(i)`` materializes the i-th trace step
  as a feature batch, deterministic per (spec.seed, i): the same spec
  replays bit-identically to the serving fleet and to any offline
  consumer. ``labels(i)`` is the simulated user: click probability is a
  fixed function of the request's ids (hot rows click more), so the
  ground truth is stationary and learnable while the TRAFFIC drifts —
  AUC measures whether the model keeps up, not whether the world moved.
- :class:`FeedbackSpool` — the bounded join between serving and
  training: served batches land (with their click labels and scores)
  append-only, and ``source(i)`` replays them to ``fit_stream`` so the
  model trains on exactly what it served. Bounded: past ``capacity``
  un-consumed batches, new offers are DROPPED and counted (feedback lag
  is a judged budget, not an unbounded queue); ``faults.
  take_feedback_loss`` drops records before they land
  (``FF_FAULT_FEEDBACK_LOSS``). Landed batches are immutable, so a
  re-read of ``source(i)`` is deterministic — the ``fit_stream``
  contract.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from .dataloader import zipf_indices
from ..utils import faults
from ..utils.logging import get_logger

log_replay = get_logger("replay")

SCENARIOS = ("diurnal", "flash_crowd", "drifting_zipf")


@dataclass
class ReplaySpec:
    """One named traffic shape, fully determined by its fields + seed."""

    name: str = "diurnal"
    steps: int = 240             # trace length (the compressed 24 h)
    batch: int = 8               # rows per request batch
    base_qps: float = 64.0       # open-loop arrival rate at the trough
    alpha0: float = 0.9          # zipf exponent at t=0
    alpha1: Optional[float] = None   # exponent at t=end (None = flat)
    diurnal_amp: float = 0.0     # QPS swing, 0..1 (0 = flat day)
    diurnal_period: int = 0      # steps per day; 0 = no cycle
    flash_at: float = -1.0       # burst start, as a fraction of steps
    flash_len: float = 0.0       # burst length, fraction of steps
    flash_mult: float = 1.0      # QPS multiplier inside the burst
    churn_at: float = -1.0       # hot-set rotation point, fraction
    churn_stride: int = 0        # id-space rotation applied at churn
    seed: int = 0

    def __post_init__(self):
        if self.steps < 1:
            raise ValueError(f"replay needs >= 1 step, got {self.steps}")
        if self.batch < 1:
            raise ValueError(f"replay needs batch >= 1, got {self.batch}")

    def alpha_at(self, i: int) -> float:
        """Zipf exponent at trace step i (linear ramp alpha0->alpha1)."""
        if self.alpha1 is None or self.steps <= 1:
            return float(self.alpha0)
        f = min(max(i / (self.steps - 1), 0.0), 1.0)
        return float(self.alpha0 + f * (self.alpha1 - self.alpha0))

    def qps_at(self, i: int) -> float:
        """Arrival rate at trace step i: diurnal sinusoid x flash."""
        q = float(self.base_qps)
        if self.diurnal_amp > 0 and self.diurnal_period > 0:
            q *= 1.0 + self.diurnal_amp * 0.5 * (
                1.0 + math.sin(2.0 * math.pi * i / self.diurnal_period
                               - math.pi / 2.0))
        if self.in_flash(i):
            q *= float(self.flash_mult)
        return q

    def in_flash(self, i: int) -> bool:
        if self.flash_at < 0 or self.flash_len <= 0:
            return False
        lo = self.flash_at * self.steps
        return lo <= i < lo + self.flash_len * self.steps

    def churn_step(self) -> Optional[int]:
        """The trace step at which the hot set rotates (None = never)."""
        if self.churn_at < 0 or self.churn_stride == 0:
            return None
        return int(self.churn_at * self.steps)

    def interarrival_s(self, i: int) -> float:
        """Open-loop pacing: seconds until the next request batch."""
        return 1.0 / max(self.qps_at(i), 1e-9)


def scenario_spec(name: str, steps: int = 240, batch: int = 8,
                  seed: int = 0, rows: int = 64) -> ReplaySpec:
    """The three named scenarios the runner (and ROADMAP item 4) judge.

    - ``diurnal``: flat skew, QPS swings 3x over one compressed day.
    - ``flash_crowd``: a 10%-of-trace burst at 5x QPS mid-day.
    - ``drifting_zipf``: the placement-invalidating one — skew ramps
      0.6 -> 1.1 AND the hot set rotates halfway through (the searched
      histogram's head ids go cold; a new head appears mid-table).
    """
    if name == "diurnal":
        return ReplaySpec(name=name, steps=steps, batch=batch, seed=seed,
                          alpha0=0.9, diurnal_amp=2.0,
                          diurnal_period=steps)
    if name == "flash_crowd":
        return ReplaySpec(name=name, steps=steps, batch=batch, seed=seed,
                          alpha0=0.9, diurnal_amp=1.0,
                          diurnal_period=steps, flash_at=0.45,
                          flash_len=0.1, flash_mult=5.0)
    if name == "drifting_zipf":
        return ReplaySpec(name=name, steps=steps, batch=batch, seed=seed,
                          alpha0=0.6, alpha1=1.1, churn_at=0.5,
                          churn_stride=max(rows // 2, 1))
    raise ValueError(
        f"unknown scenario {name!r} — valid scenarios are "
        f"{', '.join(SCENARIOS)}")


class TraceReplay:
    """Deterministic request/label stream over one :class:`ReplaySpec`.

    ``tables`` embedding tables of ``rows`` rows each, ``bag`` lookups
    per table per sample, ``dense_dim`` dense features — the shapes a
    DLRM's ``build_dlrm`` inputs expect (``dense`` float32
    ``(batch, dense_dim)``, ``sparse`` int32 ``(batch, tables, bag)``).
    """

    # an id is "hot" for the CLICK model when its within-table row falls
    # below rows/HOT_DIV — a fixed property of the id space, NOT of the
    # traffic, so the label function stays stationary under churn/drift
    HOT_DIV = 8

    def __init__(self, tables: int, rows: int, bag: int, dense_dim: int,
                 spec: ReplaySpec):
        self.tables = int(tables)
        self.rows = int(rows)
        self.bag = int(bag)
        self.dense_dim = int(dense_dim)
        self.spec = spec
        self._hot_cut = max(self.rows // self.HOT_DIV, 1)

    def _rng(self, i: int, salt: int = 0) -> np.random.RandomState:
        return np.random.RandomState(
            (self.spec.seed * 1000003 + i * 9176 + salt) % (2 ** 31 - 1))

    def _hot_frac(self, sparse: np.ndarray) -> np.ndarray:
        """Per-sample fraction of lookups that hit the hot head."""
        hot = (sparse % self.rows) < self._hot_cut
        return hot.reshape(sparse.shape[0], -1).mean(axis=1)

    def request(self, i: int) -> Dict[str, np.ndarray]:
        """The i-th trace step's feature batch, deterministic per
        (seed, i). Post-churn, drawn ids rotate by ``churn_stride``: the
        zipf head (low ids) lands on different rows, moving the hot set
        without changing the marginal skew."""
        spec = self.spec
        rng = self._rng(i)
        alpha = spec.alpha_at(i)
        sparse = np.stack(
            [zipf_indices(rng, self.rows, (spec.batch, self.bag), alpha)
             for _ in range(self.tables)], axis=1)
        churn = spec.churn_step()
        if churn is not None and i >= churn:
            sparse = (sparse + spec.churn_stride) % self.rows
        sparse = sparse.astype(np.int32)
        dense = rng.rand(spec.batch, self.dense_dim).astype(np.float32)
        # the first dense column carries the same hotness signal the
        # click model uses (noisy), so the bottom MLP can learn fast in
        # short smoke runs while the embeddings learn the id mapping
        hf = self._hot_frac(sparse).astype(np.float32)
        dense[:, 0] = hf - 0.5 + 0.3 * dense[:, 0]
        return {"dense": dense, "sparse": sparse}

    def labels(self, i: int,
               features: Optional[Dict[str, np.ndarray]] = None
               ) -> np.ndarray:
        """Simulated clicks for the i-th request batch, ``(batch, 1)``
        float32 — Bernoulli with p a fixed sigmoid of the sample's
        hot-lookup fraction. Stationary ground truth: drift moves WHICH
        ids are drawn, never what an id is worth."""
        feats = features if features is not None else self.request(i)
        hf = self._hot_frac(np.asarray(feats["sparse"]))
        p = 1.0 / (1.0 + np.exp(-(6.0 * hf - 1.5)))
        draws = self._rng(i, salt=7).random_sample(p.shape)
        return (draws < p).astype(np.float32).reshape(-1, 1)


class FeedbackSpool:
    """Bounded append-only join of served batches + click feedback, the
    training side of the closed loop (see module docstring).

    ``offer()`` is called by the serving driver (features + labels +
    optionally the served scores/step, kept for judging); ``source(i)``
    is handed to ``fit_stream`` and blocks until batch i lands (None
    once the spool is closed and drained — the stream's end). ``lag()``
    is landed-but-unconsumed batches, the freshness debt the scenarios
    budget."""

    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError(f"spool needs capacity >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._cond = threading.Condition()
        self._batches: list = []          # immutable landed batches
        self._closed = False
        self._consumed = 0
        self.offered = 0
        self.dropped_faults = 0
        self.dropped_overflow = 0

    def offer(self, features: Dict[str, np.ndarray],
              labels: np.ndarray, scores: Optional[np.ndarray] = None,
              step: Optional[int] = None) -> bool:
        """Join one served batch with its feedback; True when it landed.
        Dropped (and counted) on fault injection or when the spool is
        at capacity — feedback beyond the bound is lost, not queued
        forever, so a stalled trainer shows up as lag + loss, never as
        unbounded memory."""
        if faults.take_feedback_loss():
            with self._cond:
                self.offered += 1
                self.dropped_faults += 1
            return False
        batch = dict(features)
        batch["label"] = np.asarray(labels, np.float32)
        if scores is not None:
            batch["_served_scores"] = np.asarray(scores)
        if step is not None:
            batch["_trace_step"] = int(step)
        with self._cond:
            self.offered += 1
            if self._closed:
                self.dropped_overflow += 1
                return False
            if len(self._batches) - self._consumed >= self.capacity:
                self.dropped_overflow += 1
                return False
            self._batches.append(batch)
            self._cond.notify_all()
        return True

    def source(self, i: int, timeout_s: float = 30.0):
        """``fit_stream`` source: the i-th landed batch (training keys
        only), blocking until it lands; None ends the stream once the
        spool is closed and drained (or nothing landed for
        ``timeout_s`` — a wedged serving side must not hang the trainer
        forever)."""
        import time as _time
        deadline = _time.monotonic() + timeout_s
        with self._cond:
            while len(self._batches) <= i:
                if self._closed:
                    return None
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    log_replay.warning(
                        "feedback spool: batch %d never landed within "
                        "%.0fs; ending the training stream", i,
                        timeout_s)
                    return None
                self._cond.wait(min(remaining, 0.1))
            batch = self._batches[i]
            self._consumed = max(self._consumed, i + 1)
        return {k: v for k, v in batch.items()
                if not k.startswith("_")}

    def served(self, i: int) -> Optional[Dict[str, Any]]:
        """The i-th landed batch WITH its judge-only keys (scores,
        trace step), or None — the scenario judge reads AUC from these."""
        with self._cond:
            if i >= len(self._batches):
                return None
            return self._batches[i]

    def lag(self) -> int:
        with self._cond:
            return len(self._batches) - self._consumed

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def stats(self) -> Dict[str, int]:
        with self._cond:
            return {"offered": self.offered,
                    "landed": len(self._batches),
                    "consumed": self._consumed,
                    "lag": len(self._batches) - self._consumed,
                    "dropped_faults": self.dropped_faults,
                    "dropped_overflow": self.dropped_overflow}
