"""Streaming data sources for the continual-learning loop.

``FFModel.fit_stream`` consumes a plain callable ``source(i) -> batch``
(a host feature dict including ``"label"``); this module provides the
common cases. Sources are DETERMINISTIC in ``i`` — the prefetch ring
may re-produce an index after a drain, and a resumed stream re-enters
at a recorded position, so ``source(i)`` must return the same batch
both times.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ArrayStream:
    """An endless (or ``max_steps``-bounded) batch stream over in-memory
    arrays: epoch-wise shuffled passes, reshuffled per epoch from a
    fixed seed — batch ``i`` is a pure function of ``(seed, i)``, so the
    stream is exactly resumable at any position.
    """

    def __init__(self, inputs: Dict[str, np.ndarray], labels: np.ndarray,
                 batch_size: int, shuffle: bool = True, seed: int = 0,
                 max_steps: Optional[int] = None):
        self.inputs = {k: np.asarray(v) for k, v in inputs.items()}
        self.labels = np.asarray(labels)
        self.batch_size = int(batch_size)
        n = len(self.labels)
        if n < self.batch_size:
            raise ValueError(
                f"dataset has {n} samples < batch size {self.batch_size}")
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.max_steps = max_steps
        self._per_epoch = n // self.batch_size
        self._n = n
        # one epoch's permutation is cached; i is monotone in practice
        self._perm_epoch = -1
        self._perm: Optional[np.ndarray] = None

    def _epoch_perm(self, epoch: int) -> np.ndarray:
        if epoch != self._perm_epoch:
            if self.shuffle:
                rng = np.random.RandomState(
                    (self.seed + epoch) % (2 ** 31))
                self._perm = rng.permutation(self._n)
            else:
                self._perm = np.arange(self._n)
            self._perm_epoch = epoch
        return self._perm

    def __call__(self, i: int) -> Optional[Dict[str, np.ndarray]]:
        if self.max_steps is not None and i >= self.max_steps:
            return None
        epoch, b = divmod(int(i), self._per_epoch)
        sel = self._epoch_perm(epoch)[b * self.batch_size:
                                      (b + 1) * self.batch_size]
        batch = {k: v[sel] for k, v in self.inputs.items()}
        batch["label"] = self.labels[sel]
        return batch
