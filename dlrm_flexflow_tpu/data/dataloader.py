"""Data loaders.

Parity with the reference loaders (reference: python/flexflow_dataloader.{h,
cc,cu} — ImgDataLoader4D/2D and SingleDataLoader keep the FULL dataset in
zero-copy pinned host memory and scatter one batch per step to each GPU's
framebuffer with dtype-templated GPU tasks; the DLRM app's loader does the
same from HDF5, examples/cpp/DLRM/dlrm.cc:266-589).

TPU redesign: the dataset stays in host RAM as numpy; `next_batch` stages
one batch to device HBM via `jax.device_put` with the input's GSPMD
sharding (each chip receives exactly its shard — the analog of the
ZC-memory -> per-part scatter). An optional background prefetch of the next
batch overlaps H2D with the device step, like the reference's async index
launches.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, Optional

import numpy as np

import jax


class SingleDataLoader:
    """Cycles a dict of full arrays in batches (reference SingleDataLoader:
    any 2-D/4-D tensor, full dataset resident, next_batch scatters)."""

    def __init__(self, model, inputs: Dict[str, np.ndarray],
                 labels: np.ndarray, batch_size: Optional[int] = None,
                 shuffle: bool = False, seed: int = 0,
                 prefetch: bool = True):
        self.model = model
        self.inputs = dict(inputs)
        self.labels = labels
        self.batch_size = batch_size or model.config.batch_size
        self.shuffle = shuffle
        self.rng = np.random.RandomState(seed)
        self.num_samples = len(labels)
        self.num_batches = self.num_samples // self.batch_size
        if self.num_batches == 0:
            raise ValueError(
                f"dataset ({self.num_samples}) smaller than one batch "
                f"({self.batch_size})")
        self._order = np.arange(self.num_samples)
        self._idx = 0
        self._prefetch = prefetch
        self._next: Optional[Dict] = None
        self._thread: Optional[threading.Thread] = None

    def reset(self):
        """reference: dataloader reset() task."""
        self._idx = 0
        self._join()
        self._next = None
        if self.shuffle:
            self.rng.shuffle(self._order)

    def _host_batch(self, b: int) -> Dict[str, np.ndarray]:
        sl = self._order[b * self.batch_size:(b + 1) * self.batch_size]
        batch = {k: v[sl] for k, v in self.inputs.items()}
        batch["label"] = self.labels[sl]
        return batch

    def _stage(self, b: int) -> Dict:
        return self.model._device_batch(self._host_batch(b))

    def _join(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def next_batch(self) -> Dict:
        """Device-resident batch dict (reference next_batch(ff):
        dlrm.cc:486-589). Wraps around at the end of the dataset."""
        b = self._idx % self.num_batches
        if b == 0 and self._idx > 0 and self.shuffle:
            self.rng.shuffle(self._order)
        self._idx += 1
        if not self._prefetch:
            return self._stage(b)
        self._join()
        cur = self._next if self._next is not None else self._stage(b)
        nxt_b = self._idx % self.num_batches

        def work():
            self._next = self._stage(nxt_b)

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()
        return cur

    def __iter__(self) -> Iterator[Dict]:
        self.reset()
        for _ in range(self.num_batches):
            yield self.next_batch()


def load_dlrm_hdf5(path: str):
    """DLRM Criteo HDF5 loader (reference dlrm.cc:266-382: datasets X_int
    (dense), X_cat (sparse indices), y (labels), probed for shapes then
    loaded whole into zero-copy memory)."""
    import h5py

    with h5py.File(path, "r") as f:
        x_int = np.asarray(f["X_int"], dtype=np.float32)
        x_cat = np.asarray(f["X_cat"], dtype=np.int32)
        y = np.asarray(f["y"], dtype=np.float32).reshape(-1, 1)
    # log-transform dense features like the reference preprocessing
    # (examples/cpp/DLRM/preprocess_hdf.py)
    x_int = np.log1p(np.maximum(x_int, 0.0))
    if x_cat.ndim == 2:
        x_cat = x_cat[:, :, None]  # (n, T) -> (n, T, bag=1)
    return {"dense": x_int, "sparse": x_cat}, y
